"""Benchmark entry: prints ONE JSON line with the headline metric.

Run by the driver on real TPU hardware at the end of each round:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: Llama pretraining tokens/sec/chip (the BASELINE.json north-star
metric); vs_baseline = achieved MFU / 0.40 target MFU (the reference
publishes no absolute numbers — BASELINE.md).

Model size auto-scales to the backend: a ~1B-param Llama on a real TPU chip,
a tiny config on CPU smoke runs.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.trainer import Trainer, device_peak_flops

    pt.seed(0)
    if on_tpu:
        # ~0.5B params — fits one v5e chip (16GB) in bf16 with adam fp32 state
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4608, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048, dtype="bfloat16")
        batch_size, seq_len, steps, warmup = 8, 2048, 10, 3
    else:
        cfg = LlamaConfig.tiny()
        batch_size, seq_len, steps, warmup = 4, 128, 6, 2

    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01, parameters=model)
    tr = Trainer(model, opt)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (batch_size, seq_len + 1))
    batch = {"input_ids": jnp.asarray(ids[:, :-1]),
             "labels": jnp.asarray(ids[:, 1:])}

    for _ in range(warmup):
        loss = tr.train_step(batch)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = tr.train_step(batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    tokens = batch_size * seq_len * steps
    tps_chip = tokens / dt / n_chips
    mfu = tps_chip * model.flops_per_token(seq_len) / device_peak_flops()

    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tps_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {
            "backend": backend,
            "device": getattr(jax.devices()[0], "device_kind", "unknown"),
            "n_chips": n_chips,
            "params": model.num_params(),
            "batch_size": batch_size,
            "seq_len": seq_len,
            "mfu": round(mfu, 4),
            "final_loss": float(loss),
        },
    }))


if __name__ == "__main__":
    main()
