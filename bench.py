"""Benchmark entry: prints ONE JSON line with the headline metric.

Run by the driver on real TPU hardware at the end of each round:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: Llama pretraining tokens/sec/chip (the BASELINE.json north-star
metric); vs_baseline = achieved MFU / 0.40 target MFU (the reference
publishes no absolute numbers — BASELINE.md).

Round-3 structure (per round-2 verdict):

- The measured loop trains THROUGH the input pipeline: an io.DataLoader
  (worker threads + device prefetch) feeds Trainer.train_step, and the
  time spent blocked on the loader is reported as input_stall_s — SURVEY
  §7 hard-part 7 ("input pipeline feeds the chip") is on the clock.
- Per-feature degradation: the run is attempted with the Pallas kernel
  path active; if the step fails (kernel lowering / driver drift), it is
  retried once with PT_DISABLE_PALLAS=1 so a kernel regression degrades
  the number instead of zeroing it (round-2 failure mode). The JSON
  records which path ran.
- Serving numbers ride along in "detail": compiled decode (generate_scan,
  dense KV cache) tokens/s and the paged-decode kernel microbench.
- TPU availability is probed in a SUBPROCESS under a timeout (the
  tunneled TPU plugin can hang inside backend init); every failure path
  still prints one parseable JSON line.
"""

import json
import os
import sys
import time
import traceback

from paddle_tpu.utils.hw_probe import probe_tpu


def _probe_tpu():
    return probe_tpu(cwd=os.path.dirname(os.path.abspath(__file__)))


def _emit(payload):
    print(json.dumps(payload), flush=True)


def _log(msg):
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


from paddle_tpu.utils.hw_probe import force_host_sync as _sync


def _make_loader(cfg, batch_size, seq_len, steps, extra_batches=4):
    """Synthetic LM batches through the real input pipeline (worker
    threads, collate, device prefetch)."""
    import numpy as np
    from paddle_tpu.io import DataLoader, Dataset

    class SyntheticLM(Dataset):
        def __len__(self):
            return batch_size * (steps + extra_batches)

        def __getitem__(self, i):
            rs = np.random.RandomState(i)
            ids = rs.randint(0, cfg.vocab_size, (seq_len + 1,), np.int32)
            return {"input_ids": ids[:-1], "labels": ids[1:]}

    return DataLoader(SyntheticLM(), batch_size=batch_size, num_workers=2,
                      prefetch_factor=4, prefetch_to_device=True,
                      drop_last=True)


def _train_bench(cfg, batch_size, seq_len, steps, warmup,
                 superstep_probe=False):
    """Returns (tokens_per_sec_total, step_time_s, input_stall_s, loss,
    model, fenced_per_step_times, superstep_detail, cost_attr).

    ``cost_attr`` is the cost observatory's analytical attribution of the
    HEADLINE step executable's optimized HLO (flops/bytes/comm bytes +
    roofline-predicted step seconds), or None when the executable can't
    render HLO — it prices the very program the timed loop ran."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.trainer import Trainer

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01, parameters=model)
    tr = Trainer(model, opt)

    # the superstep A/B leg consumes K(warm) + 2*n_ab extra batches
    loader = _make_loader(cfg, batch_size, seq_len, steps + warmup,
                          extra_batches=4 + (24 if superstep_probe else 0))
    it = iter(loader)

    loss = None
    _log("train: compiling + warmup")
    for _ in range(warmup):
        batch = next(it)
        loss = tr.train_step(batch)
    _sync(loss)
    _log("train: warmup done, timing")

    stall = 0.0
    t0 = time.perf_counter()
    for _ in range(steps):
        s0 = time.perf_counter()
        batch = next(it)
        stall += time.perf_counter() - s0
        loss = tr.train_step(batch)
    _sync(loss)
    dt = time.perf_counter() - t0
    _log("train: timed loop done")

    # a few FENCED steps for the auditable artifact: per-step wall times
    # with a host round-trip fence each (excluded from the headline, which
    # keeps the async-dispatch profile). Never let a transient failure
    # here discard the already-successful headline measurement.
    per_step = []
    try:
        for _ in range(3):
            batch = next(it)
            s0 = time.perf_counter()
            loss2 = tr.train_step(batch)
            _sync(loss2)
            per_step.append(round(time.perf_counter() - s0, 4))
    except Exception as e:
        _log(f"fenced-step loop failed (headline kept): {e}")

    # superstep A/B (ISSUE 2): per-step HOST dispatch overhead (wall time
    # spent enqueueing compiled programs, not waiting on them) with K=1 vs
    # K=4 over the same trainer — the amortization the superstep runtime
    # exists for. Never lets a probe failure touch the headline.
    superstep = {}
    if superstep_probe:
        try:
            K, n_ab = 4, 8
            _log("superstep: compiling K=4 scan")
            warm = [next(it) for _ in range(K)]
            tr.fit(iter(warm), steps=K, log_every=10 ** 9,
                   steps_per_dispatch=K)          # compile off the clock
            ab1 = [next(it) for _ in range(n_ab)]
            abk = [next(it) for _ in range(n_ab)]
            _log("superstep: timing K=1 vs K=4 dispatch overhead")
            tr.dispatch_stats = {"steps": 0, "dispatches": 0,
                                 "dispatch_host_s": 0.0}
            tr.fit(iter(ab1), steps=n_ab, log_every=10 ** 9)
            o1 = (tr.dispatch_stats["dispatch_host_s"]
                  / max(tr.dispatch_stats["steps"], 1))
            tr.dispatch_stats = {"steps": 0, "dispatches": 0,
                                 "dispatch_host_s": 0.0}
            tr.fit(iter(abk), steps=n_ab, log_every=10 ** 9,
                   steps_per_dispatch=K)
            ok = (tr.dispatch_stats["dispatch_host_s"]
                  / max(tr.dispatch_stats["steps"], 1))
            superstep = {
                "steps_per_dispatch": K,
                "dispatch_overhead_s_per_step_k1": round(o1, 7),
                f"dispatch_overhead_s_per_step_k{K}": round(ok, 7),
                # headline key = the superstep value (K>1 must beat k1)
                "dispatch_overhead_s_per_step": round(ok, 7),
            }
        except Exception as e:
            superstep = {"superstep_error":
                         f"{type(e).__name__}: {str(e)[:150]}"}

    # analytical attribution of the step executable that just ran (ISSUE
    # 9): ONE flop definition — the observability/costs analyzer over the
    # optimized HLO — shared with the live gauge and graph_lint's floor
    cost_attr = None
    try:
        from paddle_tpu.analysis.hlo import parse_hlo
        from paddle_tpu.observability import costs
        fn = next(iter(tr._step_exec.values()), None)
        if fn is not None and hasattr(fn, "as_text"):
            rep = costs.attribute_costs(parse_hlo(fn.as_text()))
            cost_attr = {"flops": rep.total_flops,
                         "bytes": rep.total_bytes,
                         "comm_bytes": rep.total_comm_bytes,
                         "predicted_s": rep.predicted_step_s,
                         "unmodeled_ops": sum(rep.unmodeled.values())}
    except Exception as e:
        _log(f"cost attribution failed (headline kept): {e}")

    tokens = batch_size * seq_len * steps
    return (tokens / dt, dt / steps, stall / steps, float(loss),
            model, per_step, superstep, cost_attr)


def _spawn_probe(strip_flags):
    """Run one overlap-probe child; returns its parsed JSON dict.
    The child is IDENTICAL code either way — the only difference is
    whether the overlap flag set is present in its XLA_FLAGS."""
    import subprocess

    from paddle_tpu.distributed.overlap import OVERLAP_XLA_FLAGS
    env = dict(os.environ)
    env["PT_BENCH_OVERLAP_PROBE"] = "1"
    env.pop("PT_DISABLE_PALLAS", None)     # ladder state must not leak
    if strip_flags:
        # the parent's apply_overlap_flags wrote the flags into XLA_FLAGS;
        # PT_NO_OVERLAP only stops the child ADDING them — strip them too,
        # or the "off" leg runs with overlap on
        env["PT_NO_OVERLAP"] = "1"
        toks = set(OVERLAP_XLA_FLAGS.split())
        env["XLA_FLAGS"] = " ".join(
            t for t in env.get("XLA_FLAGS", "").split() if t not in toks)
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__)], env=env,
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    if not lines:
        return {"step_time_s": None,
                "error": f"probe produced no JSON (rc={r.returncode}): "
                         f"{r.stderr[-300:]}"}
    return json.loads(lines[-1])


def _overlap_ab(on_tpu, degraded):
    """A/B the async-collective/latency-hiding XLA flag set (round-4
    verdict weak #7: the flags' value was vetted for safety but never
    measured). XLA_FLAGS bind at backend init, so BOTH legs run as fresh
    subprocesses executing identical probe code (bare train_step
    min-of-rounds, no input pipeline) — one inheriting the parent's
    overlap flags, one with them stripped; comparing the parent's
    loader-through mean against a bare child min would bias the delta.
    Skipped when the degradation ladder changed the parent's config.
    Caveat recorded in the artifact: the legs still run serially on a
    shared chip, so each reports its per-round spread — a delta smaller
    than the combined spread is noise, not signal."""
    out = {}
    if not on_tpu or degraded or os.environ.get("PT_BENCH_OVERLAP_PROBE") \
            or os.environ.get("PT_NO_OVERLAP"):
        return out
    try:
        _log("overlap A/B: spawning flags-off probe subprocess")
        p_off = _spawn_probe(strip_flags=True)
        _log("overlap A/B: spawning flags-on probe subprocess")
        p_on = _spawn_probe(strip_flags=False)
        off, on = p_off.get("step_time_s"), p_on.get("step_time_s")
        if off and on:
            out["overlap_off_step_time_s"] = off
            out["overlap_on_step_time_s"] = on
            out["overlap_spread_s"] = round(
                max(p_off.get("spread_s") or 0, p_on.get("spread_s") or 0),
                4)
            # >0: flags help (off leg slower)
            out["overlap_delta"] = round((off - on) / off, 4)
            # ISSUE 14 pinned ratio row: off ÷ on, >1.0 when the
            # latency-hiding flags actually buy step time
            out["overlap_on_step_speedup"] = round(off / on, 4)
        else:
            out["overlap_ab_error"] = (p_off.get("error")
                                       or p_on.get("error")
                                       or "no step time")[:300]
    except Exception as e:
        out["overlap_ab_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    return out


# the headline TPU training config — shared by _run and the overlap probe
# child so the A/B legs can never drift apart
_HEADLINE_TPU_CFG = dict(vocab_size=32000, hidden_size=1536,
                         intermediate_size=4608, num_hidden_layers=12,
                         num_attention_heads=12, num_key_value_heads=4,
                         max_position_embeddings=2048, dtype="bfloat16")


def _overlap_probe_main():
    """Child-process entry for the overlap A/B: headline config, min of 3
    rounds of 3 steps (amortized dispatch) + round spread. Prints
    {"step_time_s": ...} as its last line."""
    import numpy as np

    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.trainer import Trainer
    try:
        cfg = LlamaConfig(**_HEADLINE_TPU_CFG)
        pt.seed(0)
        model = LlamaForCausalLM(cfg)
        tr = Trainer(model, AdamW(learning_rate=1e-4, weight_decay=0.01,
                                  parameters=model))
        rs = np.random.RandomState(0)
        ids = rs.randint(0, cfg.vocab_size, (8, 2049), np.int32)
        batch = {"input_ids": jnp.asarray(ids[:, :-1]),
                 "labels": jnp.asarray(ids[:, 1:])}
        for _ in range(3):                    # compile + warm
            loss = tr.train_step(batch)
        _sync(loss)
        rounds = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(3):
                loss = tr.train_step(batch)
            _sync(loss)
            rounds.append((time.perf_counter() - t0) / 3)
        _emit({"step_time_s": round(min(rounds), 4),
               "spread_s": round(max(rounds) - min(rounds), 4),
               "overlap_flags": ("on" if "async_collective"
                                 in os.environ.get("XLA_FLAGS", "")
                                 else "off")})
    except Exception as e:
        _emit({"step_time_s": None,
               "error": f"{type(e).__name__}: {str(e)[:200]}"})


def _decode_bench(cfg, on_tpu):
    """Serving-path numbers (detail): compiled dense-cache decode via
    generate_scan, and the paged-decode kernel step time."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.inference.generation import (GenerationConfig,
                                                 generate_paged,
                                                 generate_scan)
    out = {}
    # shared serving-model setup in its OWN try: a failure here (e.g. OOM
    # building a second model next to the training one) must degrade to a
    # decode_error detail, never zero the already-measured training number
    try:
        # max_position 1152 covers the chunked-prefill leg's 896-token
        # long prompt + 32 new + page padding (a 512 table crashed that
        # leg: rope cos [512] broadcast against 896 positions)
        dcfg = LlamaConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_hidden_layers=cfg.num_hidden_layers,
            num_attention_heads=cfg.num_attention_heads,
            num_key_value_heads=cfg.num_key_value_heads,
            max_position_embeddings=1152, dtype=cfg.dtype) \
            if on_tpu else LlamaConfig.tiny()
        pt.seed(0)
        dmodel = LlamaForCausalLM(dcfg)
        B, prompt_len, new_tokens = (8, 128, 128) if on_tpu else (2, 8, 8)
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, dcfg.vocab_size, (B, prompt_len)))
        gc = GenerationConfig(max_new_tokens=new_tokens, do_sample=False)
    except Exception as e:
        out["decode_error"] = f"setup: {type(e).__name__}: {str(e)[:150]}"
        return out
    try:
        _log("decode: compiling generate_scan")
        toks = generate_scan(dmodel, ids, gc)          # compile
        _sync(toks)
        t0 = time.perf_counter()
        toks = generate_scan(dmodel, ids, gc)
        _sync(toks)
        dt = time.perf_counter() - t0
        _log("decode: generate_scan timed")
        out["decode_tokens_per_sec"] = round(B * new_tokens / dt, 1)
        out["decode_batch"] = B
        out["decode_new_tokens"] = new_tokens
    except Exception as e:
        out["decode_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # paged-KV serving path (vLLM-style): same decode through page
        # pools + the Pallas paged kernel on TPU
        _log("decode: compiling generate_paged")
        toks = generate_paged(dmodel, ids, gc, page_size=128 if on_tpu else 8)
        _sync(toks)
        t0 = time.perf_counter()
        toks = generate_paged(dmodel, ids, gc, page_size=128 if on_tpu else 8)
        _sync(toks)
        dt = time.perf_counter() - t0
        _log("decode: generate_paged timed")
        out["paged_decode_tokens_per_sec"] = round(B * new_tokens / dt, 1)
    except Exception as e:
        out["paged_generate_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # continuous-batching engine throughput: staggered prompts through
        # fewer slots than requests (admission + retirement + lazy paging
        # on the clock) — the serving-system layer over the paged kernel
        from paddle_tpu.inference import ContinuousBatchingEngine
        # decode_block: one compiled K-token scan per scheduler tick, so
        # the tunnel round trip is paid per-block, not per-token (the
        # raw kernel decode rate is decode_tokens_per_sec above). The
        # async engine's on-device stop detection keeps any K exact, and
        # its depth-2 dispatch window (inflight_depth below) hides the
        # host bookkeeping of block N under the device's block N+1.
        n_req, slots = (16, 4) if on_tpu else (4, 2)
        s_new = min(new_tokens, 64 if on_tpu else 24)
        s_block = 16 if on_tpu else 8
        eng = ContinuousBatchingEngine(
            dmodel, max_batch=slots, page_size=128 if on_tpu else 8,
            max_len=(prompt_len + new_tokens + 128) if on_tpu else 32,
            generation_config=GenerationConfig(max_new_tokens=s_new,
                                               do_sample=False),
            decode_block=s_block)
        rs = np.random.RandomState(1)
        stag = 8 if on_tpu else 2
        lens = [prompt_len - (i % 3) * stag for i in range(n_req)]
        reqs = [rs.randint(0, dcfg.vocab_size, (L,)).astype(np.int32)
                for L in lens]
        # every 3rd request SAMPLES (temp/top-k/top-p inside the compiled
        # block, round-4 verdict missing #2) — per-slot knob arrays, so
        # greedy and sampled share executables
        sample_gc = GenerationConfig(max_new_tokens=s_new, do_sample=True,
                                     temperature=0.8, top_k=40, top_p=0.95)

        def _submit_mix(eng, prompts):
            n_sampled = 0
            for i, r in enumerate(prompts):
                if i % 3 == 2:
                    eng.submit(r, generation_config=sample_gc)
                    n_sampled += 1
                else:
                    eng.submit(r)
            return n_sampled
        _log("decode: continuous-batching engine (warmup)")
        # warm the engine's compiled surfaces (one prefill per distinct
        # bucket + greedy AND sampling decode blocks) so the TIMED window
        # measures serving, not jit compiles — the steady-state number a
        # serving deployment sees. Warmup latencies are dropped from the
        # percentile stats.
        for L in sorted(set(lens)):        # greedy-only pass: (K, False)
            eng.submit(reqs[lens.index(L)][:L])
        eng.run()
        for L in sorted(set(lens)):        # sampled pass: (K, True)
            eng.submit(reqs[lens.index(L)][:L],
                       generation_config=sample_gc)
        eng.run()
        eng.reset_latency_stats()
        _log("decode: continuous-batching engine")
        n_sampled = _submit_mix(eng, reqs)
        pre0 = eng.preemptions
        t0 = time.perf_counter()
        results = eng.run()
        dt = time.perf_counter() - t0
        total = sum(len(v) for v in results.values())
        out["serving_tokens_per_sec"] = round(total / dt, 1)
        out["serving_requests"] = n_req
        out["serving_sampled_requests"] = n_sampled
        out["serving_slots"] = slots
        out["serving_decode_block"] = s_block
        out["inflight_depth"] = eng.async_depth
        # context-aware dense/paged dispatch (VERDICT item 4): which
        # attention path each decode block actually took
        out["serving_attn_dense_ticks"] = eng.attn_path_ticks["dense"]
        out["serving_attn_paged_ticks"] = eng.attn_path_ticks["paged"]
        out["serving_attn_crossover"] = eng.attn_crossover
        # how much of the raw paged-decode rate the serving layer keeps:
        # the host-overhead tax the async engine exists to eliminate
        if out.get("paged_decode_tokens_per_sec"):
            out["serving_decode_efficiency"] = round(
                out["serving_tokens_per_sec"]
                / out["paged_decode_tokens_per_sec"], 3)
        # per-window delta: eng.preemptions is a lifetime counter
        out["serving_preemptions"] = eng.preemptions - pre0
        lat = eng.latency_stats()
        if lat:
            out["serving_ttft_p50_s"] = round(lat["ttft_p50_s"], 4)
            out["serving_ttft_p99_s"] = round(lat["ttft_p99_s"], 4)
            out["serving_latency_p50_s"] = round(lat["latency_p50_s"], 4)
            out["serving_latency_p99_s"] = round(lat["latency_p99_s"], 4)

        # strict per-tick row (decode_block=1, CPU tier): like-for-like
        # with rounds <= 5, which timed the engine at K=1 — isolates the
        # async-loop win (device-resident state + pipelined dispatch)
        # from the larger decode block on-device stop detection enables
        if not on_tpu:
            eng1 = ContinuousBatchingEngine(
                dmodel, max_batch=slots, page_size=8, max_len=32,
                generation_config=GenerationConfig(max_new_tokens=s_new,
                                                   do_sample=False),
                decode_block=1)
            for L in sorted(set(lens)):
                eng1.submit(reqs[lens.index(L)][:L])
            eng1.run()
            for L in sorted(set(lens)):
                eng1.submit(reqs[lens.index(L)][:L],
                            generation_config=sample_gc)
            eng1.run()
            _submit_mix(eng1, reqs)
            t0 = time.perf_counter()
            results1 = eng1.run()
            dt1 = time.perf_counter() - t0
            out["serving_k1_tokens_per_sec"] = round(
                sum(len(v) for v in results1.values()) / dt1, 1)

        # 64-request mixed-length load ON the chip (round-4 weak #3: the
        # load test ran only on CPU). Same buckets + decode blocks as the
        # window above — zero extra compiles, this times scheduling +
        # paging + decode at queue depth 16x slots.
        if on_tpu:
            eng.reset_latency_stats()
            reqs64 = [rs.randint(0, dcfg.vocab_size,
                                 (lens[i % n_req],)).astype(np.int32)
                      for i in range(64)]
            _log("decode: 64-request load")
            n_sampled64 = _submit_mix(eng, reqs64)
            pre0 = eng.preemptions
            t0 = time.perf_counter()
            results = eng.run()
            dt = time.perf_counter() - t0
            total = sum(len(v) for v in results.values())
            lat = eng.latency_stats()
            out["serving_load64_tokens_per_sec"] = round(total / dt, 1)
            out["serving_load64_sampled"] = n_sampled64
            out["serving_load64_preemptions"] = eng.preemptions - pre0
            if lat:
                out["serving_load64_ttft_p99_s"] = round(
                    lat["ttft_p99_s"], 4)
                out["serving_load64_latency_p99_s"] = round(
                    lat["latency_p99_s"], 4)
    except Exception as e:
        out["serving_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # token-level speculative decoding (ISSUE 6): spec-on ÷ spec-off
        # A/B on a REPETITIVE-text workload (the n-gram prompt-lookup
        # drafter's target regime — quoting/templated/code-ish traffic).
        # Interleaved min-of-rounds, identical engines modulo the spec_k
        # knob, greedy (so both legs emit bit-identical streams and the
        # ratio is pure speed). Ratios, not absolute tok/s, are the
        # signal on this host (memory: bench-cpu-variance).
        from paddle_tpu.inference import ContinuousBatchingEngine
        sp_rs = np.random.RandomState(3)
        sp_len, sp_new, sp_k, sp_rounds = \
            (96, 48, 4, 3) if on_tpu else (64, 48, 4, 3)
        sp_page = 128 if on_tpu else 8
        # the workload: each prompt is the MODEL'S OWN greedy text (seed
        # + generate_scan continuation) — generation then continues the
        # pattern already present in the prompt, which is the regime
        # prompt-lookup drafting targets (quoting / templated /
        # input-grounded output). Random-token prompts would measure the
        # drafter's worst case, not the feature.
        sp_seeds = jnp.asarray(sp_rs.randint(0, dcfg.vocab_size, (4, 6)))
        sp_gc = GenerationConfig(max_new_tokens=sp_len - 6,
                                 do_sample=False)
        sp_prompts = np.asarray(
            generate_scan(dmodel, sp_seeds, sp_gc)).astype(np.int32)
        for nbatch, sfx in ((1, ""), (4, "_b4")):
            _log(f"decode: speculative A/B (batch {nbatch})")
            prompts = [sp_prompts[i] for i in range(nbatch)]
            legs, engines = {}, {}
            # two off legs: decode_block=1 (the default-config knob flip
            # the headline ratio measures) AND decode_block=spec_k+1
            # (same host-round-trip amortization as a spec tick, so the
            # _vs_block row isolates speculation's per-weight-pass win
            # from the block amortization decode_block already buys)
            for name, k, blk in (("off", 0, 1), ("offblk", 0, sp_k + 1),
                                 ("on", sp_k, 1)):
                eng = ContinuousBatchingEngine(
                    dmodel, max_batch=nbatch, page_size=sp_page,
                    max_len=sp_len + sp_new + sp_page,
                    generation_config=GenerationConfig(
                        max_new_tokens=sp_new, do_sample=False),
                    decode_block=blk, spec_k=k)
                for p in prompts:                  # warm the executables
                    eng.submit(p)
                legs[name] = {r: v.tolist() for r, v in eng.run().items()}
                engines[name] = eng
            assert (list(legs["on"].values())
                    == list(legs["off"].values())
                    == list(legs["offblk"].values())), \
                "spec-on stream diverged from spec-off"
            best = {name: float("inf") for name in engines}
            for _ in range(sp_rounds):
                for name, eng in engines.items():  # interleaved legs
                    for p in prompts:
                        eng.submit(p)
                    t0 = time.perf_counter()
                    res = eng.run()
                    dt = time.perf_counter() - t0
                    ntok = sum(len(v) for v in res.values())
                    best[name] = min(best[name], dt / max(ntok, 1))
            out[f"spec_decode_speedup{sfx}"] = round(
                best["off"] / best["on"], 3)
            out[f"spec_decode_speedup_vs_block{sfx}"] = round(
                best["offblk"] / best["on"], 3)
            out[f"spec_on_tokens_per_sec{sfx}"] = round(1 / best["on"], 1)
            out[f"spec_off_tokens_per_sec{sfx}"] = round(1 / best["off"], 1)
            out[f"spec_offblk_tokens_per_sec{sfx}"] = round(
                1 / best["offblk"], 1)
            sp = engines["on"].spec_stats()
            out[f"spec_accept_rate{sfx}"] = round(
                sp.get("spec_accept_rate", 0.0), 3)
            out[f"spec_mean_accepted_len{sfx}"] = round(
                sp.get("spec_mean_accepted_len", 1.0), 2)
        out["spec_k"] = sp_k
    except Exception as e:
        out["spec_decode_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # radix prefix-shared KV (ISSUE 7): N requests over a COMMON long
        # system prompt, prefix sharing ON vs OFF — identical engines
        # modulo the knob, streams asserted identical, interleaved
        # min-of-rounds, reported as RATIOS (memory: bench-cpu-variance).
        # The warmup run seeds the ON leg's radix tree (the steady state
        # for shared-prompt traffic), so the timed rounds measure
        # mapped-pages admission (COW + 1-token re-forward) against full
        # prefills; TTFT is the metric admission controls, so the
        # headline is mean-TTFT-off / mean-TTFT-on at p50.
        from paddle_tpu.inference import ContinuousBatchingEngine
        px_rs = np.random.RandomState(5)
        px_shared, px_tail, px_new = (512, 32, 16) if on_tpu \
            else (160, 8, 4)
        px_page = 128 if on_tpu else 8
        px_n, px_rounds = 8, 3
        shared_ids = px_rs.randint(0, dcfg.vocab_size,
                                   (px_shared,)).astype(np.int32)
        px_prompts = [
            np.concatenate([shared_ids,
                            px_rs.randint(0, dcfg.vocab_size,
                                          (px_tail,)).astype(np.int32)])
            for _ in range(px_n)]
        _log("decode: prefix-sharing A/B")
        px_engines, px_legs = {}, {}
        for name, knob in (("off", False), ("on", True)):
            eng = ContinuousBatchingEngine(
                dmodel, max_batch=px_n, page_size=px_page,
                max_len=px_shared + px_tail + px_new + px_page,
                generation_config=GenerationConfig(
                    max_new_tokens=px_new, do_sample=False),
                prefix_cache=knob)
            for p in px_prompts:       # warm executables (+ the tree)
                eng.submit(p)
            px_legs[name] = [v.tolist() for v in eng.run().values()]
            px_engines[name] = eng
        assert px_legs["on"] == px_legs["off"], \
            "prefix-on stream diverged from prefix-off"
        best = {name: float("inf") for name in px_engines}
        for _ in range(px_rounds):
            streams = {}
            for name, eng in px_engines.items():   # interleaved legs
                eng.reset_latency_stats()
                for p in px_prompts:
                    eng.submit(p)
                streams[name] = [v.tolist() for v in eng.run().values()]
                best[name] = min(best[name],
                                 eng.latency_stats()["ttft_p50_s"])
            # warm-tree rounds are all COW fast-path admits — the path
            # the timed window measures must stay parity-checked too
            assert streams["on"] == streams["off"], \
                "prefix fast-path stream diverged from prefix-off"
        out["prefix_reuse_ttft_speedup"] = round(
            best["off"] / best["on"], 3)
        out["prefix_ttft_off_p50_s"] = round(best["off"], 5)
        out["prefix_ttft_on_p50_s"] = round(best["on"], 5)
        pxs = px_engines["on"].prefix_stats()
        out["prefix_hit_rate"] = round(pxs.get("prefix_hit_rate", 0.0), 3)
        out["prefix_cow_copies"] = int(pxs.get("prefix_cow_copies", 0))
        out["prefix_shared_pages"] = int(
            pxs.get("prefix_shared_pages", 0))
        out["prefix_shared_prompt_tokens"] = px_shared
        del px_engines
    except Exception as e:
        out["prefix_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # chunked-prefill in its long-prompt regime (round-4 weak #3: it
        # was only measured at short prompts, where it costs throughput).
        # One long prompt + 8 short ones; chunked ON bounds the per-tick
        # stall the long prefill inflicts on the shorts' TTFT.
        if on_tpu:
            long_len, short_len, s_new2 = 896, 128, 32
            rs2 = np.random.RandomState(4)
            longp = rs2.randint(0, dcfg.vocab_size, (long_len,)) \
                .astype(np.int32)
            shorts = [rs2.randint(0, dcfg.vocab_size, (short_len,))
                      .astype(np.int32) for _ in range(8)]
            cp_res = {}
            for label, ck in (("chunked", True), ("unchunked", False)):
                eng2 = ContinuousBatchingEngine(
                    dmodel, max_batch=4, page_size=128,
                    max_len=long_len + s_new2 + 128,
                    generation_config=GenerationConfig(
                        max_new_tokens=s_new2, do_sample=False),
                    decode_block=8, chunked_prefill=ck,
                    prefill_chunk=128 if ck else None)
                # warm compiles (prefill buckets / chunk fn + decode)
                _log(f"decode: chunked-prefill A/B warmup ({label})")
                eng2.submit(longp)
                eng2.submit(shorts[0])
                eng2.run()
                eng2.reset_latency_stats()
                eng2.submit(longp)
                for r in shorts:
                    eng2.submit(r)
                t0 = time.perf_counter()
                res = eng2.run()
                dt = time.perf_counter() - t0
                lat = eng2.latency_stats()
                cp_res[label] = (sum(len(v) for v in res.values()) / dt,
                                 lat.get("ttft_p99_s", 0.0),
                                 lat.get("itl_p99_s", 0.0))
            out["chunked_prefill_long_tokens_per_sec"] = round(
                cp_res["chunked"][0], 1)
            out["unchunked_long_tokens_per_sec"] = round(
                cp_res["unchunked"][0], 1)
            out["chunked_prefill_long_ttft_p99_s"] = round(
                cp_res["chunked"][1], 4)
            out["unchunked_long_ttft_p99_s"] = round(
                cp_res["unchunked"][1], 4)
            # the fairness metric chunked prefill exists for: the worst
            # per-tick stall a RUNNING request sees while the long
            # prompt prefills
            out["chunked_prefill_long_itl_p99_s"] = round(
                cp_res["chunked"][2], 4)
            out["unchunked_long_itl_p99_s"] = round(
                cp_res["unchunked"][2], 4)
    except Exception as e:
        out["chunked_prefill_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # serving fabric (ISSUE 12): 2 in-process replicas under a mixed
        # two-tenant trace — 4 shared-prefix families (tenant "shared")
        # + cold long prompts (tenant "cold") — affinity vs round-robin,
        # interleaved min-of-rounds, RATIO rows (bench-variance policy).
        # The pool is sized so ONE replica cannot hold every family's
        # prefix: affinity partitions families across replicas and every
        # admit hits; round-robin scatters them and the trees thrash.
        from paddle_tpu.serving_fabric import (InProcTransport,
                                               ServingFabric,
                                               TenantFairPolicy,
                                               build_replicas)
        fb_page = 128 if on_tpu else 8
        # family prefixes sized so a MISS costs a real prefill (the PR 7
        # leg's scale: 160 shared tokens on cpu, 512 on tpu); TPU cold
        # prompts capped at 896 — the dcfg rope table (max_position
        # 1152) must cover prompt + new, same bound the chunked leg
        # lives with
        fb_fam_pages, fb_tail, fb_new = (4, 32, 16) if on_tpu \
            else (20, 4, 6)
        fb_cold_pages = 7 if on_tpu else 10
        n_fam, per_fam, n_cold, fb_rounds = 4, 3, 2, 3
        fb_rs = np.random.RandomState(6)
        fam_heads = [fb_rs.randint(0, dcfg.vocab_size,
                                   (fb_fam_pages * fb_page,))
                     .astype(np.int32) for _ in range(n_fam)]
        colds = [fb_rs.randint(0, dcfg.vocab_size,
                               (fb_cold_pages * fb_page,))
                 .astype(np.int32) for _ in range(n_cold)]

        # ONE fixed trace — shuffled so round-robin cannot accidentally
        # partition the families — reused by every leg and round: the
        # A/B compares routing policies, so both legs must see the same
        # prompts (and repeat rounds measure the steady state)
        fb_fixed_trace = []
        for j in range(per_fam):
            for h in fam_heads:
                fb_fixed_trace.append(("shared", np.concatenate(
                    [h, fb_rs.randint(0, dcfg.vocab_size, (fb_tail,))
                     .astype(np.int32)])))
        for c in colds:
            fb_fixed_trace.append(("cold", c))
        fb_order = np.random.RandomState(3).permutation(
            len(fb_fixed_trace))
        fb_fixed_trace = [fb_fixed_trace[i] for i in fb_order]

        def fb_trace():
            return fb_fixed_trace

        fb_max_len = (max(fb_fam_pages, fb_cold_pages) + 3) * fb_page
        # per-replica pool: HALF the families' prefixes + a working set
        # fit, all four do NOT — affinity partitions 2 families per
        # replica and keeps hitting, round-robin sprays all 4 onto both
        # and the trees thrash (the regime the router exists for)
        fb_pages = (n_fam // 2) * fb_fam_pages + (8 if on_tpu else 4)

        def fb_build(policy):
            reps = build_replicas(
                dmodel, 2, page_size=fb_page, max_len=fb_max_len,
                max_batch=8, num_pages=fb_pages,
                names=[f"{policy[:2]}0", f"{policy[:2]}1"],
                generation_config=GenerationConfig(
                    max_new_tokens=fb_new, do_sample=False))
            return ServingFabric(InProcTransport(reps), policy=policy,
                                 fair=TenantFairPolicy(),
                                 name=f"bench-{policy}")

        _log("decode: serving-fabric affinity-vs-round-robin A/B")
        legs = {p: fb_build(p) for p in ("affinity", "round-robin")}
        warm_streams = {}
        for p, fb in legs.items():
            # TWO warmup rounds: round 1 compiles the cold-prefill
            # buckets and seeds the trees, round 2 reaches the steady
            # eviction state whose suffix-prefill widths the timed
            # rounds reuse (a fresh width mid-round is a ~1s retrace
            # that would poison a TTFT percentile)
            for _ in range(2):
                fids = [fb.submit(pr, fb_new, tenant=tn)
                        for tn, pr in fb_trace()]
                res = fb.run()
            warm_streams[p] = [res[f].tolist() for f in fids]
        assert warm_streams["affinity"] == warm_streams["round-robin"], \
            "fabric streams diverged across routing policies"
        best_ttft = {p: float("inf") for p in legs}
        best_tps = {p: 0.0 for p in legs}
        for _ in range(fb_rounds):
            for p, fb in legs.items():   # interleaved legs
                fb.reset_latency_stats()
                fids = [fb.submit(pr, fb_new, tenant=tn)
                        for tn, pr in fb_trace()]
                t0 = time.perf_counter()
                res = fb.run()
                dt = time.perf_counter() - t0
                toks = sum(len(v) for v in res.values())
                best_tps[p] = max(best_tps[p], toks / dt)
                best_ttft[p] = min(
                    best_ttft[p], fb.latency_stats()["ttft_p50_s"])
        out["fabric_affinity_ttft_speedup"] = round(
            best_ttft["round-robin"] / best_ttft["affinity"], 3)
        out["fabric_goodput_ratio"] = round(
            best_tps["affinity"] / best_tps["round-robin"], 3)
        out["fabric_affinity_ttft_p50_s"] = round(
            best_ttft["affinity"], 5)
        out["fabric_rr_ttft_p50_s"] = round(
            best_ttft["round-robin"], 5)
        st = legs["affinity"].stats()
        out["fabric_affinity_hits"] = st["affinity_hits"]
        out["fabric_routed"] = st["routed"]
        for p, fb in legs.items():
            hr = [round(r.engine.prefix_hit_tokens
                        / max(r.engine._prefix_prompt_tokens, 1), 3)
                  for r in fb.transport._replicas.values()]
            out[f"fabric_{p.replace('-', '_')}_hit_rates"] = hr
        del legs

        # disaggregation A/B: same 3-replica capacity, mixed trace of
        # decode-heavy shorts + the cold long prompts; WITH a dedicated
        # prefill replica + handoff the decode replicas never run the
        # long cold prefill, so their ITL p99 holds — the ratio row is
        # disagg ÷ no-disagg p99 ITL (< 1 is the win, worse=higher)
        _log("decode: serving-fabric disaggregation A/B")
        shorts = [fb_rs.randint(0, dcfg.vocab_size, (fb_page - 2,))
                  .astype(np.int32) for _ in range(6)]

        def dg_build(disagg):
            reps = build_replicas(
                dmodel, 3,
                roles=(["prefill", "both", "both"] if disagg
                       else ["both"] * 3),
                page_size=fb_page, max_len=fb_max_len, max_batch=4,
                names=[f"dg{'a' if disagg else 'b'}{i}"
                       for i in range(3)],
                generation_config=GenerationConfig(
                    max_new_tokens=fb_new, do_sample=False))
            return ServingFabric(
                InProcTransport(reps), policy="least-loaded",
                disagg_threshold_tokens=(2 * fb_page if disagg
                                         else None),
                name=f"bench-{'disagg' if disagg else 'plain'}")

        def dg_run(fb):
            fids = [fb.submit(s, fb_new, tenant="short")
                    for s in shorts[:3]]
            fids += [fb.submit(c, fb_new, tenant="cold")
                     for c in colds]
            fids += [fb.submit(s, fb_new, tenant="short")
                     for s in shorts[3:]]
            res = fb.run()
            return [res[f].tolist() for f in fids]

        dg_legs = {lbl: dg_build(d) for lbl, d in (("disagg", True),
                                                   ("plain", False))}
        dg_warm = {lbl: dg_run(fb) for lbl, fb in dg_legs.items()}
        assert dg_warm["disagg"] == dg_warm["plain"], \
            "disaggregated streams diverged from plain fabric"
        dg_itl = {lbl: float("inf") for lbl in dg_legs}
        for _ in range(fb_rounds):
            for lbl, fb in dg_legs.items():
                fb.reset_latency_stats()
                dg_run(fb)
                dg_itl[lbl] = min(dg_itl[lbl],
                                  fb.latency_stats()["itl_p99_s"])
        out["fabric_p99_itl_with_disagg_ratio"] = round(
            dg_itl["disagg"] / dg_itl["plain"], 3)
        out["fabric_disagg_itl_p99_s"] = round(dg_itl["disagg"], 5)
        out["fabric_plain_itl_p99_s"] = round(dg_itl["plain"], 5)
        out["fabric_handoffs"] = dg_legs["disagg"].stats()["handoffs"]
        out["fabric_handoff_bytes"] = \
            dg_legs["disagg"].stats()["handoff_bytes"]
        del dg_legs
    except Exception as e:
        out["fabric_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # front-door robustness (ISSUE 16): two pinned ratio rows over
        # the full client → FrontDoor → fabric stack (legs live in
        # tools/load_test.py so the CI smoke and the bench share one
        # harness).
        # 1) goodput under 2x+ offered load, shed ladder on ÷ off: both
        #    legs share ONE calibrated deadline; shed-off admits deep
        #    queue positions, burns their prefill/partial decode, then
        #    the deadline cancels them — shed-on refuses them typed at
        #    admission and finishes what it admits (>1 = shedding wins).
        # 2) p99 TTFT with a replica HUNG mid-run, breaker budgets
        #    tight ÷ loose (8x): "off" is a loose budget, not none — an
        #    unbounded poll on a hung replica wedges the driver forever
        #    (<1 = the breaker converts the hang into a fast failover).
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import load_test as _lt
        _log("decode: front-door shed-on-vs-off goodput under overload")
        fd_on = _lt.overload_leg(dmodel, shed=True)
        fd_off = _lt.overload_leg(dmodel, shed=False,
                                  deadline_ms=fd_on["deadline_ms"])
        out["frontdoor_goodput_under_overload"] = round(
            fd_on["goodput_tps"] / max(fd_off["goodput_tps"], 1e-9), 3)
        out["frontdoor_shed_on_goodput_tps"] = round(
            fd_on["goodput_tps"], 1)
        out["frontdoor_shed_off_goodput_tps"] = round(
            fd_off["goodput_tps"], 1)
        out["frontdoor_shed_on_completed"] = fd_on["completed"]
        out["frontdoor_shed_off_completed"] = fd_off["completed"]
        _log("decode: front-door hung-replica breaker-vs-loose TTFT")
        fd_tight = _lt.hang_leg(dmodel, poll_budget_s=0.75)
        fd_loose = _lt.hang_leg(dmodel, poll_budget_s=6.0)
        out["frontdoor_p99_ttft_with_breaker_ratio"] = round(
            fd_tight["ttft_p99_s"] / max(fd_loose["ttft_p99_s"], 1e-9),
            3)
        out["frontdoor_breaker_ttft_p99_s"] = round(
            fd_tight["ttft_p99_s"], 4)
        out["frontdoor_nobreaker_ttft_p99_s"] = round(
            fd_loose["ttft_p99_s"], 4)
    except Exception as e:
        out["frontdoor_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # distributed request tracing (ISSUE 19): one fabric wave traced
        # ÷ untraced, interleaved min-of-rounds on the same warmed
        # replicas. Prices the span machinery (router queue/route/submit
        # + engine queue/resident/prefill/decode spans, per-request) —
        # healthy is ~1.0; a drift means a hot-path site stopped
        # honoring the attribute-load-plus-branch disabled contract.
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import load_test as _lt2
        _log("decode: request-tracing overhead (traced vs untraced wave)")
        tr_leg = _lt2.trace_overhead_legs(dmodel)
        out["trace_overhead_ratio"] = round(tr_leg["ratio"], 3)
        out["trace_traced_wall_s"] = round(tr_leg["wall_on_s"], 4)
        out["trace_untraced_wall_s"] = round(tr_leg["wall_off_s"], 4)
        out["trace_complete_traces"] = tr_leg["traces"]
    except Exception as e:
        out["trace_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # quantized serving A/B (ISSUE 17): int8 weights + int8 KV pages
        # vs the bf16 engine — identical engines modulo the quant knobs,
        # interleaved min-of-rounds, RATIO rows (memory:
        # bench-cpu-variance). The bf16 leg is re-checked against an
        # independent generate_scan stream so quant-knob bleed between
        # the A/B engines is caught, not averaged in.
        from paddle_tpu.inference import ContinuousBatchingEngine
        from paddle_tpu.quantization import quantize_model
        _log("decode: quantizing serving model (int8 weights + int8 KV)")
        qmodel = quantize_model(dmodel, kv_dtype="int8")
        qz_rs = np.random.RandomState(7)
        qz_page = 128 if on_tpu else 8
        # the A/B runs at EQUAL HBM budget — the deployment question
        # quantization answers is "what does this pool buy me", not
        # "what does a pool of unbounded pages buy me". Both engines
        # get the pages the SAME byte budget affords; the workload's
        # working set exceeds the bf16 allotment, so the bf16 leg pays
        # recompute-preemptions while the int8 leg stays resident.
        # (Unconstrained, the int8 leg LOSES on CPU — per-call dequant
        # with no HBM to save; TPU is the target regime.)
        # 4-page prompt + 3 pages of decode growth = 7 pages per slot;
        # the budget holds ~3 bf16 slots, so the bf16 leg both preempts
        # (prefill replay) and decodes NARROW — the int8 leg's pages
        # keep all 8 slots resident, and the per-tick cost of a decode
        # batch is nearly flat in width, so wider residency is the win
        qz_len, qz_new, qz_rounds = 4 * qz_page, 3 * qz_page, 3
        qz_n = 8
        qz_budget_pages = 22     # bf16 pages: ~3 resident 7-page slots

        def _qz_page_bytes(model):
            core = getattr(model, "model", model)
            sizes = []
            for np_ in (1, 2):
                pools, _ = core.alloc_paged_caches(1, np_ * qz_page,
                                                   qz_page)
                sizes.append(sum(a.size * a.dtype.itemsize
                                 for e in pools for a in e))
            return sizes[1] - sizes[0]

        qz_pb = {"bf16": _qz_page_bytes(dmodel),
                 "int8": _qz_page_bytes(qmodel)}
        qz_budget = qz_budget_pages * qz_pb["bf16"]
        qz_prompts = [qz_rs.randint(0, dcfg.vocab_size, (qz_len,))
                      .astype(np.int32) for _ in range(qz_n)]
        ref_gc = GenerationConfig(max_new_tokens=qz_new, do_sample=False)
        qz_ref = [np.asarray(generate_scan(
            dmodel, jnp.asarray(p)[None], ref_gc))[0, len(p):].tolist()
            for p in qz_prompts]
        qz_engines, qz_streams = {}, {}
        for name, mdl in (("bf16", dmodel), ("int8", qmodel)):
            eng = ContinuousBatchingEngine(
                mdl, max_batch=qz_n, page_size=qz_page,
                max_len=qz_len + qz_new + qz_page,
                num_pages=int(qz_budget // qz_pb[name]),
                generation_config=ref_gc)
            for p in qz_prompts:               # warm the executables
                eng.submit(p)
            qz_streams[name] = [v.tolist() for v in eng.run().values()]
            qz_engines[name] = eng
        # preemption replay is exact (recompute policy), so the budget
        # squeeze cannot change the bf16 stream — this assert holds
        # under thrash, and catches quant-knob bleed between the legs
        assert qz_streams["bf16"] == qz_ref, \
            "bf16 reference leg diverged from generate_scan (knob bleed)"
        # greedy agreement of the quantized streams vs the bf16
        # reference (free-running, so one near-tie flip cascades — the
        # pinned floor lives in the tests; here it's a tracked row)
        agree = [sum(a == b for a, b in zip(s, r)) / max(len(r), 1)
                 for s, r in zip(qz_streams["int8"], qz_ref)]
        out["quant_stream_agreement"] = round(sum(agree) / len(agree), 3)
        _log("decode: quantized A/B timed rounds")
        best = {name: float("inf") for name in qz_engines}
        preempt = {name: 0 for name in qz_engines}
        for _ in range(qz_rounds):
            for name, eng in qz_engines.items():   # interleaved legs
                for p in qz_prompts:
                    eng.submit(p)
                pre0 = eng.preemptions
                t0 = time.perf_counter()
                res = eng.run()
                dt = time.perf_counter() - t0
                preempt[name] += eng.preemptions - pre0
                ntok = sum(len(v) for v in res.values())
                best[name] = min(best[name], dt / max(ntok, 1))
        out["quant_decode_speedup"] = round(best["bf16"] / best["int8"],
                                            3)
        out["quant_int8_tokens_per_sec"] = round(1 / best["int8"], 1)
        out["quant_bf16_tokens_per_sec"] = round(1 / best["bf16"], 1)
        out["quant_bf16_preemptions"] = preempt["bf16"]
        out["quant_int8_preemptions"] = preempt["int8"]
        out["quant_budget_pages_bf16"] = int(qz_budget // qz_pb["bf16"])
        out["quant_budget_pages_int8"] = int(qz_budget // qz_pb["int8"])
        out["quant_kv_ticks"] = qz_engines["int8"].kv_quant_ticks
        # serving_decode_efficiency re-measured on the quantized leg:
        # int8 engine tok/s over the raw int8 paged-decode rate (same
        # definition as the bf16 row above)
        toks = generate_paged(qmodel, ids, gc, page_size=qz_page)
        _sync(toks)
        t0 = time.perf_counter()
        toks = generate_paged(qmodel, ids, gc, page_size=qz_page)
        _sync(toks)
        qraw = B * new_tokens / (time.perf_counter() - t0)
        out["quant_paged_decode_tokens_per_sec"] = round(qraw, 1)
        out["quant_serving_decode_efficiency"] = round(
            (1 / best["int8"]) / qraw, 3)
        del qz_engines
    except Exception as e:
        out["quant_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # KV capacity at EQUAL HBM budget (ISSUE 17): fix a byte budget,
        # give each pool dtype the pages that budget affords, then ramp
        # concurrent slots until the first recompute-preemption — the
        # ratio is the "~2x users per replica" claim, measured through
        # the engine's own allocator/preemption machinery rather than
        # arithmetic on dtype widths.
        from paddle_tpu.inference import ContinuousBatchingEngine
        from paddle_tpu.quantization import quantize_model
        qz_page = 128 if on_tpu else 8
        qmodel2 = quantize_model(dmodel, kv_dtype="int8")
        cap_rs = np.random.RandomState(8)

        def _page_bytes(model):
            core = getattr(model, "model", model)
            sizes = []
            for np_ in (1, 2):
                pools, _ = core.alloc_paged_caches(1, np_ * qz_page,
                                                   qz_page)
                sizes.append(sum(a.size * a.dtype.itemsize
                                 for e in pools for a in e))
            return sizes[1] - sizes[0]

        pb = {"bf16": _page_bytes(dmodel), "int8": _page_bytes(qmodel2)}
        out["quant_kv_page_bytes_ratio"] = round(
            pb["bf16"] / pb["int8"], 3)
        # budget = 13 bf16 pages: 1 reserved + 4 slots x 3 pages each
        # (2-page prompt + growth page); int8 affords ~2x the pages
        cap_budget = 13 * pb["bf16"]
        cap_prompt, cap_new, cap_max = 2 * qz_page, qz_page, 12
        cap_gc = GenerationConfig(max_new_tokens=cap_new,
                                  do_sample=False)
        cap_slots = {}
        _log("decode: quantized KV capacity ramp (equal HBM budget)")
        for name, mdl in (("bf16", dmodel), ("int8", qmodel2)):
            eng = ContinuousBatchingEngine(
                mdl, max_batch=cap_max, page_size=qz_page,
                max_len=cap_prompt + cap_new + qz_page,
                num_pages=int(cap_budget // pb[name]),
                generation_config=cap_gc)
            cap = 0
            for n in range(1, cap_max + 1):
                pre0 = eng.preemptions
                for _ in range(n):
                    eng.submit(cap_rs.randint(0, dcfg.vocab_size,
                                              (cap_prompt,))
                               .astype(np.int32))
                eng.run()
                if eng.preemptions - pre0:
                    break
                cap = n
            cap_slots[name] = cap
        out["quant_kv_capacity_ratio"] = round(
            cap_slots["int8"] / max(cap_slots["bf16"], 1), 3)
        out["quant_kv_slots_int8"] = cap_slots["int8"]
        out["quant_kv_slots_bf16"] = cap_slots["bf16"]
        out["quant_kv_budget_pages_bf16"] = int(cap_budget // pb["bf16"])
        out["quant_kv_budget_pages_int8"] = int(cap_budget // pb["int8"])
    except Exception as e:
        out["quant_capacity_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    def _amortized_ab_us(fa, fb, x0, length=20, rounds=6):
        """A/B kernel timing robust to a SHARED chip: each leg runs
        `length` applications chained in one compiled scan (per-call
        timing over the tunnel measures dispatch latency, not the
        kernel), the two legs' repeats are INTERLEAVED so both see the
        same contention profile (the chip has been observed 2-3x slower
        for whole seconds — un-interleaved legs flip the verdict run to
        run), and each leg reports its MIN round (discards spikes)."""
        def mk(f):
            lp = jax.jit(lambda a: jax.lax.scan(
                lambda c, _: (f(c), ()), a, None, length=length)[0])
            r = lp(x0)
            _sync(jax.tree.leaves(r)[0])
            return lp
        la, lb = mk(fa), mk(fb)
        best = [float("inf"), float("inf")]
        for _ in range(rounds):
            for i, lp in enumerate((la, lb)):
                t0 = time.perf_counter()
                r = lp(x0)
                _sync(jax.tree.leaves(r)[0])
                best[i] = min(best[i], time.perf_counter() - t0)
        return (best[0] / length * 1e6, best[1] / length * 1e6)

    try:
        # weight-only int8 linear: fused Pallas kernel vs XLA dequant
        # (reference: cutlass weight-only GEMM). Kernel called DIRECTLY —
        # production dispatch consults the tune DB's measured winner, so
        # weight_only_linear alone would A/B XLA against itself. TPU-only.
        if on_tpu:
            from paddle_tpu.nn.quantized_linear import weight_quantize
            from paddle_tpu.ops.pallas import int8_matmul as im
            # n_ == k_ REQUIRED: the A/B harness feeds each [m, n] output
            # back as the next [m, k] activation (scan carry)
            m_, k_, n_ = 512, 4096, 4096
            assert n_ == k_, "A/B scan chaining needs shape-preserving f"
            rs2 = np.random.RandomState(2)
            xw = jnp.asarray(rs2.normal(0, 1, (m_, k_)), jnp.bfloat16)
            w = jnp.asarray(rs2.normal(0, 0.05, (k_, n_)), jnp.float32)
            qw, sc = weight_quantize(w, algo="weight_only_int8")
            scf = jnp.asarray(sc, jnp.float32)
            wdq = (qw.astype(jnp.float32) * scf[:, None]).astype(jnp.bfloat16)
            p_us, x_us = _amortized_ab_us(
                lambda a: im.int8_matmul_pallas(a, qw, scf),
                lambda a: jax.lax.dot_general(
                    a, wdq, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32).astype(a.dtype),
                xw)
            out["int8_matmul_pallas_us"] = round(p_us, 1)
            out["int8_matmul_xla_us"] = round(x_us, 1)
    except Exception as e:
        out["int8_matmul_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # fused rope: Pallas q+k single-pass vs XLA elementwise fusion
        # (keep-only-if-it-wins: the ledger records both numbers)
        if on_tpu:
            from paddle_tpu.ops import rope as rope_ops
            from paddle_tpu.ops.pallas.fused_rope import fused_rope_pallas
            from paddle_tpu.ops.registry import pallas_disabled_scope
            b_, s_, h_, hk_, d_ = 8, 2048, 16, 4, 128
            rs3 = np.random.RandomState(3)
            q_ = jnp.asarray(rs3.normal(0, 1, (b_, s_, h_, d_)), jnp.bfloat16)
            k_ = jnp.asarray(rs3.normal(0, 1, (b_, s_, hk_, d_)), jnp.bfloat16)
            cos_, sin_ = rope_ops.rope_freqs(d_, s_)

            def _rope_xla(qk):
                with pallas_disabled_scope():
                    return rope_ops.apply_rotary_pos_emb(
                        qk[0], qk[1], cos_, sin_)
            p_us, x_us = _amortized_ab_us(
                lambda qk: fused_rope_pallas(qk[0], qk[1], cos_, sin_),
                _rope_xla, (q_, k_))
            out["rope_pallas_us"] = round(p_us, 1)
            out["rope_xla_us"] = round(x_us, 1)
    except Exception as e:
        out["rope_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    if on_tpu:
        try:
            # paged vs dense decode CROSSOVER over context length (round-4
            # weak #2: paged was only measured at ctx 2048, where dense
            # wins — the point of paged KV is long/ragged contexts). One
            # decode step, B=8 sequences, both paths attending the same
            # ctx; dense = the models' contiguous-cache einsum path.
            from paddle_tpu.ops.pallas.paged_attention import (
                paged_decode_attention)
            B, H, H_kv, D = 8, 8, 2, 128
            page = 128
            rs = np.random.RandomState(0)
            q = jnp.asarray(rs.normal(0, 1, (B, H, D)), jnp.bfloat16)

            def dense_step(q, kc, vc, lens):
                rep = H // H_kv
                kf = jnp.repeat(kc, rep, axis=2).astype(jnp.float32)
                vf = jnp.repeat(vc, rep, axis=2).astype(jnp.float32)
                lg = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                                kf) / np.sqrt(D)
                t_idx = jnp.arange(kc.shape[1])[None, None, :]
                lg = jnp.where(t_idx <= lens[:, None, None], lg, -jnp.inf)
                p = jax.nn.softmax(lg, axis=-1)
                return jnp.einsum("bht,bthd->bhd", p, vf)

            for per_seq in (16, 64, 128):
                ctx = page * per_seq
                npages = B * per_seq + 8
                kp = jnp.asarray(rs.normal(0, 1, (H_kv, npages, page, D)),
                                 jnp.bfloat16)
                vp = kp
                tables = jnp.asarray(
                    rs.permutation(npages)[:B * per_seq]
                    .reshape(B, per_seq).astype(np.int32))
                lens = jnp.full((B,), ctx - 2, jnp.int32)
                _log(f"decode: paged vs dense kernel, ctx={ctx}")
                fp = jax.jit(paged_decode_attention)
                r = fp(q, kp, vp, tables, lens)
                _sync(r)
                kc = jnp.asarray(rs.normal(0, 1, (B, ctx, H_kv, D)),
                                 jnp.bfloat16)
                vc = kc
                fd = jax.jit(dense_step)
                r2 = fd(q, kc, vc, lens)
                _sync(r2)
                n = 20
                best_p = best_d = float("inf")
                for _ in range(3):       # interleaved min-of-rounds
                    t0 = time.perf_counter()
                    for _ in range(n):
                        r = fp(q, kp, vp, tables, lens)
                    _sync(r)
                    best_p = min(best_p, (time.perf_counter() - t0) / n)
                    t0 = time.perf_counter()
                    for _ in range(n):
                        r2 = fd(q, kc, vc, lens)
                    _sync(r2)
                    best_d = min(best_d, (time.perf_counter() - t0) / n)
                out[f"paged_decode_us_ctx{ctx}"] = round(best_p * 1e6, 1)
                out[f"dense_decode_us_ctx{ctx}"] = round(best_d * 1e6, 1)
                del kp, vp, kc, vc
        except Exception as e:
            out["paged_decode_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # long-context leg: s=8192 training on the flash kernel — the
        # dense XLA attention path fails to COMPILE at this length on
        # v5e (tune-sweep evidence), so the leg is flash-kernel-only and
        # SKIPPED when the degradation ladder disabled Pallas. Runs LAST,
        # after the serving model is dropped, to free HBM first.
        # Round-5 A/B (temp/exp_longctx*.py): b=2 + NO recompute fits v5e
        # HBM and reads MFU 0.626 vs full-remat-b1's 0.49 — full remat was
        # costing the extra forward; the ladder below keeps b1/full as the
        # OOM fallback.
        if on_tpu and not os.environ.get("PT_DISABLE_PALLAS"):
            try:
                del dmodel
            except NameError:
                pass
            from paddle_tpu.models import LlamaConfig as _LC
            from paddle_tpu.trainer import device_peak_flops as _pk
            last_exc = None
            for lb, lrec in ((2, "none"), (1, "full")):
                lcfg = _LC(vocab_size=32000, hidden_size=1024,
                           intermediate_size=3072, num_hidden_layers=8,
                           num_attention_heads=8, num_key_value_heads=4,
                           max_position_embeddings=8192, dtype="bfloat16",
                           recompute=lrec)
                _log(f"long-context: compiling s=8192 b={lb} recompute={lrec}")
                try:
                    (ltps, lstep, _stall, _loss, lmodel,
                     _ps, _ss, _ca) = _train_bench(lcfg, lb, 8192, 5, 2)
                    break
                except Exception as e:
                    # clear frame locals: the traceback pins the failed
                    # tier's model/opt device arrays, which would keep HBM
                    # allocated while the fallback tier compiles
                    traceback.clear_frames(e.__traceback__)
                    last_exc = e
            else:
                raise RuntimeError("all longctx tiers failed") from last_exc
            ltps_chip = ltps / jax.device_count()
            out["longctx_seq_len"] = 8192
            out["longctx_batch"] = lb
            out["longctx_recompute"] = lrec
            out["longctx_tokens_per_sec_per_chip"] = round(ltps_chip, 1)
            out["longctx_mfu"] = round(
                ltps_chip * lmodel.flops_per_token(8192) / _pk(), 4)
            out["longctx_mfu_causal"] = round(
                ltps_chip * lmodel.flops_per_token(8192, causal=True)
                / _pk(), 4)
            out["longctx_params"] = lmodel.num_params()
            _log("long-context: timed")

            # sequence-packing sub-leg: two 4096-token documents packed per
            # row via the flash kernel's segment-id path (reference varlen:
            # flash_attn_kernel.cu:91) — same s=8192 compute budget, zero
            # padding waste; per-segment positions restart and boundary
            # labels are masked, so this is exact packed-pretraining
            # semantics, not an approximation.
            try:
                import numpy as _n
                from paddle_tpu.optimizer import AdamW as _AW
                from paddle_tpu.trainer import Trainer as _Tr
                ptr = _Tr(lmodel, _AW(learning_rate=1e-4,
                                      parameters=lmodel))
                rs = _n.random.RandomState(7)
                ids = rs.randint(0, lcfg.vocab_size, (lb, 8192 + 1),
                                 _n.int32)
                lbl = ids[:, 1:].copy()
                lbl[:, 4095] = -100          # no cross-document target
                pos = _n.concatenate([_n.arange(4096), _n.arange(4096)])
                pbatch = {
                    "input_ids": jnp.asarray(ids[:, :-1]),
                    "labels": jnp.asarray(lbl),
                    "position_ids": jnp.broadcast_to(
                        jnp.asarray(pos, jnp.int32)[None], (lb, 8192)),
                    "segment_ids": jnp.broadcast_to(
                        jnp.asarray(_n.repeat(_n.arange(2), 4096),
                                    jnp.int32)[None], (lb, 8192)),
                }
                _log("long-context: compiling packed (segment-id) step")
                # 3 warmup calls: the FIRST post-compile step re-specializes
                # on the donated buffers' layouts (observed live: one ~15 s
                # stall exactly once, then steady 216 ms) — time min-of-
                # rounds after it
                for _ in range(3):
                    l2 = ptr.train_step(pbatch)
                _sync(l2)
                pdt = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    for _ in range(3):
                        l2 = ptr.train_step(pbatch)
                    _sync(l2)
                    pdt = min(pdt, (time.perf_counter() - t0) / 3)
                out["longctx_packed_tokens_per_sec_per_chip"] = round(
                    lb * 8192 / pdt / jax.device_count(), 1)
                out["longctx_packed_segments"] = 2
            except Exception as e:
                out["longctx_packed_error"] = (f"{type(e).__name__}: "
                                               f"{str(e)[:150]}")
    except Exception as e:
        out["longctx_error"] = f"{type(e).__name__}: {str(e)[:150]}"

    try:
        # MoE leg (round-4 verdict missing #5): dropless grouped-matmul vs
        # capacity-dense at DeepSeekMoE expert scale (e=64, d=2048, f=1408,
        # top-6), fwd+bwd, interleaved min-of-rounds. Dropless runs
        # lax.ragged_dot (tune_db moe_grouped_mm: 1.7x over megablox gmm);
        # capacity=1.25 computes 1.25/6 the routed rows via one batched
        # einsum but DROPS overflow tokens — both are reported, the
        # semantics choice stays with the user (parallel/moe.py).
        if on_tpu:
            import numpy as _n

            import paddle_tpu as _pt
            from paddle_tpu.parallel.moe import MoELayer as _ML
            _B, _S, _D, _F, _E, _K = 1, 4096, 2048, 1408, 64, 6
            rsm = _n.random.RandomState(0)
            xm = jnp.asarray(rsm.normal(0, 1, (_B, _S, _D)), jnp.bfloat16)
            moe_legs = {}
            for nm, cf in (("moe_dropless_us", None),
                           ("moe_dense_cap125_us", 1.25)):
                _pt.seed(0)
                lyr = _ML(_D, _F, _E, top_k=_K, capacity_factor=cf,
                          dtype="bfloat16")
                prm = lyr.raw_parameters()

                def _mloss(p, x, lyr=lyr):
                    o, aux = lyr.functional_call(p, x)
                    return o.astype(jnp.float32).mean() + 0.01 * aux
                _log(f"moe: compiling {nm}")
                gfn = jax.jit(jax.grad(_mloss, argnums=(0, 1)))
                r = gfn(prm, xm)
                _sync(jax.tree.leaves(r)[0])
                moe_legs[nm] = (gfn, prm)
            best = {nm: float("inf") for nm in moe_legs}
            for _ in range(4):
                for nm, (gfn, prm) in moe_legs.items():
                    t0 = time.perf_counter()
                    for _ in range(3):
                        r = gfn(prm, xm)
                    _sync(jax.tree.leaves(r)[0])
                    best[nm] = min(best[nm],
                                   (time.perf_counter() - t0) / 3)
            for nm, v in best.items():
                out[nm] = round(v * 1e6, 1)
            out["moe_experts"] = _E
            out["moe_top_k"] = _K
            _log("moe: timed")
    except Exception as e:
        out["moe_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    return out


def _loss_head_probe(cfg, on_tpu, step_time_s):
    """Loss-head step-decomposition (ISSUE 5): fused vocab-CE vs the naive
    materialized-logits head, compiled grad(loss) over the same arrays,
    interleaved min-of-rounds — reported as RATIOS (noisy shared host).
    ``loss_head_share`` = fused head time / full train-step time, the
    decomposition the 0.63→0.81 e2e-MFU-gap work tracks;
    ``loss_head_logits_mb_avoided`` = the fp32 [B*S, V] activation the
    fused path never allocates."""
    out = {}
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        from loss_head_bench import run_loss_head_bench
        if on_tpu:
            # the headline training shape: the decomposition then speaks
            # to the measured e2e step directly
            kw = dict(n=8 * 2048, h=cfg.hidden_size, v=cfg.vocab_size,
                      dtype="bfloat16", rounds=4, iters=2)
        else:
            # CPU tier: a loss-head-bound shape (V >> H — the regime the
            # fused head targets; tiny-vocab configs are trunk-bound and
            # time nothing but matmul noise). step share is only
            # meaningful when the probe shape IS the headline shape, so
            # it's TPU-only
            kw = dict(n=2048, h=128, v=16000, dtype="bfloat16",
                      rounds=6, iters=1)
            step_time_s = None
        _log("loss-head: fused vs naive A/B")
        out.update(run_loss_head_bench(step_time_s=step_time_s, **kw))
    except Exception as e:
        out["loss_head_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    return out


def _obs_probe(on_tpu):
    """Metrics-plane probe (ISSUE 4): A/B a short Trainer.fit with the
    observability registry off vs on, SAME process and trainer, rounds
    interleaved min-of-rounds — the overhead is reported as a RATIO
    (absolute tok/s is too noisy on a shared host). Then snapshots the
    enabled-leg telemetry (goodput buckets, compile counters, serving
    percentiles via a micro serving leg) into the detail section."""
    import numpy as np

    import jax.numpy as jnp

    import paddle_tpu as pt
    import paddle_tpu.observability as obs
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.trainer import Trainer
    out = {}
    try:
        cfg = LlamaConfig.tiny()
        pt.seed(0)
        model = LlamaForCausalLM(cfg)
        tr = Trainer(model, AdamW(learning_rate=1e-4, parameters=model))
        rs = np.random.RandomState(0)

        def batches(n):
            bs = []
            for _ in range(n):
                ids = rs.randint(0, cfg.vocab_size, (4, 129), np.int32)
                bs.append({"input_ids": jnp.asarray(ids[:, :-1]),
                           "labels": jnp.asarray(ids[:, 1:])})
            return bs

        n, rounds = 50, 4
        _log("obs: compiling probe trainer")
        tr.fit(iter(batches(4)), steps=4, log_every=10 ** 9)
        legs = {"off": float("inf"), "on": float("inf")}
        data = {k: [batches(n) for _ in range(rounds)] for k in legs}
        _log("obs: timing metrics off vs on (interleaved)")
        for r in range(rounds):
            obs.REGISTRY.disable()
            t0 = time.perf_counter()
            tr.fit(iter(data["off"][r]), steps=n, log_every=10)
            legs["off"] = min(legs["off"], time.perf_counter() - t0)
            obs.ledger().reset()
            obs.REGISTRY.enable()
            t0 = time.perf_counter()
            tr.fit(iter(data["on"][r]), steps=n, log_every=10)
            legs["on"] = min(legs["on"], time.perf_counter() - t0)
        out["obs_step_time_off_s"] = round(legs["off"] / n, 6)
        out["obs_step_time_on_s"] = round(legs["on"] / n, 6)
        out["obs_overhead_ratio"] = round(legs["on"] / legs["off"], 4)
        # deterministic half of the ≤2% claim (the A/B ratio above rides
        # a noisy shared host): the disabled-path cost of one instrument
        # call — the price every hot path pays in a run that never opts in
        obs.REGISTRY.disable()
        c = obs.REGISTRY.counter("pt_bench_disabled_probe")
        t0 = time.perf_counter()
        for _ in range(100_000):
            c.inc()
        out["obs_disabled_ns_per_inc"] = round(
            (time.perf_counter() - t0) / 100_000 * 1e9, 1)

        # micro serving leg with the plane on -> percentile gauges.
        # The default SLO packs (ISSUE 10) ride this leg: installed
        # AFTER the timing A/B so the sentry's snapshot-per-tick cost
        # can't tilt obs_overhead_ratio, ticked by the engine's own
        # drain-boundary wiring — the slo_incidents row records which
        # default rules this round trips. On the CPU tier the
        # cost-model drift band legitimately fires (the roofline does
        # not model tiny-model CPU dispatch overhead — documented in
        # DESIGN_DECISIONS ISSUE 9); an honest row beats a quiet one.
        obs.REGISTRY.enable()
        from paddle_tpu.observability import sentry as sn
        # min_interval_s keeps the engine's per-drain maybe_tick from
        # paying a full collect() inside the very leg whose ITL/TTFT
        # percentiles the serving rules then judge (the README's own
        # recommended hot-path setting)
        sentry = sn.install(sn.SloSentry(sn.default_rules(),
                                         min_interval_s=1.0))
        from paddle_tpu.inference import ContinuousBatchingEngine
        from paddle_tpu.inference.generation import GenerationConfig
        eng = ContinuousBatchingEngine(
            model, max_batch=2, page_size=8, max_len=32,
            generation_config=GenerationConfig(max_new_tokens=8,
                                               do_sample=False),
            decode_block=4)
        for L in (6, 8, 5):
            eng.submit(rs.randint(0, cfg.vocab_size, (L,)).astype(np.int32))
        eng.run()
        lat = eng.publish_metrics()
        # final evaluation over the freshly published percentile gauges
        # — drop the hot-path rate limit so it can't be skipped
        sentry.min_interval_s = 0.0
        sentry.tick()
        out["slo_incidents"] = {
            "count": len(sentry.incidents),
            "ticks": sentry.ticks,
            "rules_fired": sorted({i.rule for i in sentry.incidents})}
        sn.uninstall()
        snap = obs.collect()
        t = obs.ledger().totals()
        from paddle_tpu.core import compile_cache as _cc
        out["obs_metrics"] = {
            "series": len(snap),
            "goodput": {k: t[k] for k in
                        list(obs.goodput.BUCKETS) + ["total_s",
                                                     "goodput_fraction"]},
            "compile_cache": {k: v for k, v in _cc.stats().items()
                              if k != "persistent_dir"},
            "serving": {k: round(v, 5) for k, v in lat.items()
                        if k.endswith("_s")},
        }
    except Exception as e:
        out["obs_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    finally:
        try:
            from paddle_tpu.observability import sentry as _sn
            _sn.uninstall()
        except Exception:
            pass
        try:
            obs.REGISTRY.disable()
        except Exception:
            pass
    return out


def _graph_contracts_probe(on_tpu):
    """Graph-contract rows (ISSUE 8): run the static analyzers over the
    canonical compiled entrypoints and report count/byte metrics — per the
    bench-variance policy these are structural (deterministic per build),
    not wall-time. ``train_step_collective_count`` counts collectives in
    the canonical train-step graph (0 single-chip; a sharded trainer on a
    pod shows its real comm load), ``serving_tick_donated_bytes`` is the
    aliased (donated) input bytes of the serving decode tick — the number
    that drops when a refactor silently loses a donation.

    ISSUE 14 adds ``overlap_exposed_comm_fraction``: the exposed
    (un-overlapped) comm fraction of the dp2xtp2 canonical step
    (``tp_fused_ce``) from the same start→done pairing the budget gate
    enforces. The graph needs a 2x2 mesh, so a single-device host
    delegates to a ``tools/graph_lint.py --json`` subprocess on 8
    virtual CPU devices (it self-forces the count) and reads the
    snapshot; ``overlap_backend`` records which path the number rode."""
    out = {}
    try:
        import paddle_tpu.analysis as A
        _log("graph contracts: analyzing canonical train/serving graphs")
        g = A.build_graph("train_step_k1")
        rep = A.analyze(g.compiled, g.name, g.contract)
        out["train_step_collective_count"] = \
            rep.collectives["total_collectives"]
        out["train_step_largest_intermediate_mb"] = round(
            rep.materialization["largest_intermediate_bytes"] / 2 ** 20, 3)
        g = A.build_graph("serving_tick")
        rep = A.analyze(g.compiled, g.name, g.contract)
        out["serving_tick_donated_bytes"] = rep.donation["donated_bytes"]
        out["serving_tick_host_transfers"] = \
            rep.transfers["host_transfer_count"]
    except Exception as e:
        out["graph_contracts_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    try:
        import jax

        import paddle_tpu.analysis as A
        if jax.device_count() >= 4:
            _log("graph contracts: overlap report on the dp2xtp2 step")
            g = A.build_graph("tp_fused_ce")
            rep = A.analyze(g.compiled, g.name, g.contract, mesh=g.mesh)
            snap = A.snapshot_report(rep)
            out["overlap_backend"] = "inline"
        else:
            import subprocess

            from paddle_tpu.distributed.overlap import OVERLAP_XLA_FLAGS
            _log("graph contracts: overlap report via graph_lint "
                 "subprocess (8 virtual devices)")
            env = dict(os.environ)
            env.pop("PALLAS_AXON_POOL_IPS", None)
            # the child runs on forced CPU devices — the parent's vetted
            # TPU overlap flags would be rejected there, so strip them
            env["PT_NO_OVERLAP"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            toks = set(OVERLAP_XLA_FLAGS.split())
            env["XLA_FLAGS"] = " ".join(
                t for t in env.get("XLA_FLAGS", "").split()
                if t not in toks)
            cmd = [sys.executable,
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tools", "graph_lint.py"),
                   "--graphs", "tp_fused_ce", "--json"]
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=600, env=env)
            # --json prints one indented JSON object (verbose output is
            # suppressed); tolerate stray preamble lines before it
            d = json.loads(res.stdout[res.stdout.index("{"):])
            snap = d["snapshots"]["tp_fused_ce"]
            out["overlap_backend"] = "cpu-subprocess"
        out["overlap_exposed_comm_fraction"] = \
            snap["exposed_comm_fraction"]
        out["overlap_min_distance"] = snap["min_overlap_distance"]
    except Exception as e:
        out["overlap_row_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    return out


def _planner_probe(on_tpu):
    """Sharding-planner rows (ISSUE 11): predicted-vs-measured rank
    order over the legal configs of a small mesh, on the micro model.

    Ratio rows per the bench-variance policy:
    ``planner_rank_agreement`` (pairwise concordance of the predicted
    and measured step-time orderings), ``planner_top1_is_measured_top2``
    (1.0 when the planner's pick lands in the measured top 2 — the
    acceptance bar), ``planner_predicted_mfu`` (the chosen config's
    predicted MFU), plus the chosen config string as a detail row.

    With ≥4 local devices the validation runs inline on the real mesh;
    a single-device host delegates to ``tools/plan.py --validate`` in a
    subprocess on 8 virtual CPU devices (the dryrun tier) —
    ``planner_backend`` records which, so cross-round readers know what
    the numbers rode on."""
    out = {}
    try:
        import jax
        if jax.device_count() >= 4:
            from paddle_tpu.distributed import auto_parallel as ap
            from paddle_tpu.models import LlamaConfig
            _log("planner: pricing configs on the local mesh")
            mcfg = LlamaConfig(
                vocab_size=320, hidden_size=64, intermediate_size=96,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=128)
            n = 8 if jax.device_count() >= 8 else 4
            rep = ap.plan(mcfg, n_devices=n, global_batch=8, seq_len=64,
                          keep_builds=True, model_name="llama-micro")
            v = ap.validate_rank_order(rep)
            chosen_cfg = str(rep.chosen.config)
            chosen_mfu = rep.chosen.predicted_mfu
            out["planner_backend"] = "inline"
        else:
            import subprocess
            _log("planner: validating on an 8-virtual-device subprocess")
            env = dict(os.environ)
            env.pop("PALLAS_AXON_POOL_IPS", None)
            cmd = [sys.executable,
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tools", "plan.py"),
                   "--mesh", "4x2", "--model", "llama-micro",
                   "--batch", "8", "--seq", "64",
                   "--validate", "--json", "--virtual-devices", "8"]
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=900, env=env)
            if res.returncode != 0:
                raise RuntimeError(f"plan.py rc={res.returncode}: "
                                   f"{res.stderr[-300:]}")
            d = json.loads(res.stdout.strip().splitlines()[-1])
            v = d["validation"]
            chosen_cfg = d["chosen"]
            chosen_mfu = d["ranked"][0]["predicted_mfu"]
            out["planner_backend"] = "cpu-subprocess"
        out["planner_rank_agreement"] = round(v["agreement"], 4)
        out["planner_top1_is_measured_top2"] = \
            float(v["top1_is_measured_top2"])
        out["planner_predicted_mfu"] = round(chosen_mfu, 4)
        out["planner_chosen_config"] = chosen_cfg
        out["planner_n_configs"] = v["n_configs"]
    except Exception as e:
        out["planner_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    return out


def _fsdp_probe(on_tpu):
    """ZeRO/FSDP rows (ISSUE 18): what the fsdp axis costs and buys at
    EQUAL device count, on the micro model.

    ``fsdp_step_overhead_ratio`` — measured fsdp4 ÷ dp4 step time over
    4 devices (the gather/reduce-scatter tax; interleaved min-of-rounds
    via the planner's own rank-order measurement). ``fsdp_hbm_ratio`` —
    closed-form ``estimate_hbm`` total for the same pair (params+slots+
    grads ÷4 plus the one-layer gather working set vs pure dp): the
    memory the axis exists to save, deterministic arithmetic so a tight
    band. With ≥4 local devices the A/B runs inline; otherwise two
    ``tools/plan.py --config`` subprocesses on 4 virtual CPU devices —
    ``fsdp_backend`` records which."""
    out = {}
    from paddle_tpu.distributed import auto_parallel as ap
    from paddle_tpu.models import LlamaConfig
    mcfg = LlamaConfig(
        vocab_size=320, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=128)
    cfg_off = ap.ParallelConfig(dp=4)
    cfg_on = ap.ParallelConfig(fsdp=4)
    try:
        m_off = ap.estimate_hbm(mcfg, cfg_off, global_batch=8, seq_len=64)
        m_on = ap.estimate_hbm(mcfg, cfg_on, global_batch=8, seq_len=64)
        out["fsdp_hbm_ratio"] = round(m_on.total_bytes
                                      / m_off.total_bytes, 4)
    except Exception as e:
        out["fsdp_hbm_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    try:
        import jax
        meas = {}
        if jax.device_count() >= 4:
            _log("fsdp: A/B pricing dp4 vs fsdp4 on the local mesh")
            rep = ap.plan(mcfg, n_devices=4, global_batch=8, seq_len=64,
                          configs=[cfg_off, cfg_on], keep_builds=True,
                          drift="ignore", model_name="llama-micro")
            ap.validate_rank_order(rep)
            for pc in rep.ranked:
                meas[str(pc.config)] = pc.measured_step_s
            out["fsdp_backend"] = "inline"
        else:
            import subprocess
            _log("fsdp: A/B via plan.py on 4 virtual devices")
            for cfg in (cfg_off, cfg_on):
                env = dict(os.environ)
                env.pop("PALLAS_AXON_POOL_IPS", None)
                cmd = [sys.executable,
                       os.path.join(os.path.dirname(
                           os.path.abspath(__file__)), "tools", "plan.py"),
                       "--devices", "4", "--model", "llama-micro",
                       "--batch", "8", "--seq", "64",
                       "--config", str(cfg),
                       "--validate", "--json", "--virtual-devices", "4"]
                res = subprocess.run(cmd, capture_output=True, text=True,
                                     timeout=900, env=env)
                if res.returncode != 0:
                    raise RuntimeError(f"plan.py rc={res.returncode}: "
                                       f"{res.stderr[-300:]}")
                d = json.loads(res.stdout.strip().splitlines()[-1])
                meas[d["chosen"]] = d["ranked"][0]["measured_step_s"]
            out["fsdp_backend"] = "cpu-subprocess"
        t_off = meas[str(cfg_off)]
        t_on = meas[str(cfg_on)]
        out["fsdp_step_overhead_ratio"] = round(t_on / t_off, 4)
        out["fsdp_step_dp4_s"] = t_off
        out["fsdp_step_fsdp4_s"] = t_on
    except Exception as e:
        out["fsdp_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    return out


def _moe_ep_probe(on_tpu):
    """Expert-parallelism rows (ISSUE 20), micro MoE model.

    ``moe_ep_step_speedup`` — replicated-experts dp2 ÷ dp2_ep2 measured
    step time at EQUAL devices and experts (interleaved min-of-rounds
    via the planner's rank-order measurement; the ep leg pays the
    all-to-all, buys per-rank expert HBM). ``moe_ep_a2a_pred_over_
    measured`` — the priced census's per-a2a seconds ÷ a wall-clock
    shard_map all-to-all of the same dispatch buffer on the same mesh
    (cost-model drift for the NEW collective, healthy ~1.0 on TPU,
    nominal on CPU). ``moe_grouped_matmul_speedup`` — XLA ragged_dot ÷
    Pallas grouped-matmul wall time, interleaved min-of-rounds
    (interpret mode off-TPU, so the CPU row only proves the kernel
    path runs; the TPU row is the one the kernel must win)."""
    out = {}
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed import auto_parallel as ap
    from paddle_tpu.models.moe_lm import MoEConfig
    mcfg = MoEConfig(
        vocab_size=320, hidden_size=64, intermediate_size=96,
        moe_intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, num_experts=4,
        num_experts_per_tok=2, num_shared_experts=1,
        first_k_dense_replace=1, capacity_factor=None,
        max_position_embeddings=128)
    cfg_rep = ap.ParallelConfig(dp=2)
    cfg_ep = ap.ParallelConfig(dp=2, ep=2)
    try:
        if jax.device_count() < 2:
            raise RuntimeError("needs >= 2 devices for the ep=2 mesh")
        _log("moe-ep: A/B pricing dp2 vs dp2_ep2 on the micro MoE")
        rep = ap.plan(mcfg, n_devices=2, global_batch=8, seq_len=64,
                      configs=[cfg_rep, cfg_ep], keep_builds=True,
                      drift="ignore", model_name="moe-micro")
        ap.validate_rank_order(rep)
        meas = {str(pc.config): pc.measured_step_s for pc in rep.ranked}
        out["moe_ep_step_speedup"] = round(
            meas[str(cfg_rep)] / meas[str(cfg_ep)], 4)
        out["moe_ep_step_dp2_s"] = meas[str(cfg_rep)]
        out["moe_ep_step_ep2_s"] = meas[str(cfg_ep)]

        pc_ep = next(pc for pc in rep.ranked if pc.config.ep > 1)
        rows = [r for r in pc_ep.graph.priced_census["per_op"]
                if r["opcode"] == "all-to-all"]
        if rows and pc_ep.build is not None:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P
            mesh_ = getattr(pc_ep.build.mesh, "mesh", pc_ep.build.mesh)
            # the dropless dispatch buffer of THIS config: [e, t_local, d]
            t_local = 8 * 64 // 2
            buf = jnp.ones((mcfg.num_experts, t_local, mcfg.hidden_size),
                           jnp.float32)
            fn = jax.jit(shard_map(
                lambda x: jax.lax.all_to_all(
                    x, "ep", split_axis=0, concat_axis=1, tiled=True),
                mesh=mesh_, axis_names=frozenset({"ep"}),
                in_specs=P("ep", None, None),
                out_specs=P("ep", None, None), check_vma=False))
            fn(buf).block_until_ready()
            t_meas = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                fn(buf).block_until_ready()
                t_meas = min(t_meas, time.perf_counter() - t0)
            pred_one = sum(r["seconds"] for r in rows) / len(rows)
            if t_meas > 0:
                out["moe_ep_a2a_pred_over_measured"] = round(
                    pred_one / t_meas, 4)
        out["moe_ep_backend"] = "inline"
    except Exception as e:
        out["moe_ep_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    try:
        from paddle_tpu.ops.pallas import grouped_matmul as gmm
        m, k, n, g = 512, 128, 128, 4
        dt = jnp.bfloat16 if on_tpu else jnp.float32
        rs = np.random.RandomState(0)
        xs = jnp.asarray(rs.randn(m, k), dt)
        w = jnp.asarray(rs.randn(g, k, n), dt)
        gs = jnp.full((g,), m // g, jnp.int32)
        xla_fn = jax.jit(gmm.xla_grouped_matmul)
        interp = not on_tpu
        pal_fn = jax.jit(lambda a, b, s: gmm.grouped_matmul_pallas(
            a, b, s, interpret=interp))
        xla_fn(xs, w, gs).block_until_ready()
        pal_fn(xs, w, gs).block_until_ready()
        t_xla, t_pal = float("inf"), float("inf")
        for _ in range(5):       # interleaved min-of-rounds
            t0 = time.perf_counter()
            xla_fn(xs, w, gs).block_until_ready()
            t_xla = min(t_xla, time.perf_counter() - t0)
            t0 = time.perf_counter()
            pal_fn(xs, w, gs).block_until_ready()
            t_pal = min(t_pal, time.perf_counter() - t0)
        out["moe_grouped_matmul_speedup"] = round(t_xla / t_pal, 4)
        out["moe_grouped_matmul_backend"] = ("pallas-tpu" if on_tpu
                                             else "pallas-interpret")
    except Exception as e:
        out["moe_gmm_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    return out


def _elastic_probe(on_tpu):
    """Elastic scale-in rows (ISSUE 15): a timed mini kill→reshard cycle
    on the micro model. ``elastic_reshard_seconds`` = wall time to
    verify + reshard + place a checkpoint saved under the big mesh onto
    half the devices; ``elastic_resume_steps_replayed`` = killed_step −
    restored_step under the probe's save-every-4/kill-at-6 schedule
    (2 by construction — any other value means the cadence or the
    commit/fallback logic regressed). With ≥2 local devices the cycle
    runs inline; a single-device host delegates to
    ``paddle_tpu.testing._elastic_train --probe-reshard`` on 4 virtual
    CPU devices — ``elastic_probe_backend`` records which."""
    out = {}
    try:
        import jax
        if jax.device_count() >= 2:
            _log("elastic: timing reshard cycle on the local mesh")
            from paddle_tpu.testing._elastic_train import reshard_probe
            out.update(reshard_probe())
            out["elastic_probe_backend"] = "inline"
        else:
            import subprocess
            _log("elastic: reshard cycle on a 4-virtual-device subprocess")
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env.setdefault("JAX_PLATFORMS", "cpu")
            cmd = [sys.executable, "-m",
                   "paddle_tpu.testing._elastic_train",
                   "--ckpt-dir", "unused", "--probe-reshard",
                   "--virtual-devices", "4"]
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=900, env=env)
            if res.returncode != 0:
                raise RuntimeError(f"_elastic_train rc={res.returncode}: "
                                   f"{res.stderr[-300:]}")
            for line in res.stdout.splitlines():
                if line.startswith("ELASTIC_PROBE "):
                    out.update(json.loads(line[len("ELASTIC_PROBE "):]))
            out["elastic_probe_backend"] = "cpu-subprocess"
    except Exception as e:
        out["elastic_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    return out


_ARTIFACT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_artifacts")


def _write_tpu_artifact(payload, early: bool = False):
    """Persist every successful real-TPU measurement as an auditable JSON
    (round-3 verdict: TPU claims without committed artifacts are
    unauditable). Includes git HEAD so the artifact pins the exact code.

    ``early=True`` writes the headline-only capture the moment the
    training number exists (VERDICT r05 item 1c): the detail probes that
    follow take many minutes over a tunnel that has wedged mid-round twice
    now — a late wedge (or driver timeout) must never zero the round's
    record. The final full artifact is written afterwards with a later
    captured_at, so _latest_tpu_artifact prefers it when both exist."""
    import datetime
    import subprocess
    try:
        os.makedirs(_ARTIFACT_DIR, exist_ok=True)
        try:
            head = subprocess.run(
                ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
                cwd=os.path.dirname(_ARTIFACT_DIR),
                timeout=10).stdout.strip() or "unknown"
        except Exception:
            head = "unknown"
        art = dict(payload)
        art["git_head"] = head
        if early:
            art["early_capture"] = True
        now = datetime.datetime.now(datetime.timezone.utc)
        art["captured_at"] = now.isoformat()
        d = payload.get("detail", {})
        # timestamp + attention path in the name: a later degraded run must
        # never clobber an earlier good artifact of the same config
        name = (f"tpu_{d.get('device', 'unknown').replace(' ', '_')}"
                f"_{d.get('params', 0) // 1_000_000}M"
                f"_s{d.get('seq_len', 0)}"
                f"_{d.get('attention_path', 'x').split(' ')[0]}"
                f"{'_early' if early else ''}"
                f"_{now.strftime('%Y%m%dT%H%M%S')}.json")
        path = os.path.join(_ARTIFACT_DIR, name)
        with open(path, "w") as f:
            json.dump(art, f, indent=1)
        _log(f"{'EARLY ' if early else ''}TPU artifact written: {path} "
             f"(commit it!)")
    except Exception as e:
        _log(f"artifact write failed: {e}")


def _latest_tpu_artifact():
    """Newest committed TPU artifact, surfaced when the round-end tunnel is
    down so the official record still points at auditable TPU data."""
    try:
        files = [os.path.join(_ARTIFACT_DIR, f)
                 for f in os.listdir(_ARTIFACT_DIR) if f.endswith(".json")]
        if not files:
            return None
        # order by the embedded capture time, not fs mtime (fresh clones
        # assign arbitrary near-identical mtimes)
        def load(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except Exception:
                return {}
        arts = {path: load(path) for path in files}

        def cap_time(path):
            return arts[path].get("captured_at") or ""
        # prefer the newest artifact with a REAL headline value: a
        # null-value record (e.g. a projection sheet, BENCH_r05's case)
        # must not shadow auditable TPU numbers; fall back to plain
        # newest only if no artifact carries a value
        valued = [p for p in files if arts[p].get("value") is not None]
        newest = max(valued or files, key=cap_time)
        art = arts[newest]
        return {"file": os.path.relpath(newest, os.path.dirname(_ARTIFACT_DIR)),
                "git_head": art.get("git_head"),
                "captured_at": art.get("captured_at"),
                "value": art.get("value"), "unit": art.get("unit"),
                "vs_baseline": art.get("vs_baseline"),
                "mfu": art.get("detail", {}).get("mfu"),
                "backend": art.get("detail", {}).get("backend")}
    except Exception:
        return None


def _run(error_note):
    import jax

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig
    from paddle_tpu.ops.registry import device_is_tpu
    from paddle_tpu.trainer import device_peak_flops

    backend = jax.default_backend()
    on_tpu = device_is_tpu(jax.devices()[0])
    if on_tpu:
        # ~0.5B params — fits one v5e chip (16GB) in bf16 with adam fp32 state
        cfg = LlamaConfig(**_HEADLINE_TPU_CFG)
        batch_size, seq_len, steps, warmup = 8, 2048, 10, 3
    else:
        cfg = LlamaConfig.tiny()
        batch_size, seq_len, steps, warmup = 4, 128, 6, 2

    # degradation ladder (round-2 lesson: never zero the bench when a
    # weaker configuration can still produce a number): full config →
    # recompute=full on OOM-ish failures → Pallas disabled. The tiers
    # NEST: an OOM retry that then hits a kernel regression still falls
    # through to the XLA tier.
    attn_path = "pallas" if on_tpu else "xla"
    attempts = [("as-configured", lambda: None)]
    if on_tpu:
        attempts.append(("recompute=full",
                         lambda: setattr(cfg, "recompute", "full")))
        attempts.append(("PT_DISABLE_PALLAS",
                         lambda: os.environ.__setitem__(
                             "PT_DISABLE_PALLAS", "1")))
    last_exc = None
    for tier, apply in attempts:
        apply()
        try:
            (tps, step_s, stall_s, loss, model, per_step,
             superstep, cost_attr) = _train_bench(
                 cfg, batch_size, seq_len, steps, warmup,
                 superstep_probe=True)
            if tier != "as-configured":
                note = (f"degraded to {tier} after: "
                        f"{type(last_exc).__name__}: {str(last_exc)[:200]}")
                error_note = f"{error_note}; {note}" if error_note else note
                if tier == "PT_DISABLE_PALLAS":
                    attn_path = "xla-fallback"
            break
        except Exception as e:
            # clear frame locals so the failed tier's device arrays are
            # freed before the next tier compiles (the traceback would
            # otherwise pin model+opt HBM through the retry)
            traceback.clear_frames(e.__traceback__)
            last_exc = e
    else:
        # chain the real exception so main()'s traceback artifact shows
        # where the bench actually failed, not this raise site
        raise RuntimeError("all bench tiers failed") from last_exc

    if attn_path == "pallas":
        # report what actually ran: the kernel's own lowering probe can
        # silently drop dispatch to XLA without raising
        from paddle_tpu.ops.pallas.flash_attention import _tpu_lowering_ok
        if os.environ.get("PT_DISABLE_PALLAS"):
            attn_path = "xla-fallback"
        elif not _tpu_lowering_ok():
            attn_path = "xla (pallas lowering probe failed)"

    n_chips = jax.device_count()
    tps_chip = tps / n_chips
    mfu = tps_chip * model.flops_per_token(seq_len) / device_peak_flops()
    # dual-convention MFU (round-4 verdict weak #5): the headline `mfu` is
    # amortized-async + PaLM non-causal FLOPs (cross-paper comparable);
    # `mfu_fenced_causal` is the strictest honest-utilization reading —
    # per-step host-fenced wall time + only the FLOPs the causal kernel
    # executes. Both are quoted wherever the headline appears (README).
    mfu_causal = (tps_chip * model.flops_per_token(seq_len, causal=True)
                  / device_peak_flops())
    mfu_fenced_causal = None
    if per_step:
        fenced = sorted(per_step)[len(per_step) // 2]
        tps_fenced = batch_size * seq_len / fenced / n_chips
        mfu_fenced_causal = round(
            tps_fenced * model.flops_per_token(seq_len, causal=True)
            / device_peak_flops(), 4)

    detail = {
        "backend": backend,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "attention_path": attn_path,
        # report what is ACTUALLY in XLA_FLAGS — PT_NO_OVERLAP only stops
        # bench from adding flags, it cannot strip preexisting ones
        "overlap_flags": ("on" if "async_collective" in
                          os.environ.get("XLA_FLAGS", "")
                          else ("off" if os.environ.get("PT_NO_OVERLAP")
                                else "default")),
        "n_chips": n_chips,
        "params": model.num_params(),
        "batch_size": batch_size,
        "seq_len": seq_len,
        "steps": steps,
        "step_time_s": round(step_s, 4),
        "fenced_step_times_s": per_step,
        "input_stall_s_per_step": round(stall_s, 4),
        "mfu": round(mfu, 4),
        "mfu_causal": round(mfu_causal, 4),
        "mfu_fenced_causal": mfu_fenced_causal,
        "final_loss": loss,
    }
    detail.update(superstep)
    # cost-observatory rows (ISSUE 9) — ratio metrics per the bench-
    # variance policy: `mfu_analytical` is HLO-attributed flops of the
    # HEADLINE step executable / (measured step time x device peak) —
    # same analyzer as the live pt_model_flops_utilization gauge and
    # graph_lint's flop floor (vs `mfu`, the PaLM closed form);
    # `step_time_predicted_over_measured` is roofline-predicted /
    # measured (cost-model drift); `comm_time_predicted_s` prices the
    # step's collective census bytes against the axis link bandwidth
    # (0.0 single-chip — a sharded pod shows its real comm price here)
    if cost_attr:
        try:
            from paddle_tpu.observability.costs import device_spec
            spec = device_spec()
            detail["mfu_analytical"] = round(
                cost_attr["flops"] / (step_s * spec.peak_flops), 4)
            detail["step_time_predicted_over_measured"] = round(
                cost_attr["predicted_s"] / step_s, 4)
            detail["comm_time_predicted_s"] = round(
                cost_attr["comm_bytes"] / spec.link_bw, 6)
            detail["cost_unmodeled_ops"] = cost_attr["unmodeled_ops"]
        except Exception as e:
            detail["cost_rows_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    # which loss head actually trained: fused (blockwise vocab-CE, no
    # [b, s, V] logits) is the default; PT_NAIVE_LOSS_HEAD or
    # cfg.loss_impl flip it back
    from paddle_tpu.models.llama import fused_loss_enabled
    detail["loss_head_path"] = ("fused" if fused_loss_enabled(cfg)
                                else "naive")
    # compile/AOT cache counters (core/compile_cache.py): hit/miss across
    # this whole process — miss-only means cold; persistent_dir records
    # whether PT_COMPILE_CACHE_DIR wiring was active for this run
    from paddle_tpu.core import compile_cache
    detail["compile_cache"] = compile_cache.stats()

    # ONE payload dict: the early artifact and the final record must never
    # disagree on the headline numbers (detail is shared by reference; the
    # early write snapshots it pre-probes)
    payload = {
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tps_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": detail,
    }
    # EARLY artifact (VERDICT r05 1c): the headline TPU number is now on
    # disk before the long detail probes run — a late tunnel wedge can no
    # longer zero the round's record
    if on_tpu:
        _write_tpu_artifact({**payload, "detail": dict(detail)}, early=True)

    # degraded = any ladder tier beyond as-configured (recompute=full
    # mutation or pallas-off): the A/B legs would differ in more than flags
    detail.update(_overlap_ab(on_tpu, degraded=(tier != "as-configured")))
    detail.update(_decode_bench(cfg, on_tpu))
    detail.update(_loss_head_probe(cfg, on_tpu, step_s))
    detail.update(_obs_probe(on_tpu))
    detail.update(_graph_contracts_probe(on_tpu))
    detail.update(_planner_probe(on_tpu))
    detail.update(_fsdp_probe(on_tpu))
    detail.update(_moe_ep_probe(on_tpu))
    detail.update(_elastic_probe(on_tpu))
    # noise-aware regression verdict vs the checked-in pinned baseline
    # (ISSUE 10): ratio metrics only, per the bench-variance policy —
    # the round records whether it moved past the band, mechanically
    try:
        from paddle_tpu.observability.sentry import baselines as _bl
        bpath = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools", "bench_baseline.json")
        if os.path.exists(bpath):
            detail["bench_diff"] = _bl.diff_records(
                _bl.load_record(bpath), payload).summary()
    except Exception as e:
        detail["bench_diff_error"] = f"{type(e).__name__}: {str(e)[:150]}"
    if error_note:
        payload["error"] = error_note
    if on_tpu:
        _write_tpu_artifact(payload)
    else:
        last = _latest_tpu_artifact()
        if last:
            payload["last_tpu_artifact"] = last
    _emit(payload)


def main():
    tpu_ok, note = _probe_tpu()
    if os.environ.get("PT_BENCH_OVERLAP_PROBE"):
        if not tpu_ok:
            _emit({"step_time_s": None, "error": f"tpu unavailable: {note}"})
            return
        _overlap_probe_main()
        return
    error_note = None
    if tpu_ok:
        # async-collective + latency-hiding scheduler flags (overlap.py);
        # A/B lever: PT_NO_OVERLAP=1
        from paddle_tpu.distributed.overlap import apply_overlap_flags
        apply_overlap_flags(True, target="tpu", validate=True,
                            cwd=os.path.dirname(os.path.abspath(__file__)))
    else:
        error_note = f"TPU unavailable, CPU fallback: {note}"
        # config.update beats the site hook's forced jax_platforms=axon,cpu;
        # must run before any backend initialization in this process
        import jax
        jax.config.update("jax_platforms", "cpu")
    try:
        _run(error_note)
    except Exception as e:
        _emit({"metric": "llama_pretrain_tokens_per_sec_per_chip",
               "value": 0, "unit": "tokens/s/chip", "vs_baseline": 0,
               "error": f"bench run failed ({error_note or 'tpu'}): "
                        f"{type(e).__name__}: {str(e)[:300]}",
               "traceback": traceback.format_exc()[-1500:]})
        sys.exit(0)  # the JSON line IS the artifact


if __name__ == "__main__":
    main()
