"""Benchmark entry: prints ONE JSON line with the headline metric.

Run by the driver on real TPU hardware at the end of each round:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: Llama pretraining tokens/sec/chip (the BASELINE.json north-star
metric); vs_baseline = achieved MFU / 0.40 target MFU (the reference
publishes no absolute numbers — BASELINE.md).

Hardened per round-1 verdict (BENCH_r01 was rc=1 with no artifact):

- TPU availability is probed in a SUBPROCESS under a timeout, because the
  tunneled TPU plugin can hang indefinitely inside backend init (not just
  fail) — an in-process attempt would wedge the whole bench. The probe is
  retried with backoff.
- If the probe never succeeds we switch this process to the CPU backend
  (jax.config.update wins over the site hook's forced "axon,cpu") and still
  emit a JSON line carrying an "error" field describing the degradation.
- Every failure path still prints one parseable JSON line (reference
  posture: tools/ci_op_benchmark.sh perf-gating culture — a wedged runner
  must produce a diagnosable record, not a stack trace).

Model size auto-scales to the backend: a ~0.5B-param Llama on a real TPU
chip, a tiny config on CPU smoke runs.
"""

import json
import os
import sys
import time
import traceback

from paddle_tpu.utils.hw_probe import probe_tpu


def _probe_tpu():
    return probe_tpu(cwd=os.path.dirname(os.path.abspath(__file__)))


def _emit(payload):
    print(json.dumps(payload), flush=True)


def _run(error_note):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.trainer import Trainer, device_peak_flops

    from paddle_tpu.ops.registry import device_is_tpu
    backend = jax.default_backend()
    on_tpu = device_is_tpu(jax.devices()[0])
    pt.seed(0)
    if on_tpu:
        # ~0.5B params — fits one v5e chip (16GB) in bf16 with adam fp32 state
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4608, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=2048, dtype="bfloat16")
        batch_size, seq_len, steps, warmup = 8, 2048, 10, 3
    else:
        cfg = LlamaConfig.tiny()
        batch_size, seq_len, steps, warmup = 4, 128, 6, 2

    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01, parameters=model)
    tr = Trainer(model, opt)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (batch_size, seq_len + 1))
    batch = {"input_ids": jnp.asarray(ids[:, :-1]),
             "labels": jnp.asarray(ids[:, 1:])}

    for _ in range(warmup):
        loss = tr.train_step(batch)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = tr.train_step(batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    tokens = batch_size * seq_len * steps
    tps_chip = tokens / dt / n_chips
    mfu = tps_chip * model.flops_per_token(seq_len) / device_peak_flops()

    payload = {
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tps_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {
            "backend": backend,
            "device": getattr(jax.devices()[0], "device_kind", "unknown"),
            "n_chips": n_chips,
            "params": model.num_params(),
            "batch_size": batch_size,
            "seq_len": seq_len,
            "steps": steps,
            "step_time_s": round(dt / steps, 4),
            "mfu": round(mfu, 4),
            "final_loss": float(loss),
        },
    }
    if error_note:
        payload["error"] = error_note
    _emit(payload)


def main():
    tpu_ok, note = _probe_tpu()
    error_note = None
    if not tpu_ok:
        error_note = f"TPU unavailable, CPU fallback: {note}"
        # config.update beats the site hook's forced jax_platforms=axon,cpu;
        # must run before any backend initialization in this process
        import jax
        jax.config.update("jax_platforms", "cpu")
    try:
        _run(error_note)
    except Exception as e:
        _emit({"metric": "llama_pretrain_tokens_per_sec_per_chip",
               "value": 0, "unit": "tokens/s/chip", "vs_baseline": 0,
               "error": f"bench run failed ({error_note or 'tpu'}): "
                        f"{type(e).__name__}: {str(e)[:300]}",
               "traceback": traceback.format_exc()[-1500:]})
        sys.exit(0)  # the JSON line IS the artifact


if __name__ == "__main__":
    main()
