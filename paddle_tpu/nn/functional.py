"""Functional ops: ``paddle_tpu.nn.functional``.

TPU-native rebuild of the reference functional surface
(reference: python/paddle/nn/functional/ — activation.py, common.py, conv.py,
norm.py, loss.py, pooling.py, flash_attention.py). Everything here is a
jnp/lax composition XLA can fuse; the hot fused kernels (flash attention,
fused rms/layer norm, rope) dispatch through paddle_tpu.ops which selects a
Pallas TPU kernel when available (reference analogues:
paddle/phi/kernels/gpu/flash_attn_kernel.cu,
paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu,
fusion/gpu/fused_rope_kernel.cu).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..core.rng import rng_tracker, GLOBAL_STREAM, LOCAL_STREAM

# ---------------------------------------------------------------------------
# activations (reference: python/paddle/nn/functional/activation.py)
# ---------------------------------------------------------------------------

def relu(x):
    return jnp.maximum(x, 0)


def relu6(x):
    return jnp.clip(x, 0, 6)


def leaky_relu(x, negative_slope: float = 0.01):
    return jnp.where(x >= 0, x, x * negative_slope)


def elu(x, alpha: float = 1.0):
    return jnp.where(x > 0, x, alpha * jnp.expm1(x))


def gelu(x, approximate: bool = False):
    if approximate:
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.gelu(x, approximate=False)


def silu(x):
    return x * jax.nn.sigmoid(x)


swish = silu


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x, slope: float = 1.0 / 6.0, offset: float = 0.5):
    return jnp.clip(x * slope + offset, 0.0, 1.0)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def softplus(x, beta: float = 1.0, threshold: float = 20.0):
    bx = beta * x
    return jnp.where(bx > threshold, x, jnp.log1p(jnp.exp(bx)) / beta)


def tanh(x):
    return jnp.tanh(x)


def softmax(x, axis: int = -1, dtype=None, name=None):
    if dtype is not None:
        from ..core.dtype import convert_dtype
        x = jnp.asarray(x).astype(convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis: int = -1, dtype=None, name=None):
    if dtype is not None:
        from ..core.dtype import convert_dtype
        x = jnp.asarray(x).astype(convert_dtype(dtype))
    return jax.nn.log_softmax(x, axis=axis)


def glu(x, axis: int = -1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def swiglu(x, y=None):
    """SwiGLU used by Llama-style MLPs (reference:
    python/paddle/incubate/nn/functional/swiglu — fused op)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return silu(x) * y


# ---------------------------------------------------------------------------
# linear / embedding (reference: functional/common.py, functional/input.py)
# ---------------------------------------------------------------------------

def linear(x, weight, bias=None):
    """x @ weight (+ bias). Weight layout [in, out], matching the reference
    (python/paddle/nn/functional/common.py:linear)."""
    from ..amp.auto_cast import maybe_cast_inputs
    x, weight, bias = maybe_cast_inputs("linear", x, weight, bias)
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


def embedding(ids, weight, padding_idx: Optional[int] = None):
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros_like(out), out)
    return out


def one_hot(x, num_classes: int, dtype=jnp.float32):
    return jax.nn.one_hot(x, num_classes, dtype=dtype)


# ---------------------------------------------------------------------------
# dropout (reference: functional/common.py:dropout; RNG semantics follow
# fleet/layers/mpu/random.py — "local" stream for TP regions)
# ---------------------------------------------------------------------------

def dropout(x, p: float = 0.5, axis=None, training: bool = True,
            mode: str = "upscale_in_train", rng_name: str = GLOBAL_STREAM):
    """``axis`` (reference: functional/common.py dropout): the mask is
    drawn only along the listed axes and broadcast over the rest (e.g.
    axis=0 drops whole rows). ``downscale_in_infer`` keeps train outputs
    unscaled and multiplies by (1-p) at inference."""
    if mode not in ("upscale_in_train", "downscale_in_infer"):
        raise ValueError(f"mode must be 'upscale_in_train'|"
                         f"'downscale_in_infer', got {mode!r}")
    keep = 1.0 - p
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            return (x * keep).astype(x.dtype)
        return x
    key = rng_tracker().next_key(rng_name)
    if axis is None:
        mask_shape = x.shape
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        axes = tuple(a + x.ndim if a < 0 else a for a in axes)
        if any(a < 0 or a >= x.ndim for a in axes):
            raise ValueError(f"dropout axis {axis} out of range for "
                             f"rank-{x.ndim} input")
        mask_shape = tuple(s if i in axes else 1
                           for i, s in enumerate(x.shape))
    mask = jax.random.bernoulli(key, keep, mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


# ---------------------------------------------------------------------------
# normalization (reference: functional/norm.py + fused kernels under
# paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu)
# ---------------------------------------------------------------------------

def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon: float = 1e-5):
    from ..ops import norm as _norm_ops
    return _norm_ops.layer_norm(x, weight, bias, epsilon)


def rms_norm(x, weight=None, epsilon: float = 1e-6):
    from ..ops import norm as _norm_ops
    return _norm_ops.rms_norm(x, weight, epsilon)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.9, epsilon: float = 1e-5,
               data_format: str = "NCHW"):
    """Inference-style batch norm over N(+spatial) dims. Returns
    (out, new_mean, new_var) when training so the Layer can update buffers."""
    axis = 1 if data_format == "NCHW" else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    if training:
        mean = jnp.mean(x.astype(jnp.float32), axis=reduce_axes)
        var = jnp.var(x.astype(jnp.float32), axis=reduce_axes)
        new_mean = momentum * running_mean + (1 - momentum) * mean
        new_var = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    xn = (x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + epsilon)
    if weight is not None:
        xn = xn * weight.reshape(shape)
    if bias is not None:
        xn = xn + bias.reshape(shape)
    xn = xn.astype(x.dtype)
    if training:
        return xn, new_mean.astype(running_mean.dtype), new_var.astype(running_var.dtype)
    return xn


def group_norm(x, num_groups: int, weight=None, bias=None, epsilon: float = 1e-5,
               data_format: str = "NCHW"):
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xg = x.reshape(n, num_groups, c // num_groups, *spatial).astype(jnp.float32)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + epsilon)
    out = xg.reshape(x.shape)
    shape = [1, c] + [1] * len(spatial)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    out = out.astype(x.dtype)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


def normalize(x, p: float = 2, axis: int = 1, epsilon: float = 1e-12):
    norm = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, epsilon)


# ---------------------------------------------------------------------------
# conv / pooling (reference: functional/conv.py, functional/pooling.py —
# these map directly onto lax.conv_general_dilated / reduce_window which XLA
# tiles onto the MXU)
# ---------------------------------------------------------------------------

def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1,
           data_format: str = "NCHW"):
    """weight layout [out_c, in_c/groups, kh, kw] (reference conv2d layout)."""
    from ..amp.auto_cast import maybe_cast_inputs
    x, weight, bias = maybe_cast_inputs("conv2d", x, weight, bias)
    stride = _norm_tuple(stride, 2)
    dilation = _norm_tuple(dilation, 2)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _norm_tuple(padding, 2)
        pad = [(p[0], p[0]), (p[1], p[1])]
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
                                    else ("NHWC", "OIHW", "NHWC"))
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None)
    out = out.astype(x.dtype)
    if bias is not None:
        bshape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(bshape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups: int = 1,
           data_format: str = "NCL"):
    if data_format == "NLC":
        x = jnp.swapaxes(x, 1, 2)
    x4 = x[..., None]  # NCL -> NCL1
    w4 = weight[..., None]
    s = _norm_tuple(stride, 1)
    d = _norm_tuple(dilation, 1)
    p = padding if isinstance(padding, str) else _norm_tuple(padding, 1)
    pad2 = p if isinstance(p, str) else (p[0], 0)
    out = conv2d(x4, w4, bias, stride=(s[0], 1), padding=pad2, dilation=(d[0], 1),
                 groups=groups, data_format="NCHW")[..., 0]
    if data_format == "NLC":
        out = jnp.swapaxes(out, 1, 2)
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups: int = 1, data_format: str = "NCHW"):
    """weight layout [in_c, out_c/groups, kh, kw] (reference layout)."""
    stride = _norm_tuple(stride, 2)
    dilation = _norm_tuple(dilation, 2)
    p = _norm_tuple(padding, 2)
    op = _norm_tuple(output_padding, 2)
    kh, kw = weight.shape[2], weight.shape[3]
    # transposed conv = lhs-dilated conv with flipped kernel
    pad = [
        (dilation[0] * (kh - 1) - p[0], dilation[0] * (kh - 1) - p[0] + op[0]),
        (dilation[1] * (kw - 1) - p[1], dilation[1] * (kw - 1) - p[1] + op[1]),
    ]
    w = jnp.flip(weight, axis=(2, 3))
    if groups > 1:
        ic, ocg = weight.shape[0], weight.shape[1]
        w = w.reshape(groups, ic // groups, ocg, kh, kw)
        w = jnp.moveaxis(w, 2, 1).reshape(groups * ocg, ic // groups, kh, kw)
    else:
        w = jnp.swapaxes(w, 0, 1)
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
                                    else ("NHWC", "OIHW", "NHWC"))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups)
    out = out.astype(x.dtype)
    if bias is not None:
        bshape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(bshape)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0,
               return_mask: bool = False, ceil_mode: bool = False,
               data_format: str = "NCHW", name=None):
    k = _norm_tuple(kernel_size, 2)
    s = _norm_tuple(stride if stride is not None else kernel_size, 2)
    p = _norm_tuple(padding, 2)
    if data_format == "NCHW":
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    if ceil_mode and data_format == "NCHW":
        # extend the high-side padding so output dims use ceil division
        # (reduce_window pads with init, which max ignores)
        h_in, w_in = x.shape[2], x.shape[3]
        def hi_extra(n_, k_, s_, p_):
            out_c = -(-(n_ + 2 * p_ - k_) // s_) + 1
            return max((out_c - 1) * s_ + k_ - n_ - 2 * p_, 0)
        pads = ((0, 0), (0, 0),
                (p[0], p[0] + hi_extra(h_in, k[0], s[0], p[0])),
                (p[1], p[1] + hi_extra(w_in, k[1], s[1], p[1])))
    elif ceil_mode:
        raise NotImplementedError("ceil_mode supports NCHW")
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    out = lax.reduce_window(x, init, lax.max, window, strides, pads)
    if not return_mask:
        return out
    # argmax indices into the flattened H*W plane (reference mask
    # contract, max_pool2d_with_index kernel): extract windows, take the
    # in-window argmax, map back to global coordinates. Indices are
    # computed in float32 precision (ties beyond 2^24 in integer inputs
    # may pick an equal-valued-in-f32 neighbor).
    if data_format != "NCHW":
        raise NotImplementedError("return_mask supports NCHW")
    n, c, h, w = x.shape
    # pad with -inf OURSELVES: the patches op pads with zeros, which (a)
    # diverges from reduce_window's -inf when a window is all-negative
    # and (b) lets argmax select a padding cell (out-of-range index)
    # large FINITE sentinel: the patches op is a one-hot convolution and
    # -inf * 0 would poison whole windows with NaN
    xp = jnp.pad(x.astype(jnp.float32),
                 ((0, 0), (0, 0), pads[2], pads[3]),
                 constant_values=-3.0e38)
    patches = lax.conv_general_dilated_patches(
        xp, filter_shape=k, window_strides=s, padding=[(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ho, wo = patches.shape[-2:]
    patches = patches.reshape(n, c, k[0] * k[1], ho, wo)
    local = jnp.argmax(patches, axis=2)          # [n, c, ho, wo]
    oy = jnp.arange(ho)[:, None]
    ox = jnp.arange(wo)[None, :]
    gy = oy * s[0] + local // k[1] - p[0]        # padded -> input frame
    gx = ox * s[1] + local % k[1] - p[1]
    mask = (gy * w + gx).astype(jnp.int32)
    return out, mask


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format: str = "NCHW",
               exclusive: bool = True):
    k = _norm_tuple(kernel_size, 2)
    s = _norm_tuple(stride if stride is not None else kernel_size, 2)
    p = _norm_tuple(padding, 2)
    if data_format == "NCHW":
        window = (1, 1, k[0], k[1])
        strides = (1, 1, s[0], s[1])
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1, k[0], k[1], 1)
        strides = (1, s[0], s[1], 1)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    summed = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, window, strides, pads)
    if exclusive and (p[0] or p[1]):
        ones = jnp.ones(x.shape, jnp.float32)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        out = summed / counts
    else:
        out = summed / (k[0] * k[1])
    return out.astype(x.dtype)


def adaptive_avg_pool2d(x, output_size, data_format: str = "NCHW"):
    out_hw = _norm_tuple(output_size, 2)
    if data_format == "NCHW":
        h, w = x.shape[2], x.shape[3]
    else:
        h, w = x.shape[1], x.shape[2]
    if h % out_hw[0] == 0 and w % out_hw[1] == 0:
        k = (h // out_hw[0], w // out_hw[1])
        return avg_pool2d(x, k, stride=k, padding=0, data_format=data_format)
    # general case: mean over computed bins (rare; small outputs)
    axis_h = 2 if data_format == "NCHW" else 1
    outs = []
    for i in range(out_hw[0]):
        h0, h1 = (i * h) // out_hw[0], -(-((i + 1) * h) // out_hw[0])
        row = []
        for j in range(out_hw[1]):
            w0, w1 = (j * w) // out_hw[1], -(-((j + 1) * w) // out_hw[1])
            sl = [slice(None)] * x.ndim
            sl[axis_h] = slice(h0, h1)
            sl[axis_h + 1] = slice(w0, w1)
            row.append(jnp.mean(x[tuple(sl)], axis=(axis_h, axis_h + 1)))
        outs.append(jnp.stack(row, axis=-1))
    out = jnp.stack(outs, axis=-2)
    if data_format == "NCHW":
        return out
    return jnp.moveaxis(out, 1, -1)


def pad(x, paddings, mode: str = "constant", value: float = 0.0,
        data_format: str = "NCHW"):
    """paddings: flat [before,after] pairs for the trailing dims (paddle
    convention for conv-style pads) or full per-dim list of pairs."""
    if isinstance(paddings[0], (list, tuple)):
        cfg = [tuple(p) for p in paddings]
    else:
        # flat [left,right,(top,bottom,...)] pairs apply to the spatial dims,
        # last spatial dim first (paddle convention: [W, H, D] order)
        n_spec = len(paddings) // 2
        pairs = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(n_spec)]
        cfg = [(0, 0)] * x.ndim
        if n_spec == x.ndim:
            # full-rank flat list: pads first dim → last dim (paddle constant
            # mode with len(pad) == 2*ndim)
            cfg = pairs
        else:
            if x.ndim >= 3 and data_format.startswith("NC"):  # NCL/NCHW/NCDHW
                spatial_dims = list(range(2, x.ndim))
            elif x.ndim >= 3:                                 # NLC/NHWC/NDHWC
                spatial_dims = list(range(1, x.ndim - 1))
            else:  # low-rank tensors: pad trailing dims, last dim first
                spatial_dims = list(range(x.ndim))
            for i, dim in enumerate(reversed(spatial_dims[-n_spec:])):
                cfg[dim] = pairs[i]
    if mode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, cfg, mode=jmode)


def interpolate(x, size=None, scale_factor=None, mode: str = "nearest",
                align_corners: bool = False, data_format: str = "NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
    else:
        n, h, w, c = x.shape
    if size is None:
        sf = _norm_tuple(scale_factor, 2)
        size = (int(h * sf[0]), int(w * sf[1]))
    size = _norm_tuple(size, 2)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]
    if data_format == "NCHW":
        out = jax.image.resize(x, (n, c, size[0], size[1]), method=method)
    else:
        out = jax.image.resize(x, (n, size[0], size[1], c), method=method)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# losses (reference: python/paddle/nn/functional/loss.py)
# ---------------------------------------------------------------------------

def _reduce(loss, reduction: str):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input=None, label=None, weight=None,
                  ignore_index: int = -100, reduction: str = "mean",
                  soft_label: bool = False, label_smoothing: float = 0.0,
                  axis: int = -1, use_softmax: bool = True,
                  logits=None, labels=None):
    """Softmax cross entropy, computed in fp32 with the max-subtraction trick
    (reference: c_softmax_with_cross_entropy / softmax_with_cross_entropy
    kernels, paddle/phi/kernels/funcs/cross_entropy.cu).
    ``use_softmax=False`` treats ``input`` as PROBABILITIES (the reference
    contract): loss is -log(p[label]) with no extra softmax."""
    # static-mode program vars record the op instead of evaluating
    # (reference: the static softmax_with_cross_entropy layer)
    from ..static import _LazyVar, lazy_apply
    if isinstance(input, _LazyVar) or isinstance(label, _LazyVar):
        return lazy_apply(
            cross_entropy, input, label, weight=weight,
            ignore_index=ignore_index, reduction=reduction,
            soft_label=soft_label, label_smoothing=label_smoothing,
            axis=axis, use_softmax=use_softmax, name="cross_entropy")
    # reference kwarg names are input/label; logits/labels kept for the
    # existing in-repo callers
    logits = input if input is not None else logits
    labels = label if label is not None else labels
    logits = logits.astype(jnp.float32)
    if axis != -1 and axis != logits.ndim - 1:
        logits = jnp.moveaxis(logits, axis, -1)
    n_classes = logits.shape[-1]
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=-1)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-30, None))
    if soft_label:
        target = labels.astype(jnp.float32)
        loss = -jnp.sum(target * logp, axis=-1)
        return _reduce(loss, reduction)
    labels = labels.astype(jnp.int32)
    if labels.ndim == logits.ndim:  # [..., 1] style
        labels = labels.squeeze(-1)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1).squeeze(-1)
    if label_smoothing > 0.0:
        smooth = -jnp.mean(logp, axis=-1)
        nll = (1 - label_smoothing) * nll + label_smoothing * smooth
    if weight is not None:
        w = jnp.take(weight, safe_labels)
        nll = nll * w
    nll = jnp.where(valid, nll, 0.0)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        if weight is not None:
            denom = jnp.maximum(jnp.sum(jnp.where(valid, jnp.take(weight, safe_labels), 0.0)), 1e-8)
        return jnp.sum(nll) / denom
    return _reduce(nll, reduction)


softmax_with_cross_entropy = cross_entropy


def nll_loss(log_probs, labels, weight=None, ignore_index: int = -100,
             reduction: str = "mean"):
    labels = labels.astype(jnp.int32)
    if labels.ndim == log_probs.ndim and labels.shape[-1] == 1:
        labels = labels.squeeze(-1)     # reference accepts [N, 1] labels
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    nll = -jnp.take_along_axis(log_probs, safe[..., None], axis=-1).squeeze(-1)
    if weight is not None:
        nll = nll * jnp.take(weight, safe)
    nll = jnp.where(valid, nll, 0.0)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return jnp.sum(nll) / denom
    return _reduce(nll, reduction)


def mse_loss(input, label, reduction: str = "mean"):
    return _reduce((input - label) ** 2, reduction)


def l1_loss(input, label, reduction: str = "mean"):
    return _reduce(jnp.abs(input - label), reduction)


def smooth_l1_loss(input, label, reduction: str = "mean", delta: float = 1.0):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction: str = "mean"):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.clip(input, eps, 1.0))
             + (1 - label) * jnp.log(jnp.clip(1 - input, eps, 1.0)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction: str = "mean",
                                     pos_weight=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + max_val + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def kl_div(input, label, reduction: str = "mean"):
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


# ---------------------------------------------------------------------------
# attention (reference: python/paddle/nn/functional/flash_attention.py:146
# flash_attention, :441 scaled_dot_product_attention)
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p: float = 0.0, is_causal: bool = False,
                                 training: bool = True, segment_ids=None):
    """[batch, seq, heads, head_dim] layout, matching the reference API
    (python/paddle/nn/functional/flash_attention.py:441). Dispatches to the
    Pallas flash-attention kernel on TPU via paddle_tpu.ops.attention.

    ``segment_ids`` ([b, s] ints or a (q_seg, kv_seg) pair) restricts
    attention to equal-id positions — the packed-sequence / varlen path
    (reference: flash_attention.py's flash_attn_varlen surface)."""
    from ..amp.auto_cast import maybe_cast_inputs
    query, key, value = maybe_cast_inputs("attention", query, key, value)
    from ..ops import attention as attn_ops
    return attn_ops.flash_attention(query, key, value, attn_mask=attn_mask,
                                    dropout_p=dropout_p if training else 0.0,
                                    causal=is_causal, segment_ids=segment_ids)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Attention restricted to a per-(batch, head) CSR sparsity pattern
    (reference: python/paddle/nn/functional/sparse_attention.py:1, kernel
    phi/kernels/gpu/sparse_attention — CUDA-only there; here an XLA
    composition: the CSR pattern scatters into a boolean mask and the
    masked softmax runs on the MXU. Correct for any pattern; for the
    block-sparse patterns that actually pay off on TPU, prefer the flash
    kernel's segment_ids or a dense mask).

    query/key/value: [B, H, S, D]; sparse_csr_offset: [B, H, S+1] int32;
    sparse_csr_columns: [B, H, nnz] int32. Optional key_padding_mask
    [B, S] and attn_mask [S, S] follow the reference convention:
    value 0 masks the position. Returns [B, H, S, D].
    """
    q = jnp.asarray(query)
    k = jnp.asarray(key)
    v = jnp.asarray(value)
    offset = jnp.asarray(sparse_csr_offset, jnp.int32)
    columns = jnp.asarray(sparse_csr_columns, jnp.int32)
    B, H, S, D = q.shape
    nnz = columns.shape[-1]
    scale = 1.0 / math.sqrt(D)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale

    def one_mask(off, cols):
        # row of the j-th stored element = # of offset entries <= j, minus 1
        j = jnp.arange(nnz, dtype=jnp.int32)
        rows = jnp.searchsorted(off, j, side="right") - 1
        # rectangular [B, H, nnz] storage pads ragged heads: entries past
        # this head's true nnz (off[-1]) must not scatter anywhere — route
        # them out of bounds and drop
        rows = jnp.where(j < off[-1], jnp.clip(rows, 0, S - 1), S)
        return jnp.zeros((S, S), bool).at[rows, cols].set(True, mode="drop")

    mask = jax.vmap(jax.vmap(one_mask))(offset, columns)      # [B,H,S,S]
    if key_padding_mask is not None:
        kpm = jnp.asarray(key_padding_mask) != 0               # [B, S]
        mask = mask & kpm[:, None, None, :]
    if attn_mask is not None:
        am = jnp.asarray(attn_mask) != 0                       # [S, S]
        mask = mask & am[None, None]
    logits = jnp.where(mask, logits, -jnp.inf)
    # rows with an empty pattern produce zeros, not NaN
    has_any = jnp.any(mask, axis=-1, keepdims=True)
    p = jax.nn.softmax(jnp.where(has_any, logits, 0.0), axis=-1)
    p = jnp.where(has_any, p, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def flash_attention(query, key, value, dropout: float = 0.0, causal: bool = False,
                    return_softmax: bool = False, training: bool = True):
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal, training=training)
    if return_softmax:
        return out, None
    return out, None


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

def label_smooth(label, epsilon: float = 0.1):
    n = label.shape[-1]
    return (1 - epsilon) * label + epsilon / n


def cosine_similarity(x1, x2, axis: int = 1, eps: float = 1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    p = _norm_tuple(paddings, 2)
    d = _norm_tuple(dilations, 2)
    n, c, h, w = x.shape
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])], rhs_dilation=d,
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (c * k[0] * k[1], c, k[0], k[1]), ("NCHW", "OIHW", "NCHW")))
    return patches.reshape(n, c * k[0] * k[1], -1)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups: int = 1, data_format: str = "NCDHW"):
    """weight [out_c, in_c/groups, kd, kh, kw] (reference conv3d)."""
    from ..amp.auto_cast import maybe_cast_inputs
    x, weight, bias = maybe_cast_inputs("conv3d", x, weight, bias)
    stride = _norm_tuple(stride, 3)
    dilation = _norm_tuple(dilation, 3)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _norm_tuple(padding, 3)
        pad = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW"
        else ("NDHWC", "OIDHW", "NDHWC"))
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None)
    out = out.astype(x.dtype)
    if bias is not None:
        bshape = [1, -1, 1, 1, 1] if data_format == "NCDHW" else [1, 1, 1, 1, -1]
        out = out + bias.reshape(bshape)
    return out


def pixel_shuffle(x, upscale_factor: int, data_format: str = "NCHW"):
    """[N, C*r^2, H, W] → [N, C, H*r, W*r] (reference pixel_shuffle)."""
    r = upscale_factor
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, c, h, w = x.shape
    if c % (r * r):
        raise ValueError(f"channels {c} not divisible by {r}^2")
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    out = x.reshape(n, c // (r * r), h * r, w * r)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


def pixel_unshuffle(x, downscale_factor: int, data_format: str = "NCHW"):
    r = downscale_factor
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    out = x.reshape(n, c * r * r, h // r, w // r)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


# -- long tail (round-3 parity batch): activations, 1d/3d/adaptive pooling,
#    unpool, grid ops, conv transposes, loss family remainder ---------------
from .functional_extras import *   # noqa: F401,F403,E402
