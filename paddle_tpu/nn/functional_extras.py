"""nn.functional long tail: activations, pooling (1d/3d/adaptive/unpool),
spatial ops (grid_sample/affine_grid/fold), and the loss family remainder.

Reference: python/paddle/nn/functional/{activation.py,pooling.py,vision.py,
common.py,loss.py,distance.py} — TPU re-design notes inline: adaptive pools
use a static [out, L] weight/mask matrix (MXU-friendly, exact for any
size ratio); max-pool masks come from conv_general_dilated_patches; CTC is
optax's log-domain recursion; RNNT is a lax.scan alpha recursion.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.rng import rng_tracker, GLOBAL_STREAM, LOCAL_STREAM
from .functional import (_norm_tuple, _reduce, dropout, interpolate,
                         log_softmax, sigmoid, softmax, softplus, tanh,
                         binary_cross_entropy, cosine_similarity, relu, elu,
                         leaky_relu)


def _key():
    return rng_tracker().next_key(GLOBAL_STREAM)


# ---------------------------------------------------------------------------
# activations (reference: nn/functional/activation.py)
# ---------------------------------------------------------------------------

def celu(x, alpha: float = 1.0):
    return jax.nn.celu(jnp.asarray(x), alpha=alpha)


def selu(x, scale: float = 1.0507009873554805,
         alpha: float = 1.6732632423543772):
    arr = jnp.asarray(x)
    return scale * jnp.where(arr > 0, arr, alpha * jnp.expm1(arr))


def log_sigmoid(x):
    return jax.nn.log_sigmoid(jnp.asarray(x))


def hardshrink(x, threshold: float = 0.5):
    arr = jnp.asarray(x)
    return jnp.where(jnp.abs(arr) > threshold, arr, 0.0)


def softshrink(x, threshold: float = 0.5):
    arr = jnp.asarray(x)
    return jnp.where(arr > threshold, arr - threshold,
                     jnp.where(arr < -threshold, arr + threshold, 0.0))


def hardtanh(x, min: float = -1.0, max: float = 1.0):
    return jnp.clip(jnp.asarray(x), min, max)


def softsign(x):
    arr = jnp.asarray(x)
    return arr / (1.0 + jnp.abs(arr))


def tanhshrink(x):
    arr = jnp.asarray(x)
    return arr - jnp.tanh(arr)


def thresholded_relu(x, threshold: float = 1.0, value: float = 0.0):
    arr = jnp.asarray(x)
    return jnp.where(arr > threshold, arr, value)


def maxout(x, groups: int, axis: int = 1):
    arr = jnp.asarray(x)
    axis = axis % arr.ndim
    c = arr.shape[axis]
    if c % groups:
        raise ValueError(f"maxout: channels {c} not divisible by {groups}")
    new = arr.shape[:axis] + (c // groups, groups) + arr.shape[axis + 1:]
    return jnp.max(arr.reshape(new), axis=axis + 1)


def prelu(x, weight, data_format: str = "NCHW"):
    arr = jnp.asarray(x)
    w = jnp.asarray(weight)
    if w.size > 1 and arr.ndim > 1:
        ch_axis = 1 if data_format == "NCHW" else arr.ndim - 1
        shape = [1] * arr.ndim
        shape[ch_axis] = w.size
        w = w.reshape(shape)
    return jnp.where(arr > 0, arr, w * arr)


def rrelu(x, lower: float = 1.0 / 8.0, upper: float = 1.0 / 3.0,
          training: bool = False):
    arr = jnp.asarray(x)
    if training:
        a = jax.random.uniform(_key(), arr.shape, jnp.float32, lower, upper)
        a = a.astype(arr.dtype)
    else:
        a = (lower + upper) / 2.0
    return jnp.where(arr >= 0, arr, a * arr)


def gumbel_softmax(x, temperature: float = 1.0, hard: bool = False,
                   axis: int = -1):
    arr = jnp.asarray(x)
    g = jax.random.gumbel(_key(), arr.shape, jnp.float32).astype(arr.dtype)
    y = softmax((arr + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.where(
            jnp.arange(arr.shape[axis]).reshape(
                [-1 if i == axis % arr.ndim else 1 for i in range(arr.ndim)])
            == idx, 1.0, 0.0).astype(y.dtype)
        y = lax.stop_gradient(onehot - y) + y   # straight-through
    return y


# inplace-spelled aliases (value semantics; see tensor/inplace.py)
def relu_(x):
    return relu(x)


def elu_(x, alpha: float = 1.0):
    return elu(x, alpha)


def hardtanh_(x, min: float = -1.0, max: float = 1.0):
    return hardtanh(x, min, max)


def leaky_relu_(x, negative_slope: float = 0.01):
    return leaky_relu(x, negative_slope)


def softmax_(x, axis: int = -1):
    return softmax(x, axis)


def tanh_(x):
    return tanh(x)


def thresholded_relu_(x, threshold: float = 1.0, value: float = 0.0):
    return thresholded_relu(x, threshold, value)


# ---------------------------------------------------------------------------
# pooling 1d/3d + adaptive + unpool (reference: nn/functional/pooling.py)
# ---------------------------------------------------------------------------

def _pool_nd(x, nd, kernel_size, stride, padding, reducer, init,
             channel_last: bool, ceil_mode: bool = False):
    k = _norm_tuple(kernel_size, nd)
    s = _norm_tuple(stride if stride is not None else kernel_size, nd)
    p = _norm_tuple(padding, nd)
    spatial = x.shape[1:1 + nd] if channel_last else x.shape[2:2 + nd]
    # ceil_mode: extend the trailing pad so the last partial window counts
    extra = tuple(
        ((-(-(spatial[i] + 2 * p[i] - k[i]) // s[i]) * s[i] + k[i])
         - (spatial[i] + 2 * p[i])) if ceil_mode else 0
        for i in range(nd))
    extra = tuple(max(0, e) for e in extra)
    sp_pads = tuple((p[i], p[i] + extra[i]) for i in range(nd))
    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = ((0, 0),) + sp_pads + ((0, 0),)
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = ((0, 0), (0, 0)) + sp_pads
    return lax.reduce_window(x, init, reducer, window, strides, pads)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format: str = "NCL"):
    arr = jnp.asarray(x)
    init = (-jnp.inf if jnp.issubdtype(arr.dtype, jnp.floating)
            else jnp.iinfo(arr.dtype).min)
    out = _pool_nd(arr, 1, kernel_size, stride, padding, lax.max, init,
                   data_format == "NLC", ceil_mode)
    if return_mask:
        return out, _pool_argmax(arr, kernel_size, stride, padding,
                                 data_format == "NLC", ceil_mode)
    return out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format: str = "NCL"):
    arr = jnp.asarray(x)
    summed = _pool_nd(arr, 1, kernel_size, stride, padding, lax.add, 0.0,
                      data_format == "NLC", ceil_mode)
    if exclusive and (padding != 0 or ceil_mode):
        ones = jnp.ones_like(arr)
        count = _pool_nd(ones, 1, kernel_size, stride, padding, lax.add, 0.0,
                         data_format == "NLC", ceil_mode)
        return summed / count
    k = _norm_tuple(kernel_size, 1)
    return summed / k[0]


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format: str = "NCDHW"):
    arr = jnp.asarray(x)
    init = (-jnp.inf if jnp.issubdtype(arr.dtype, jnp.floating)
            else jnp.iinfo(arr.dtype).min)
    out = _pool_nd(arr, 3, kernel_size, stride, padding, lax.max, init,
                   data_format == "NDHWC", ceil_mode)
    if return_mask:
        return out, _pool_argmax(arr, _norm_tuple(kernel_size, 3), stride,
                                 padding, data_format == "NDHWC", ceil_mode)
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format: str = "NCDHW"):
    arr = jnp.asarray(x)
    summed = _pool_nd(arr, 3, kernel_size, stride, padding, lax.add, 0.0,
                      data_format == "NDHWC", ceil_mode)
    if exclusive and (padding != 0 or ceil_mode):
        count = _pool_nd(jnp.ones_like(arr), 3, kernel_size, stride, padding,
                         lax.add, 0.0, data_format == "NDHWC", ceil_mode)
        return summed / count
    k = _norm_tuple(kernel_size, 3)
    return summed / (k[0] * k[1] * k[2])


def _pool_argmax(x, kernel, stride, padding, channel_last: bool,
                 ceil_mode: bool = False):
    """Flat (per-plane) argmax indices of each pooling window, the layout
    max_unpool consumes (reference returns int indices into the padded-less
    input plane). Works for 1-3 spatial dims via dilated patches."""
    if channel_last:
        perm = (0, x.ndim - 1) + tuple(range(1, x.ndim - 1))
        x = jnp.transpose(x, perm)
    nd = x.ndim - 2
    k = _norm_tuple(kernel, nd)
    s = _norm_tuple(stride if stride is not None else kernel, nd)
    p = _norm_tuple(padding, nd)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    # trailing extra pad mirrors _pool_nd's ceil_mode so mask and values
    # agree on the output grid
    extra = tuple(
        max(0, (-(-(spatial[i] + 2 * p[i] - k[i]) // s[i]) * s[i] + k[i])
            - (spatial[i] + 2 * p[i])) if ceil_mode else 0
        for i in range(nd))
    sp_pads = tuple((p[i], p[i] + extra[i]) for i in range(nd))
    # index plane, same padding as the values, pad value -1 never wins
    flat_idx = jnp.arange(int(np.prod(spatial)), dtype=jnp.float32).reshape(
        spatial)
    big_neg = jnp.float32(-1e30)
    # finite pad: the patch extraction is an identity-kernel conv, and
    # 0 * -inf = nan would poison whole windows; ip<0 masks pads anyway
    xp = jnp.pad(x, ((0, 0), (0, 0)) + sp_pads, constant_values=-1e30)
    ip = jnp.pad(flat_idx, sp_pads, constant_values=-1)
    # extract windows of both value and index and argmax per window
    vpat = lax.conv_general_dilated_patches(
        xp, filter_shape=k, window_strides=s, padding="VALID")
    # vpat: [n, c*prod(k), *out_spatial]
    out_spatial = vpat.shape[2:]
    kprod = int(np.prod(k))
    vpat = vpat.reshape(n, c, kprod, *out_spatial)
    ipat = lax.conv_general_dilated_patches(
        ip[None, None], filter_shape=k, window_strides=s, padding="VALID")
    ipat = ipat.reshape(1, 1, kprod, *out_spatial)
    arg = jnp.argmax(jnp.where(ipat < 0, big_neg, vpat), axis=2,
                     keepdims=True)
    idx = jnp.take_along_axis(jnp.broadcast_to(
        ipat, (n, c, kprod) + out_spatial), arg, axis=2)[:, :, 0]
    return idx.astype(jnp.int32)


def _max_unpool_nd(x, indices, nd, kernel_size, stride=None, padding=0,
                   output_size=None, data_format="NCHW"):
    arr = jnp.asarray(x)
    idx = jnp.asarray(indices).astype(jnp.int32)
    k = _norm_tuple(kernel_size, nd)
    s = _norm_tuple(stride if stride is not None else kernel_size, nd)
    p = _norm_tuple(padding, nd)
    in_spatial = arr.shape[2:]
    if output_size is None:
        out_spatial = tuple((in_spatial[i] - 1) * s[i] - 2 * p[i] + k[i]
                            for i in range(nd))
    else:
        out_spatial = tuple(output_size[-nd:])
    n, c = arr.shape[0], arr.shape[1]
    plane = int(np.prod(out_spatial))
    flat = jnp.zeros((n, c, plane), arr.dtype)
    flat = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].add(arr.reshape(n, c, -1))
    return flat.reshape(n, c, *out_spatial)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool_nd(x, indices, 1, kernel_size, stride, padding,
                          output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool_nd(x, indices, 2, kernel_size, stride, padding,
                          output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool_nd(x, indices, 3, kernel_size, stride, padding,
                          output_size, data_format)


def _adaptive_weights(in_size: int, out_size: int):
    """Static [out, in] averaging matrix: row i covers
    [floor(i*L/out), ceil((i+1)*L/out)) with uniform weights — exact for
    non-divisible ratios, and the pooling becomes one (MXU) matmul."""
    w = np.zeros((out_size, in_size), np.float32)
    for i in range(out_size):
        lo = (i * in_size) // out_size
        hi = -(-((i + 1) * in_size) // out_size)
        w[i, lo:hi] = 1.0 / (hi - lo)
    return jnp.asarray(w)


def _adaptive_mask(in_size: int, out_size: int):
    m = np.zeros((out_size, in_size), bool)
    for i in range(out_size):
        lo = (i * in_size) // out_size
        hi = -(-((i + 1) * in_size) // out_size)
        m[i, lo:hi] = True
    return jnp.asarray(m)


def _adaptive_avg(x, out_sizes, spatial_axes):
    for ax, out in zip(spatial_axes, out_sizes):
        w = _adaptive_weights(x.shape[ax], out)
        x = jnp.moveaxis(jnp.tensordot(x, w, axes=[[ax], [1]]), -1, ax)
    return x


def _adaptive_max(x, out_sizes, spatial_axes, return_mask=False):
    idx_planes = []
    for ax, out in zip(spatial_axes, out_sizes):
        m = _adaptive_mask(x.shape[ax], out)                 # [out, in]
        moved = jnp.moveaxis(x, ax, -1)[..., None, :]        # [..., 1, in]
        masked = jnp.where(m, moved, -jnp.inf)               # [..., out, in]
        if return_mask:
            idx_planes.append(jnp.argmax(masked, axis=-1))
        x = jnp.moveaxis(jnp.max(masked, axis=-1), -1, ax)
    return (x, idx_planes) if return_mask else x


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_avg(jnp.asarray(x), _norm_tuple(output_size, 1), (2,))


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    axes = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    return _adaptive_avg(jnp.asarray(x), _norm_tuple(output_size, 3), axes)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_max(jnp.asarray(x), _norm_tuple(output_size, 1), (2,),
                        return_mask)
    if return_mask:
        return out[0], out[1][0].astype(jnp.int32)
    return out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    arr = jnp.asarray(x)
    sizes = _norm_tuple(output_size, 2)
    if not return_mask:
        return _adaptive_max(arr, sizes, (2, 3))
    # flat-plane indices (H*W) like max_pool's mask layout
    h, w = arr.shape[2], arr.shape[3]
    mh, mw = _adaptive_mask(h, sizes[0]), _adaptive_mask(w, sizes[1])
    # [n, c, oh, ow, h, w] masked view is too big; do it separably:
    # argmax over w within each (oh row band, ow col band) needs joint
    # search, so build [oh, h] x [ow, w] band mask lazily per output cell
    vals = _adaptive_max(arr, sizes, (2, 3))
    band = mh[:, None, :, None] & mw[None, :, None, :]  # [oh, ow, h, w]
    scores = jnp.where(band, arr[:, :, None, None, :, :], -jnp.inf)
    flat = scores.reshape(*scores.shape[:4], h * w)
    idx = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    return vals, idx


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    arr = jnp.asarray(x)
    sizes = _norm_tuple(output_size, 3)
    if not return_mask:
        return _adaptive_max(arr, sizes, (2, 3, 4))
    # flat D*H*W indices (paddle mask layout): joint band search
    d, h, w = arr.shape[2:]
    md = _adaptive_mask(d, sizes[0])
    mh = _adaptive_mask(h, sizes[1])
    mw = _adaptive_mask(w, sizes[2])
    vals = _adaptive_max(arr, sizes, (2, 3, 4))
    band = (md[:, None, None, :, None, None]
            & mh[None, :, None, None, :, None]
            & mw[None, None, :, None, None, :])    # [od, oh, ow, d, h, w]
    scores = jnp.where(band, arr[:, :, None, None, None, :, :, :], -jnp.inf)
    flat = scores.reshape(*scores.shape[:5], d * h * w)
    idx = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    return vals, idx


# ---------------------------------------------------------------------------
# spatial / vision ops (reference: nn/functional/vision.py, common.py)
# ---------------------------------------------------------------------------

def channel_shuffle(x, groups: int, data_format: str = "NCHW"):
    arr = jnp.asarray(x)
    if data_format == "NCHW":
        n, c, h, w = arr.shape
        if c % groups:
            raise ValueError(f"channels {c} not divisible by groups {groups}")
        return arr.reshape(n, groups, c // groups, h, w).swapaxes(1, 2) \
            .reshape(n, c, h, w)
    n, h, w, c = arr.shape
    return arr.reshape(n, h, w, groups, c // groups).swapaxes(3, 4) \
        .reshape(n, h, w, c)


def zeropad2d(x, padding, data_format: str = "NCHW", name=None):
    p = _norm_tuple(padding, 4)  # [left, right, top, bottom]
    arr = jnp.asarray(x)
    if data_format == "NCHW":
        pads = ((0, 0), (0, 0), (p[2], p[3]), (p[0], p[1]))
    else:
        pads = ((0, 0), (p[2], p[3]), (p[0], p[1]), (0, 0))
    return jnp.pad(arr, pads)


def alpha_dropout(x, p: float = 0.5, training: bool = True):
    """SELU-preserving dropout (reference nn/functional/common.py
    alpha_dropout): dropped units take alpha', then affine-correct."""
    if not training or p == 0.0:
        return jnp.asarray(x)
    arr = jnp.asarray(x)
    alpha = 1.6732632423543772 * 1.0507009873554805
    alpha_p = -alpha
    keep = jax.random.bernoulli(_key(), 1.0 - p, arr.shape)
    a = (1.0 - p + p * alpha_p ** 2 * (1.0 - p)) ** -0.5
    b = -a * alpha_p * p
    return a * jnp.where(keep, arr, alpha_p) + b


def _dropout_channels(x, p, training, spatial_ndim):
    if not training or p == 0.0:
        return jnp.asarray(x)
    arr = jnp.asarray(x)
    mask_shape = arr.shape[:2] + (1,) * spatial_ndim
    keep = jax.random.bernoulli(_key(), 1.0 - p, mask_shape)
    return jnp.where(keep, arr / (1.0 - p), 0.0).astype(arr.dtype)


def dropout2d(x, p: float = 0.5, training: bool = True,
              data_format: str = "NCHW", name=None):
    if data_format != "NCHW":
        arr = jnp.moveaxis(jnp.asarray(x), -1, 1)
        return jnp.moveaxis(_dropout_channels(arr, p, training, 2), 1, -1)
    return _dropout_channels(x, p, training, 2)


def dropout3d(x, p: float = 0.5, training: bool = True,
              data_format: str = "NCDHW", name=None):
    if data_format != "NCDHW":
        arr = jnp.moveaxis(jnp.asarray(x), -1, 1)
        return jnp.moveaxis(_dropout_channels(arr, p, training, 3), 1, -1)
    return _dropout_channels(x, p, training, 3)


def local_response_norm(x, size: int = 5, alpha: float = 1e-4,
                        beta: float = 0.75, k: float = 1.0,
                        data_format: str = "NCHW"):
    arr = jnp.asarray(x)
    ch_axis = 1 if data_format.startswith("NC") else arr.ndim - 1
    sq = jnp.square(arr)
    moved = jnp.moveaxis(sq, ch_axis, -1)
    pad_lo = (size - 1) // 2
    pad_hi = size - 1 - pad_lo
    padded = jnp.pad(moved, [(0, 0)] * (arr.ndim - 1) + [(pad_lo, pad_hi)])
    windows = jnp.stack([padded[..., i:i + moved.shape[-1]]
                         for i in range(size)], axis=-1)
    den = k + alpha / size * jnp.sum(windows, axis=-1)
    return arr / jnp.moveaxis(den, -1, ch_axis) ** beta


def temporal_shift(x, seg_num: int, shift_ratio: float = 0.25,
                   data_format: str = "NCHW"):
    """Shift a fraction of channels one step along the segment (time) axis
    (reference: nn/functional/extension.py temporal_shift)."""
    arr = jnp.asarray(x)
    if data_format == "NHWC":
        arr = jnp.moveaxis(arr, -1, 1)
    nt, c, h, w = arr.shape
    n = nt // seg_num
    v = arr.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.pad(v[:, 1:, :fold], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
    right = jnp.pad(v[:, :-1, fold:2 * fold],
                    ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
    out = jnp.concatenate([left, right, v[:, :, 2 * fold:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    lengths = jnp.asarray(x)
    m = int(maxlen) if maxlen is not None else int(jnp.max(lengths))
    from ..core.dtype import convert_dtype
    return (jnp.arange(m) < lengths[..., None]).astype(convert_dtype(dtype))


def gather_tree(ids, parents):
    """Beam-search ancestry walk (reference: nn/functional/extension.py
    gather_tree / gather_tree_op): follow parent pointers from the last
    step to recover full beams. ids/parents: [T, B, W]."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    T = ids.shape[0]
    w_idx = jnp.arange(ids.shape[2])

    def body(beam, t):
        # beam: [B, W] parent slot at step t+1; emit ids[t] gathered by it
        tok = jnp.take_along_axis(ids[t], beam, axis=1)
        prev = jnp.take_along_axis(parents[t], beam, axis=1)
        return prev, tok

    init = jnp.broadcast_to(w_idx, ids.shape[1:])
    _, toks = lax.scan(body, init, jnp.arange(T - 1, -1, -1))
    return jnp.flip(toks, axis=0)


def affine_grid(theta, out_shape, align_corners: bool = True):
    """theta [n, 2, 3] -> sampling grid [n, h, w, 2] (reference:
    nn/functional/vision.py affine_grid)."""
    theta = jnp.asarray(theta)
    n, _, h, w = [int(s) for s in out_shape]

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = axis_coords(h)
    xs = axis_coords(w)
    gx, gy = jnp.meshgrid(xs, ys)                    # [h, w]
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [h, w, 3]
    return jnp.einsum("hwk,nck->nhwc", base, theta.astype(jnp.float32))


def grid_sample(x, grid, mode: str = "bilinear", padding_mode: str = "zeros",
                align_corners: bool = True):
    """Sample x [n,c,h,w] at grid [n,gh,gw,2] (x,y in [-1,1]) (reference:
    nn/functional/vision.py grid_sample; kernel grid_sample_kernel.cu).
    Gather-based: 4 taps + bilinear weights, vectorized over the grid."""
    arr = jnp.asarray(x)
    g = jnp.asarray(grid).astype(jnp.float32)
    n, c, h, w = arr.shape

    def unnorm(coord, size):
        if align_corners:
            return (coord + 1.0) * 0.5 * (size - 1)
        return ((coord + 1.0) * size - 1.0) * 0.5

    gx = unnorm(g[..., 0], w)                        # [n, gh, gw]
    gy = unnorm(g[..., 1], h)

    def reflect(coord, size):
        if align_corners:
            span = 2.0 * (size - 1)
            if size == 1:
                return jnp.zeros_like(coord)
            coord = jnp.abs(coord) % span
            return jnp.where(coord > size - 1, span - coord, coord)
        span = 2.0 * size
        coord = jnp.abs(coord + 0.5) % span
        return jnp.where(coord > size, span - coord, coord) - 0.5

    if padding_mode == "reflection":
        gx = reflect(gx, w)
        gy = reflect(gy, h)
    if padding_mode in ("border", "reflection"):
        gx = jnp.clip(gx, 0, w - 1)
        gy = jnp.clip(gy, 0, h - 1)

    def tap(ix, iy):
        """Gather arr[n, :, iy, ix] with zero padding for OOB."""
        valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        ni = jnp.arange(n)[:, None, None]
        vals = arr[ni, :, iyc, ixc]                  # [n, gh, gw, c]
        return jnp.where(valid[..., None], vals, 0.0)

    if mode == "nearest":
        out = tap(jnp.round(gx).astype(jnp.int32),
                  jnp.round(gy).astype(jnp.int32))
        return jnp.moveaxis(out, -1, 1).astype(arr.dtype)

    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx = gx - x0
    wy = gy - y0
    out = (tap(x0, y0) * ((1 - wx) * (1 - wy))[..., None]
           + tap(x1, y0) * (wx * (1 - wy))[..., None]
           + tap(x0, y1) * ((1 - wx) * wy)[..., None]
           + tap(x1, y1) * (wx * wy)[..., None])
    return jnp.moveaxis(out, -1, 1).astype(arr.dtype)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im: inverse of unfold (reference: nn/functional/common.py fold).
    x: [n, c*prod(k), L] -> [n, c, H, W] via scatter-add of patches."""
    arr = jnp.asarray(x)
    oh, ow = _norm_tuple(output_sizes, 2)
    kh, kw = _norm_tuple(kernel_sizes, 2)
    sh, sw = _norm_tuple(strides, 2)
    ph, pw = _norm_tuple(paddings, 2)
    dh, dw = _norm_tuple(dilations, 2)
    n, ck, L = arr.shape
    c = ck // (kh * kw)
    nh = (oh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    nw = (ow + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    if nh * nw != L:
        raise ValueError(f"fold: L={L} != expected {nh}*{nw}")
    patches = arr.reshape(n, c, kh, kw, nh, nw)
    # output positions per (ki, li): row = li_h*sh + ki_h*dh - ph
    rows = (np.arange(nh)[None, :] * sh
            + np.arange(kh)[:, None] * dh - ph)     # [kh, nh]
    cols = (np.arange(nw)[None, :] * sw
            + np.arange(kw)[:, None] * dw - pw)     # [kw, nw]
    valid_r = (rows >= 0) & (rows < oh)
    valid_c = (cols >= 0) & (cols < ow)
    rows_c = np.clip(rows, 0, oh - 1)
    cols_c = np.clip(cols, 0, ow - 1)
    mask = (valid_r[:, None, :, None] & valid_c[None, :, None, :])
    patches = jnp.where(mask[None, None], patches, 0.0)
    out = jnp.zeros((n, c, oh, ow), arr.dtype)
    ridx = jnp.asarray(rows_c)[:, None, :, None]     # [kh, 1, nh, 1]
    cidx = jnp.asarray(cols_c)[None, :, None, :]     # [1, kw, 1, nw]
    ridx = jnp.broadcast_to(ridx, (kh, kw, nh, nw))
    cidx = jnp.broadcast_to(cidx, (kh, kw, nh, nw))
    out = out.at[:, :, ridx, cidx].add(patches)
    return out


def upsample(x, size=None, scale_factor=None, mode: str = "nearest",
             align_corners: bool = False, data_format: str = "NCHW",
             name=None):
    return interpolate(x, size=size, scale_factor=scale_factor, mode=mode,
                       align_corners=align_corners, data_format=data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    """Bilinear form out[n, o] = x1[n, i] W[o, i, j] x2[n, j] (reference:
    nn/functional/common.py bilinear)."""
    out = jnp.einsum("ni,oij,nj->no", jnp.asarray(x1), jnp.asarray(weight),
                     jnp.asarray(x2))
    if bias is not None:
        out = out + jnp.asarray(bias).reshape(1, -1)
    return out


def pairwise_distance(x, y, p: float = 2.0, epsilon: float = 1e-6,
                      keepdim: bool = False, name=None):
    diff = jnp.asarray(x) - jnp.asarray(y) + epsilon
    if p == float("inf"):
        out = jnp.max(jnp.abs(diff), axis=-1, keepdims=keepdim)
    else:
        out = jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), axis=-1,
                                keepdims=keepdim), 1.0 / p)
    return out


# ---------------------------------------------------------------------------
# conv transpose 1d/3d (reference: nn/functional/conv.py)
# ---------------------------------------------------------------------------

def _conv_transpose_nd(x, weight, bias, nd, stride, padding, output_padding,
                       dilation, groups, data_format):
    stride = _norm_tuple(stride, nd)
    dilation = _norm_tuple(dilation, nd)
    p = _norm_tuple(padding, nd)
    op = _norm_tuple(output_padding, nd)
    kdims = weight.shape[2:]
    pad = [(dilation[i] * (kdims[i] - 1) - p[i],
            dilation[i] * (kdims[i] - 1) - p[i] + op[i]) for i in range(nd)]
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if groups > 1:
        ic, ocg = weight.shape[0], weight.shape[1]
        w = w.reshape(groups, ic // groups, ocg, *kdims)
        w = jnp.moveaxis(w, 2, 1).reshape(groups * ocg, ic // groups, *kdims)
    else:
        w = jnp.swapaxes(w, 0, 1)
    spatial = "DHW"[3 - nd:]
    fmt_in = "NC" + spatial if data_format.startswith("NC") else \
        "N" + spatial + "C"
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, (fmt_in, "OI" + spatial, fmt_in))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=pad, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups).astype(x.dtype)
    if bias is not None:
        bshape = ([1, -1] + [1] * nd if data_format.startswith("NC")
                  else [1] + [1] * nd + [-1])
        out = out + jnp.asarray(bias).reshape(bshape)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCL", name=None):
    return _conv_transpose_nd(jnp.asarray(x), jnp.asarray(weight), bias, 1,
                              stride, padding, output_padding, dilation,
                              groups, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(jnp.asarray(x), jnp.asarray(weight), bias, 3,
                              stride, padding, output_padding, dilation,
                              groups, data_format)


# ---------------------------------------------------------------------------
# norms
# ---------------------------------------------------------------------------

def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats: bool = True,
                  momentum: float = 0.9, eps: float = 1e-5,
                  data_format: str = "NCHW", name=None):
    """Per-(n, c) spatial normalization (reference: nn/functional/norm.py
    instance_norm)."""
    arr = jnp.asarray(x)
    if data_format.startswith("NC"):
        red = tuple(range(2, arr.ndim))
        ch_shape = [1, -1] + [1] * (arr.ndim - 2)
    else:
        red = tuple(range(1, arr.ndim - 1))
        ch_shape = [1] + [1] * (arr.ndim - 2) + [-1]
    mean = jnp.mean(arr, axis=red, keepdims=True)
    var = jnp.var(arr, axis=red, keepdims=True)
    out = (arr - mean) / jnp.sqrt(var + eps)
    if weight is not None:
        out = out * jnp.asarray(weight).reshape(ch_shape)
    if bias is not None:
        out = out + jnp.asarray(bias).reshape(ch_shape)
    return out.astype(arr.dtype)


# ---------------------------------------------------------------------------
# losses (reference: nn/functional/loss.py)
# ---------------------------------------------------------------------------

def square_error_cost(input, label):
    return jnp.square(jnp.asarray(input) - jnp.asarray(label))


def log_loss(input, label, epsilon: float = 1e-4, name=None):
    p = jnp.asarray(input)
    y = jnp.asarray(label)
    return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)


def soft_margin_loss(input, label, reduction: str = "mean", name=None):
    loss = jnp.log1p(jnp.exp(-jnp.asarray(label) * jnp.asarray(input)))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin: float = 1.0,
                         reduction: str = "mean", name=None):
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin: float = 0.0,
                          reduction: str = "mean", name=None):
    cos = cosine_similarity(jnp.asarray(input1), jnp.asarray(input2), axis=1)
    y = jnp.asarray(label)
    loss = jnp.where(y == 1, 1.0 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin: float = 0.0,
                        reduction: str = "mean", name=None):
    loss = jnp.maximum(
        0.0, -jnp.asarray(label) * (jnp.asarray(input) - jnp.asarray(other))
        + margin)
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input: bool = True,
                     full: bool = False, epsilon: float = 1e-8,
                     reduction: str = "mean", name=None):
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    if log_input:
        loss = jnp.exp(x) - y * x
    else:
        loss = x - y * jnp.log(x + epsilon)
    if full:  # Stirling approximation for y! (reference adds it for y > 1)
        stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
        loss = loss + jnp.where(y > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full: bool = False,
                      epsilon: float = 1e-6, reduction: str = "mean",
                      name=None):
    mu = jnp.asarray(input)
    y = jnp.asarray(label)
    var = jnp.maximum(jnp.asarray(variance), epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
    if full:
        loss = loss + 0.5 * math.log(2 * math.pi)
    return _reduce(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction: str = "mean", name=None):
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
    if weight is not None:
        loss = loss * jnp.asarray(weight)
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


def multi_margin_loss(input, label, p: int = 1, margin: float = 1.0,
                      weight=None, reduction: str = "mean", name=None):
    x = jnp.asarray(input)
    y = jnp.asarray(label).astype(jnp.int32)
    n, c = x.shape
    correct = jnp.take_along_axis(x, y[:, None], axis=1)    # [n, 1]
    diff = jnp.maximum(0.0, margin - correct + x)
    if p != 1:
        diff = diff ** p
    if weight is not None:
        diff = diff * jnp.asarray(weight)[y][:, None]
    onehot = jax.nn.one_hot(y, c, dtype=x.dtype)
    loss = jnp.sum(diff * (1 - onehot), axis=1) / c
    return _reduce(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin: float = 1.0,
                        p: float = 2.0, epsilon: float = 1e-6,
                        swap: bool = False, reduction: str = "mean",
                        name=None):
    d_pos = pairwise_distance(input, positive, p, epsilon)
    d_neg = pairwise_distance(input, negative, p, epsilon)
    if swap:
        d_neg = jnp.minimum(d_neg,
                            pairwise_distance(positive, negative, p, epsilon))
    loss = jnp.maximum(0.0, d_pos - d_neg + margin)
    return _reduce(loss, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None,
                                      margin: float = 1.0,
                                      swap: bool = False,
                                      reduction: str = "mean", name=None):
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.maximum(0.0, d_pos - d_neg + margin)
    return _reduce(loss, reduction)


def npair_loss(anchor, positive, labels, l2_reg: float = 0.002):
    """N-pair loss (reference: nn/functional/loss.py npair_loss): CE over
    anchor·positiveᵀ similarities + L2 on the embeddings."""
    a = jnp.asarray(anchor)
    p = jnp.asarray(positive)
    y = jnp.asarray(labels).reshape(-1)
    logits = a @ p.T                                  # [n, n]
    same = (y[:, None] == y[None, :]).astype(logits.dtype)
    tgt = same / jnp.sum(same, axis=1, keepdims=True)
    ce = jnp.mean(jnp.sum(-tgt * jax.nn.log_softmax(logits, axis=1), axis=1))
    l2 = jnp.mean(jnp.sum(a * a, 1) + jnp.sum(p * p, 1)) * 0.25 * l2_reg
    return ce + l2


def dice_loss(input, label, epsilon: float = 1e-5, name=None):
    """Dice loss over the last (class-prob) axis (reference:
    nn/functional/loss.py dice_loss): label is int class ids."""
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    if y.ndim == x.ndim and y.shape[-1] == 1:
        y = y[..., 0]
    onehot = jax.nn.one_hot(y, x.shape[-1], dtype=x.dtype)
    x = x.reshape(x.shape[0], -1)
    onehot = onehot.reshape(onehot.shape[0], -1)
    inter = jnp.sum(x * onehot, axis=1)
    union = jnp.sum(x, axis=1) + jnp.sum(onehot, axis=1)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha: float = 0.25,
                       gamma: float = 2.0, reduction: str = "sum", name=None):
    x = jnp.asarray(logit)
    y = jnp.asarray(label)
    p = jax.nn.sigmoid(x)
    ce = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / jnp.asarray(normalizer)
    return _reduce(loss, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths,
             blank: int = 0, reduction: str = "mean",
             norm_by_times: bool = False):
    """CTC (reference: nn/functional/loss.py ctc_loss over warpctc). Uses
    optax's log-domain forward recursion; layout adapted from paddle's
    [T, B, V] logits to optax's [B, T, V] + padding masks."""
    import optax
    lp = jnp.asarray(log_probs)
    if lp.ndim != 3:
        raise ValueError("log_probs must be [max_T, batch, vocab]")
    lp_bt = jnp.moveaxis(lp, 0, 1)                   # [B, T, V]
    y = jnp.asarray(labels)                          # [B, U]
    in_len = jnp.asarray(input_lengths).reshape(-1)
    lab_len = jnp.asarray(label_lengths).reshape(-1)
    t_pad = (jnp.arange(lp_bt.shape[1])[None, :] >= in_len[:, None]) \
        .astype(lp_bt.dtype)
    u_pad = (jnp.arange(y.shape[1])[None, :] >= lab_len[:, None]) \
        .astype(lp_bt.dtype)
    per_seq = optax.ctc_loss(lp_bt, t_pad, y, u_pad, blank_id=blank)
    if norm_by_times:
        per_seq = per_seq / jnp.maximum(in_len.astype(per_seq.dtype), 1.0)
    if reduction == "mean":
        # paddle: divide each by its label length, then mean
        return jnp.mean(per_seq / jnp.maximum(lab_len.astype(per_seq.dtype),
                                              1.0))
    return _reduce(per_seq, reduction)


def rnnt_loss(logits, labels, input_lengths, label_lengths, blank: int = 0,
              fastemit_lambda: float = 0.0, reduction: str = "mean",
              name=None):
    """RNN-T transducer loss (reference: nn/functional/loss.py rnnt_loss
    over warprnnt). Log-domain alpha recursion over the T axis with a
    lax.scan; each step advances the [B, U+1] frontier — O(T·U) work, MXU
    untouched (memory-bound by design, like the reference kernel)."""
    lp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)  # [B, T, U1, V]
    y = jnp.asarray(labels).astype(jnp.int32)              # [B, U]
    b, t_max, u1, _ = lp.shape
    in_len = jnp.asarray(input_lengths).reshape(-1)
    lab_len = jnp.asarray(label_lengths).reshape(-1)
    neg_inf = jnp.float32(-1e30)

    blank_lp = lp[..., blank]                               # [B, T, U1]
    y_pad = jnp.pad(y, ((0, 0), (0, u1 - y.shape[1])))
    emit_lp = jnp.take_along_axis(
        lp, y_pad[:, None, :, None], axis=-1)[..., 0]       # [B, T, U1]

    u_range = jnp.arange(u1)

    def time_step(alpha, t):
        # alpha carries alpha[t-1, :] ([B, U1]); produce alpha[t, :].
        # Graves recursion: alpha(t,u) = logaddexp(alpha(t-1,u)+blank(t-1,u),
        #                                          alpha(t,u-1)+emit(t,u-1))
        via_blank = jnp.where(t == 0, alpha,
                              alpha + blank_lp[:, jnp.maximum(t - 1, 0)])
        emit_t = emit_lp[:, t]

        def u_step(prev, u):
            cur = jnp.where(u == 0, via_blank[:, 0],
                            jnp.logaddexp(via_blank[:, u],
                                          prev + emit_t[:, u - 1]))
            return cur, cur

        _, cols = lax.scan(u_step, jnp.full((b,), neg_inf), u_range)
        new_alpha = jnp.moveaxis(cols, 0, 1)                # [B, U1]
        # frames beyond this sequence's length keep alpha frozen at
        # alpha[in_len-1, :]
        active = (t < in_len)[:, None]
        return jnp.where(active, new_alpha, alpha), None

    alpha0 = jnp.full((b, u1), neg_inf).at[:, 0].set(0.0)
    alpha, _ = lax.scan(time_step, alpha0, jnp.arange(t_max))
    # terminate from (T-1, U) with one final blank
    final_blank = jnp.take_along_axis(
        blank_lp[jnp.arange(b), jnp.maximum(in_len - 1, 0)],
        lab_len[:, None], axis=1)[:, 0]
    ll = jnp.take_along_axis(alpha, lab_len[:, None], axis=1)[:, 0] \
        + final_blank
    per_seq = -ll
    if reduction == "mean":
        return jnp.mean(per_seq)
    return _reduce(per_seq, reduction)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse: bool = False,
                  name=None):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: nn/functional/loss.py hsigmoid_loss; kernel
    phi/kernels/cpu/hsigmoid_loss_kernel.cc). Tree node k has children
    2k+1/2k+2; class c's path is the root-to-leaf walk of leaf (c +
    num_classes - 1)."""
    x = jnp.asarray(input)                            # [n, d]
    y = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    w = jnp.asarray(weight)                           # [num_classes-1, d]
    depth = max(1, int(math.ceil(math.log2(max(num_classes, 2)))))
    if path_table is None:
        # host-side static path construction for all classes, then gather
        tbl = np.zeros((num_classes, depth), np.int32)
        code = np.zeros((num_classes, depth), np.float32)
        valid = np.zeros((num_classes, depth), np.float32)
        for c in range(num_classes):
            node = c + num_classes - 1
            steps = []
            while node > 0:
                parent = (node - 1) // 2
                steps.append((parent, float(node == 2 * parent + 2)))
                node = parent
            for d_i, (p_n, bit) in enumerate(reversed(steps)):
                tbl[c, d_i] = p_n
                code[c, d_i] = bit
                valid[c, d_i] = 1.0
        path_table = jnp.asarray(tbl)[y]              # [n, depth]
        path_code = jnp.asarray(code)[y]
        mask = jnp.asarray(valid)[y]
    else:
        path_table = jnp.asarray(path_table)
        path_code = jnp.asarray(path_code).astype(x.dtype)
        mask = (path_table >= 0).astype(x.dtype)
        path_table = jnp.maximum(path_table, 0)
    wn = w[path_table]                                # [n, depth, d]
    logits = jnp.einsum("nd,ntd->nt", x, wn)
    if bias is not None:
        logits = logits + jnp.asarray(bias).reshape(-1)[path_table]
    # code bit 1 -> right child: target = bit
    ce = -(path_code * jax.nn.log_sigmoid(logits)
           + (1 - path_code) * jax.nn.log_sigmoid(-logits))
    return jnp.sum(ce * mask, axis=1, keepdims=True)


def margin_cross_entropy(logits, label, margin1: float = 1.0,
                         margin2: float = 0.5, margin3: float = 0.0,
                         scale: float = 64.0, group=None,
                         return_softmax: bool = False,
                         reduction: str = "mean"):
    """ArcFace/CosFace-style margin softmax (reference:
    nn/functional/loss.py margin_cross_entropy; kernel
    phi/kernels/gpu/margin_cross_entropy_kernel.cu): logits are cos(theta),
    the target class gets cos(m1*theta + m2) - m3, then scaled CE. The
    TP/sharded-class variant composes with parallel_cross_entropy."""
    x = jnp.asarray(logits)
    y = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    n, c = x.shape
    target = jnp.take_along_axis(x, y[:, None], axis=1)[:, 0]
    theta = jnp.arccos(jnp.clip(target, -1.0, 1.0))
    mod = jnp.cos(margin1 * theta + margin2) - margin3
    onehot = jax.nn.one_hot(y, c, dtype=x.dtype)
    adj = x * (1 - onehot) + mod[:, None] * onehot
    adj = adj * scale
    logp = jax.nn.log_softmax(adj, axis=1)
    loss = -jnp.take_along_axis(logp, y[:, None], axis=1)
    loss = _reduce(loss, reduction)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def class_center_sample(label, num_classes: int, num_samples: int,
                        group=None):
    """Sample negative class centers plus all positives (reference:
    nn/functional/common.py class_center_sample, PartialFC): returns
    (remapped_label, sampled_class_index). Positive classes always kept;
    negatives fill up to num_samples by hashed priority — jit-friendly
    (static output size num_samples)."""
    y = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    present = jnp.zeros((num_classes,), jnp.bool_).at[y].set(True)
    # priority: positives first (rank 0), then seeded hash order
    rnd = jax.random.uniform(_key(), (num_classes,))
    prio = jnp.where(present, -1.0, rnd)
    order = jnp.argsort(prio)                        # positives lead
    sampled = jnp.sort(order[:num_samples])          # ascending class ids
    # remap: position of each label in `sampled` (paddle semantics)
    remap = jnp.searchsorted(sampled, y).astype(jnp.int32)
    return remap, sampled


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (reference: nn/functional/loss.py
    edit_distance — GPU kernel there; host-side numpy here like the other
    data-dependent ops, NMS precedent in DESIGN_DECISIONS.md). Returns
    (distance [B, 1] float32, sequence_num [1] int64 — the sequence
    COUNT, per the kernel contract edit_distance_kernel.cc:66);
    ``normalized`` divides by the label length."""
    import numpy as _np
    a = _np.asarray(input)
    b = _np.asarray(label)
    if a.ndim == 1:
        a, b = a[None, :], b[None, :]
    B = a.shape[0]
    in_len = (_np.asarray(input_length).reshape(-1).astype(_np.int64)
              if input_length is not None
              else _np.full((B,), a.shape[1], _np.int64))
    lb_len = (_np.asarray(label_length).reshape(-1).astype(_np.int64)
              if label_length is not None
              else _np.full((B,), b.shape[1], _np.int64))
    ignored = set(_np.asarray(ignored_tokens).reshape(-1).tolist()) \
        if ignored_tokens is not None else set()

    def _lev(x, y):
        if ignored:
            x = [t for t in x if t not in ignored]
            y = [t for t in y if t not in ignored]
        m, n = len(x), len(y)
        prev = list(range(n + 1))
        for i in range(1, m + 1):
            cur = [i] + [0] * n
            for j in range(1, n + 1):
                cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                             prev[j - 1] + (x[i - 1] != y[j - 1]))
            prev = cur
        return prev[n], n

    dist = _np.zeros((B, 1), _np.float32)
    for i in range(B):
        d, n = _lev(a[i, :in_len[i]].tolist(), b[i, :lb_len[i]].tolist())
        # normalized divides UNCONDITIONALLY, mirroring the reference
        # kernel (edit_distance divides by label length even when it is
        # 0 -> inf/nan float semantics), rather than silently returning
        # the raw distance for empty labels (round-4 advice)
        if normalized:
            dist[i, 0] = (d / n if n
                          else (_np.inf if d else _np.nan))
        else:
            dist[i, 0] = d
    return jnp.asarray(dist), jnp.asarray([B], jnp.int64)


__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and callable(_v)
           and getattr(_v, "__module__", None) == __name__]
