"""nn Layer long tail: wrappers over functional_extras plus the container /
decoder pieces (ParameterList, BiRNN, BeamSearchDecoder, SpectralNorm).

Reference: python/paddle/nn/layer/{activation.py,pooling.py,loss.py,
common.py,norm.py,rnn.py,container.py} — each class keeps the reference's
constructor signature; forward delegates to the functional op.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import rng_tracker, GLOBAL_STREAM
from .layer import Layer, Parameter
from . import functional as F
from . import functional_extras as FE
from . import initializer as I


# ---------------------------------------------------------------------------
# simple activation layers
# ---------------------------------------------------------------------------

def _act_layer(name, fn, params=()):
    """Build a Layer subclass whose forward calls ``fn(x, *ctor_args)``."""

    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        vals = list(args)
        for i, (pname, default) in enumerate(params):
            if i < len(vals):
                setattr(self, "_" + pname, vals[i])
            else:
                setattr(self, "_" + pname, kwargs.get(pname, default))

    def forward(self, x):
        return fn(x, *[getattr(self, "_" + p) for p, _ in params])

    cls = type(name, (Layer,), {"__init__": __init__, "forward": forward})
    return cls


Identity = _act_layer("Identity", lambda x: jnp.asarray(x))
CELU = _act_layer("CELU", FE.celu, params=[("alpha", 1.0)])
ELU = _act_layer("ELU", F.elu, params=[("alpha", 1.0)])
GLU = _act_layer("GLU", F.glu, params=[("axis", -1)])
Hardshrink = _act_layer("Hardshrink", FE.hardshrink,
                        params=[("threshold", 0.5)])
Hardtanh = _act_layer("Hardtanh", FE.hardtanh,
                      params=[("min", -1.0), ("max", 1.0)])
LogSigmoid = _act_layer("LogSigmoid", FE.log_sigmoid)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax, params=[("axis", -1)])
Maxout = _act_layer("Maxout", FE.maxout,
                    params=[("groups", 2), ("axis", 1)])
ReLU6 = _act_layer("ReLU6", F.relu6)
SELU = _act_layer("SELU", FE.selu,
                  params=[("scale", 1.0507009873554805),
                          ("alpha", 1.6732632423543772)])
Silu = _act_layer("Silu", F.silu)
Softplus = _act_layer("Softplus", F.softplus,
                      params=[("beta", 1.0), ("threshold", 20.0)])
Softshrink = _act_layer("Softshrink", FE.softshrink,
                        params=[("threshold", 0.5)])
Softsign = _act_layer("Softsign", FE.softsign)
Swish = _act_layer("Swish", F.silu)
Tanhshrink = _act_layer("Tanhshrink", FE.tanhshrink)
ThresholdedReLU = _act_layer("ThresholdedReLU", FE.thresholded_relu,
                             params=[("threshold", 1.0), ("value", 0.0)])


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input (reference:
    nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class PReLU(Layer):
    def __init__(self, num_parameters: int = 1, init: float = 0.25,
                 weight_attr=None, data_format: str = "NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], initializer=I.Constant(init))

    def forward(self, x):
        return FE.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower: float = 1.0 / 8.0, upper: float = 1.0 / 3.0,
                 name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return FE.rrelu(x, self._lower, self._upper, training=self.training)


# ---------------------------------------------------------------------------
# pooling / padding / shuffle layers
# ---------------------------------------------------------------------------

def _pool_layer(name, fn, nd_defaults):
    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        Layer.__init__(self)
        self._args = (kernel_size, stride, padding)
        self._kwargs = kwargs

    def forward(self, x):
        return fn(x, self._args[0], self._args[1], self._args[2],
                  **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


AvgPool1D = _pool_layer("AvgPool1D", FE.avg_pool1d, 1)
AvgPool3D = _pool_layer("AvgPool3D", FE.avg_pool3d, 3)
MaxPool1D = _pool_layer("MaxPool1D", FE.max_pool1d, 1)
MaxPool3D = _pool_layer("MaxPool3D", FE.max_pool3d, 3)


def _adaptive_layer(name, fn):
    def __init__(self, output_size, **kwargs):
        Layer.__init__(self)
        self._output_size = output_size
        self._kwargs = kwargs

    def forward(self, x):
        return fn(x, self._output_size, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


AdaptiveAvgPool1D = _adaptive_layer("AdaptiveAvgPool1D",
                                    FE.adaptive_avg_pool1d)
AdaptiveAvgPool3D = _adaptive_layer("AdaptiveAvgPool3D",
                                    FE.adaptive_avg_pool3d)
AdaptiveMaxPool1D = _adaptive_layer("AdaptiveMaxPool1D",
                                    FE.adaptive_max_pool1d)
AdaptiveMaxPool2D = _adaptive_layer("AdaptiveMaxPool2D",
                                    FE.adaptive_max_pool2d)
AdaptiveMaxPool3D = _adaptive_layer("AdaptiveMaxPool3D",
                                    FE.adaptive_max_pool3d)


def _unpool_layer(cls_name, fn):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        Layer.__init__(self)
        self._a = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, osz = self._a
        return fn(x, indices, k, stride=s, padding=p, output_size=osz)

    return type(cls_name, (Layer,),
                {"__init__": __init__, "forward": forward})


MaxUnPool1D = _unpool_layer("MaxUnPool1D", FE.max_unpool1d)
MaxUnPool2D = _unpool_layer("MaxUnPool2D", FE.max_unpool2d)
MaxUnPool3D = _unpool_layer("MaxUnPool3D", FE.max_unpool3d)


class _PadNd(Layer):
    _nd = 2

    def __init__(self, padding, mode: str = "constant", value: float = 0.0,
                 data_format=None, name=None):
        super().__init__()
        self._padding = padding
        self._mode = mode
        self._value = value

    def forward(self, x):
        return F.pad(x, self._padding, mode=self._mode, value=self._value)


class Pad1D(_PadNd):
    _nd = 1


class Pad2D(_PadNd):
    _nd = 2


class Pad3D(_PadNd):
    _nd = 3


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format: str = "NCHW", name=None):
        super().__init__()
        self._padding = padding
        self._data_format = data_format

    def forward(self, x):
        return FE.zeropad2d(x, self._padding, self._data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups: int, data_format: str = "NCHW", name=None):
        super().__init__()
        self._groups = groups
        self._data_format = data_format

    def forward(self, x):
        return FE.channel_shuffle(x, self._groups, self._data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor: int, data_format: str = "NCHW",
                 name=None):
        super().__init__()
        self._factor = downscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._factor, self._data_format)


class Unflatten(Layer):
    def __init__(self, axis: int, shape, name=None):
        super().__init__()
        self._axis = axis
        self._shape = shape

    def forward(self, x):
        from ..tensor.extras import unflatten
        return unflatten(x, self._axis, self._shape)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return FE.fold(x, *self._a)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._a = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self._a)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode: str = "nearest",
                 align_corners: bool = False, align_mode: int = 0,
                 data_format: str = "NCHW", name=None):
        super().__init__()
        self._a = (size, scale_factor, mode, align_corners, data_format)

    def forward(self, x):
        size, sf, mode, ac, df = self._a
        return FE.upsample(x, size=size, scale_factor=sf, mode=mode,
                           align_corners=ac, data_format=df)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None,
                 data_format: str = "NCHW", name=None):
        super().__init__(size=size, scale_factor=scale_factor,
                         mode="bilinear", align_corners=True,
                         data_format=data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None,
                 data_format: str = "NCHW", name=None):
        super().__init__(size=size, scale_factor=scale_factor,
                         mode="nearest", data_format=data_format)


# ---------------------------------------------------------------------------
# dropout variants / norms
# ---------------------------------------------------------------------------

class AlphaDropout(Layer):
    def __init__(self, p: float = 0.5, name=None):
        super().__init__()
        self._p = p

    def forward(self, x):
        return FE.alpha_dropout(x, self._p, training=self.training)


class Dropout2D(Layer):
    def __init__(self, p: float = 0.5, data_format: str = "NCHW", name=None):
        super().__init__()
        self._p = p
        self._data_format = data_format

    def forward(self, x):
        return FE.dropout2d(x, self._p, training=self.training,
                            data_format=self._data_format)


class Dropout3D(Layer):
    def __init__(self, p: float = 0.5, data_format: str = "NCDHW",
                 name=None):
        super().__init__()
        self._p = p
        self._data_format = data_format

    def forward(self, x):
        return FE.dropout3d(x, self._p, training=self.training,
                            data_format=self._data_format)


class _InstanceNormNd(Layer):
    def __init__(self, num_features: int, epsilon: float = 1e-5,
                 momentum: float = 0.9, weight_attr=None, bias_attr=None,
                 data_format: str = "NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.scale = self.create_parameter(
                [num_features], initializer=I.Constant(1.0))
        else:
            self.scale = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], initializer=I.Constant(0.0), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return FE.instance_norm(
            x,
            weight=self.scale if self.scale is not None else None,
            bias=self.bias if self.bias is not None else None,
            eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormNd):
    pass


class InstanceNorm2D(_InstanceNormNd):
    pass


class InstanceNorm3D(_InstanceNormNd):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size: int, alpha: float = 1e-4, beta: float = 0.75,
                 k: float = 1.0, data_format: str = "NCHW", name=None):
        super().__init__()
        self._a = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return FE.local_response_norm(x, *self._a)


class SpectralNorm(Layer):
    """Spectral normalization of a weight tensor via power iteration
    (reference: nn/layer/norm.py SpectralNorm; kernel
    phi/kernels/impl/spectral_norm_kernel_impl.h). Returns W / sigma(W).
    u/v vectors are persistent buffers updated on each forward."""

    def __init__(self, weight_shape, dim: int = None, power_iters: int = 1,
                 epsilon: float = 1e-12, dtype="float32", axis: int = 0):
        # reference spells the axis arg ``dim`` (nn/layer/norm.py:1900)
        if dim is not None:
            axis = dim
        super().__init__()
        self._axis = axis
        self._power_iters = power_iters
        self._eps = epsilon
        h = int(weight_shape[axis])
        w = int(np.prod(weight_shape)) // h
        key = rng_tracker().next_key(GLOBAL_STREAM) \
            if rng_tracker().has(GLOBAL_STREAM) else jax.random.key(0)
        k1, k2 = jax.random.split(key)
        self.register_buffer("weight_u", jax.random.normal(k1, (h,)))
        self.register_buffer("weight_v", jax.random.normal(k2, (w,)))

    def forward(self, weight):
        w = jnp.asarray(weight)
        h = w.shape[self._axis]
        mat = jnp.moveaxis(w, self._axis, 0).reshape(h, -1)
        u = self.weight_u
        v = self.weight_v
        for _ in range(self._power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self._eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self._eps)
        sigma = u @ mat @ v
        if not isinstance(u, jax.core.Tracer):  # persist only eagerly —
            # under jit the iteration re-runs from the saved buffers
            self.register_buffer("weight_u", jax.lax.stop_gradient(u))
            self.register_buffer("weight_v", jax.lax.stop_gradient(v))
        return w / sigma


# ---------------------------------------------------------------------------
# similarity / distance / misc
# ---------------------------------------------------------------------------

class CosineSimilarity(Layer):
    def __init__(self, axis: int = 1, eps: float = 1e-8):
        super().__init__()
        self._axis = axis
        self._eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self._axis, eps=self._eps)


class PairwiseDistance(Layer):
    def __init__(self, p: float = 2.0, epsilon: float = 1e-6,
                 keepdim: bool = False, name=None):
        super().__init__()
        self._a = (p, epsilon, keepdim)

    def forward(self, x, y):
        return FE.pairwise_distance(x, y, *self._a)


class Bilinear(Layer):
    def __init__(self, in1_features: int, in2_features: int,
                 out_features: int, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features])
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x1, x2):
        return FE.bilinear(x1, x2, self.weight,
                           self.bias if self.bias is not None
                           else None)


class ParameterList(Layer):
    """Indexed parameter container (reference: nn/layer/container.py
    ParameterList)."""

    def __init__(self, parameters=None):
        super().__init__()
        self._n = 0
        if parameters is not None:
            for p in parameters:
                self.append(p)

    def append(self, parameter):
        if not isinstance(parameter, Parameter):
            parameter = Parameter(jnp.asarray(parameter))
        self.add_parameter(str(self._n), parameter)
        self._n += 1
        return self

    def __getitem__(self, idx):
        if not -self._n <= idx < self._n:
            raise IndexError(
                f"index {idx} out of range for ParameterList of length "
                f"{self._n}")
        return self._parameters[str(idx % self._n)]

    def __len__(self):
        return self._n

    def __iter__(self):
        return iter(self._parameters[str(i)] for i in range(self._n))


# ---------------------------------------------------------------------------
# conv transpose layers
# ---------------------------------------------------------------------------

class _ConvTransposeNd(Layer):
    _nd = 1
    _fn = staticmethod(FE.conv1d_transpose)

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, output_padding=0, groups: int = 1,
                 dilation=1, weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        from .functional import _norm_tuple
        k = _norm_tuple(kernel_size, self._nd)
        self._a = (stride, padding, output_padding, dilation, groups)
        fan_in = in_channels * int(np.prod(k))
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *k],
            initializer=I.Uniform(-bound, bound))
        self.bias = (self.create_parameter(
            [out_channels], initializer=I.Uniform(-bound, bound),
            is_bias=True) if bias_attr is not False else None)

    def forward(self, x):
        s, p, op, d, g = self._a
        return self._fn(x, self.weight,
                        self.bias if self.bias is not None else None,
                        stride=s, padding=p, output_padding=op, groups=g,
                        dilation=d)


class Conv1DTranspose(_ConvTransposeNd):
    _nd = 1
    _fn = staticmethod(FE.conv1d_transpose)


class Conv3DTranspose(_ConvTransposeNd):
    _nd = 3
    _fn = staticmethod(FE.conv3d_transpose)


# ---------------------------------------------------------------------------
# loss layers
# ---------------------------------------------------------------------------

class BCELoss(Layer):
    def __init__(self, weight=None, reduction: str = "mean", name=None):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, weight=self._weight,
                                      reduction=self._reduction)


def _loss_layer(cls_name, fn, params):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        self._kw = {}
        for i, (p, d) in enumerate(params):
            if i < len(args):
                self._kw[p] = args[i]
            else:
                self._kw[p] = kwargs.get(p, d)

    def forward(self, *args):
        return fn(*args, **self._kw)

    return type(cls_name, (Layer,),
                {"__init__": __init__, "forward": forward})


CosineEmbeddingLoss = _loss_layer(
    "CosineEmbeddingLoss", FE.cosine_embedding_loss,
    [("margin", 0.0), ("reduction", "mean")])
HingeEmbeddingLoss = _loss_layer(
    "HingeEmbeddingLoss", FE.hinge_embedding_loss,
    [("margin", 1.0), ("reduction", "mean")])
MarginRankingLoss = _loss_layer(
    "MarginRankingLoss", FE.margin_ranking_loss,
    [("margin", 0.0), ("reduction", "mean")])
PoissonNLLLoss = _loss_layer(
    "PoissonNLLLoss", FE.poisson_nll_loss,
    [("log_input", True), ("full", False), ("epsilon", 1e-8),
     ("reduction", "mean")])
GaussianNLLLoss = _loss_layer(
    "GaussianNLLLoss", FE.gaussian_nll_loss,
    [("full", False), ("epsilon", 1e-6), ("reduction", "mean")])
MultiLabelSoftMarginLoss = _loss_layer(
    "MultiLabelSoftMarginLoss", FE.multi_label_soft_margin_loss,
    [("weight", None), ("reduction", "mean")])
MultiMarginLoss = _loss_layer(
    "MultiMarginLoss", FE.multi_margin_loss,
    [("p", 1), ("margin", 1.0), ("weight", None), ("reduction", "mean")])
SoftMarginLoss = _loss_layer(
    "SoftMarginLoss", FE.soft_margin_loss, [("reduction", "mean")])
TripletMarginLoss = _loss_layer(
    "TripletMarginLoss", FE.triplet_margin_loss,
    [("margin", 1.0), ("p", 2.0), ("epsilon", 1e-6), ("swap", False),
     ("reduction", "mean")])
TripletMarginWithDistanceLoss = _loss_layer(
    "TripletMarginWithDistanceLoss", FE.triplet_margin_with_distance_loss,
    [("distance_function", None), ("margin", 1.0), ("swap", False),
     ("reduction", "mean")])


class CTCLoss(Layer):
    def __init__(self, blank: int = 0, reduction: str = "mean"):
        super().__init__()
        self._blank = blank
        self._reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times: bool = False):
        return FE.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                           blank=self._blank, reduction=self._reduction,
                           norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank: int = 0, fastemit_lambda: float = 0.0,
                 reduction: str = "mean", name=None):
        super().__init__()
        self._a = (blank, fastemit_lambda, reduction)

    def forward(self, logits, labels, input_lengths, label_lengths):
        blank, fe, red = self._a
        return FE.rnnt_loss(logits, labels, input_lengths, label_lengths,
                            blank=blank, fastemit_lambda=fe, reduction=red)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size: int, num_classes: int,
                 weight_attr=None, bias_attr=None, is_custom: bool = False,
                 is_sparse: bool = False, name=None):
        super().__init__()
        self._num_classes = num_classes
        bound = 1.0 / math.sqrt(feature_size)
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size],
            initializer=I.Uniform(-bound, bound))
        self.bias = (self.create_parameter(
            [num_classes - 1], initializer=I.Uniform(-bound, bound),
            is_bias=True) if bias_attr is not False else None)

    def forward(self, input, label, path_table=None, path_code=None):
        return FE.hsigmoid_loss(
            input, label, self._num_classes, self.weight,
            self.bias if self.bias is not None else None,
            path_table=path_table, path_code=path_code)


# ---------------------------------------------------------------------------
# recurrent extras: BiRNN, RNNCellBase, beam search decoding
# ---------------------------------------------------------------------------

from .rnn import RNN, _CellBase as RNNCellBase  # re-export base


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (reference: nn/layer/rnn.py
    BiRNN): concat of forward and time-reversed backward passes."""

    def __init__(self, cell_fw, cell_bw, time_major: bool = False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self._fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self._bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            st_fw = st_bw = None
        else:
            st_fw, st_bw = initial_states
        out_fw, fin_fw = self._fw(inputs, st_fw, sequence_length)
        out_bw, fin_bw = self._bw(inputs, st_bw, sequence_length)
        return jnp.concatenate([out_fw, out_bw], axis=-1), (fin_fw, fin_bw)


class BeamSearchDecoder:
    """Beam-search decoder over a cell (reference: nn/decode.py
    BeamSearchDecoder). Tracks log-probs per beam; step = cell forward +
    top-k over (beam x vocab); finished beams propagate EOS."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _logits(self, token, states):
        x = (self.embedding_fn(token) if self.embedding_fn is not None
             else jax.nn.one_hot(token, getattr(self.cell, "input_size")))
        out, new_states = self.cell(x, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states


def dynamic_decode(decoder, inits=None, max_step_num: int = 32,
                   output_time_major: bool = False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Unrolled beam search driving a BeamSearchDecoder (reference:
    nn/decode.py dynamic_decode). Host-side loop (max_step_num is static);
    each step is jittable cell compute. Returns (ids [b, T, beam] or
    time-major, final scores)."""
    d = decoder
    b = kwargs.get("batch_size", 1)
    if inits is not None:
        leaves = jax.tree.leaves(inits)
        if leaves:
            b = leaves[0].shape[0]
    w = d.beam_size
    # tile states to [b*w, ...]
    states = (jax.tree.map(lambda s: jnp.repeat(s, w, axis=0), inits)
              if inits is not None else None)
    token = jnp.full((b * w,), d.start_token, jnp.int32)
    log_probs = jnp.tile(
        jnp.asarray([0.0] + [-1e9] * (w - 1), jnp.float32), (b,))  # [b*w]
    finished = jnp.zeros((b * w,), bool)
    steps = []
    for _ in range(max_step_num):
        logits, new_states = d._logits(token, states)
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(logits, axis=-1)            # [b*w, v]
        # finished beams only extend with end_token at zero cost
        fin_mask = jnp.full((v,), -1e9).at[d.end_token].set(0.0)
        logp = jnp.where(finished[:, None], fin_mask[None, :], logp)
        total = (log_probs[:, None] + logp).reshape(b, w * v)
        top_lp, top_idx = jax.lax.top_k(total, w)              # [b, w]
        beam_src = top_idx // v                                # [b, w]
        token = (top_idx % v).reshape(-1).astype(jnp.int32)
        gather = (jnp.arange(b)[:, None] * w + beam_src).reshape(-1)
        states = jax.tree.map(lambda s: s[gather], new_states)
        finished = finished[gather] | (token == d.end_token)
        log_probs = top_lp.reshape(-1)
        steps.append(token.reshape(b, w))
        if bool(jnp.all(finished)):
            break
    ids = jnp.stack(steps, axis=0)                             # [T, b, w]
    if not output_time_major:
        ids = jnp.moveaxis(ids, 0, 1)                          # [b, T, w]
    return ids, log_probs.reshape(b, w)
