"""Weight-only / LLM.int8 quantized linear (LLM serving quantization).

Reference: python/paddle/nn/quant/quantized_linear.py —
weight_quantize:39 (returns TRANSPOSED int8 [n, k] + per-channel fp32
scale [n]), weight_dequantize:96, weight_only_linear:152,
llm_int8_linear:240 (CUDA cutlass kernels behind them).

TPU redesign (no cutlass): the layouts and contracts are kept exactly —
transposed int8 weights, per-channel or group-wise scales, int4 packed two
nibbles per byte — and the compute maps to what the MXU actually offers:

- weight-only: weights live int8/int4 in HBM (the point is HBM footprint
  and bandwidth at decode time); dequantization fuses into the bf16 matmul
  epilogue (XLA: convert+multiply fold into the dot's operand).
- llm.int8: per-token absmax activation quantization, int8 x int8 ->
  int32 on the MXU (2x bf16 throughput on v5e), outlier activation
  channels (amax > threshold) split out to a small bf16 matmul against
  the dequantized weight columns — the LLM.int8() decomposition. With
  calibrated ``outlier_indices`` (concrete) the fp path is a genuinely
  small static-slice matmul; with only a ``threshold`` the outlier set is
  data-dependent, so the fp path is a masked full-shape matmul (exact but
  an extra dense GEMM — XLA cannot gather a data-dependent column count).

The reference's ``arch`` (SM70/80...) parameter is accepted and ignored —
there is no SM architecture to pick on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]

_ALGOS = ("weight_only_int8", "weight_only_int4", "llm.int8")


def _check(algo, group_size):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")
    if algo == "llm.int8" and group_size != -1:
        raise ValueError("llm.int8 uses per-channel scales only "
                         "(group_size=-1); llm_int8_linear consumes a "
                         "rank-1 [n] scale")


def weight_quantize(x, algo: str = "weight_only_int8", arch=None,
                    group_size: int = -1):
    """Quantize a [k, n] float weight.

    Returns (out, scale): ``out`` int8, TRANSPOSED layout [n, k] (int4:
    [n, k//2], two nibbles per byte, low nibble first); ``scale`` fp32 —
    [n] per-channel, or [n_groups, n] for group-wise (reference contract,
    quantized_linear.py:39)."""
    _check(algo, group_size)
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"weight must be rank-2, got {x.shape}")
    k, n = x.shape
    if algo == "weight_only_int4" and k % 2:
        raise ValueError(f"int4 packing needs an even input dim, got k={k}")
    wt = x.T.astype(jnp.float32)                        # [n, k]
    qmax = 7.0 if algo == "weight_only_int4" else 127.0
    if group_size == -1:
        amax = jnp.max(jnp.abs(wt), axis=1, keepdims=True)      # [n, 1]
        scale = (amax / qmax).astype(jnp.float32)
        q = jnp.clip(jnp.round(wt / jnp.maximum(scale, 1e-10)),
                     -qmax, qmax).astype(jnp.int8)
        scale_out = scale[:, 0]                                 # [n]
    else:
        if k % group_size:
            raise ValueError(f"k={k} not divisible by group_size "
                             f"{group_size}")
        g = k // group_size
        wg = wt.reshape(n, g, group_size)
        amax = jnp.max(jnp.abs(wg), axis=2, keepdims=True)      # [n, g, 1]
        scale = (amax / qmax).astype(jnp.float32)
        q = jnp.clip(jnp.round(wg / jnp.maximum(scale, 1e-10)),
                     -qmax, qmax).astype(jnp.int8).reshape(n, k)
        scale_out = scale[:, :, 0].T                            # [g, n]
    if algo == "weight_only_int4":
        lo = q[:, 0::2].astype(jnp.int32) & 0xF
        hi = (q[:, 1::2].astype(jnp.int32) & 0xF) << 4
        q = (lo | hi).astype(jnp.uint8).view(jnp.int8)          # [n, k//2]
    return q, scale_out


def _unpack_int4(q):
    """[n, k//2] packed nibbles -> [n, k] int8 in [-8, 7]."""
    b = q.view(jnp.uint8).astype(jnp.int32)
    lo = b & 0xF
    hi = (b >> 4) & 0xF
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    n = q.shape[0]
    return jnp.stack([lo, hi], axis=2).reshape(n, -1).astype(jnp.int8)


def _dequant(weight, scale, algo, group_size, out_dtype):
    wq = _unpack_int4(weight) if algo == "weight_only_int4" else weight
    n, k = wq.shape
    w = wq.astype(jnp.float32)
    scale = jnp.asarray(scale, jnp.float32)
    if scale.ndim == 1:                                 # [n] per-channel
        if group_size != -1:
            raise ValueError(f"group_size={group_size} given but scale is "
                             f"per-channel (rank-1); pass the [g, n] "
                             f"group scale or group_size=-1")
        w = w * scale[:, None]
    else:                                               # [g, n] group-wise
        g = scale.shape[0]
        if group_size == -1:
            raise ValueError("rank-2 group scale given: pass the matching "
                             "group_size (64/128)")
        if g * group_size != k:
            raise ValueError(f"scale groups {g} x group_size {group_size} "
                             f"!= input dim {k}: quantize/dequantize "
                             f"group_size mismatch")
        w = (w.reshape(n, g, k // g) * scale.T[:, :, None]).reshape(n, k)
    return w.astype(out_dtype)


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype="float16", group_size: int = -1):
    """Inverse of weight_quantize: returns the [k, n] float weight
    (reference: quantized_linear.py:96)."""
    _check(algo, group_size)
    return _dequant(jnp.asarray(x), scale, algo, group_size,
                    jnp.dtype(out_dtype)).T


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None,
                       group_size: int = -1):
    """y = x @ dequant(weight).T + bias with int8/int4 weights
    (reference: quantized_linear.py:152). The dequant fuses into the
    matmul; weights stay quantized in HBM."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be 'int8'|'int4', "
                         f"got {weight_dtype!r}")
    x = jnp.asarray(x)
    algo = "weight_only_int8" if weight_dtype == "int8" else \
        "weight_only_int4"
    _check(algo, group_size)
    wq = jnp.asarray(weight)
    out = None
    if weight_dtype == "int8" and group_size == -1 and weight_scale is not None:
        # registry-routed path (ISSUE 17 dedupe): the ONE "int8_matmul"
        # op picks the fused Pallas kernel on TPU (TuneDB blocks +
        # lowering probe + PT_DISABLE_PALLAS honored) or the XLA
        # convert+scale composition everywhere else
        scale = jnp.asarray(weight_scale, jnp.float32)
        if scale.ndim == 1:
            try:
                from ..ops.registry import dispatch
                out = dispatch("int8_matmul")(x, wq, scale)
            except KeyError:  # pragma: no cover - jaxlib without pallas
                out = None
    if out is None:
        w = _dequant(wq, weight_scale, algo, group_size, x.dtype)  # [n, k]
        out = jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
    if bias is not None:
        out = out + jnp.asarray(bias, x.dtype)
    return out


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0, outlier_indices=None):
    """LLM.int8() linear (reference: quantized_linear.py:240): outlier
    activation channels run in x.dtype against dequantized weight columns;
    the rest run int8 x int8 -> int32 on the MXU with per-token scales.

    Two outlier modes, because XLA needs static shapes:

    - ``outlier_indices`` (recommended for serving): a CONCRETE index list
      from calibration. The fp path then really is a small [.., o] x [o, n]
      matmul over statically-sliced columns, and the int8 GEMM carries the
      bulk at 2x bf16 MXU throughput — the production LLM.int8 shape.
    - ``threshold`` only (reference default): the outlier set is a traced,
      data-dependent mask, so the fp path is a masked FULL-shape matmul —
      exact, but costs an extra dense GEMM; use it for parity/experiments,
      not speed.
    """
    x = jnp.asarray(x)
    weight = jnp.asarray(weight)                        # [n, k] int8
    scale = jnp.asarray(weight_scale, jnp.float32)      # [n]
    if scale.ndim != 1:
        raise ValueError("llm_int8_linear takes the per-channel [n] scale "
                         "from weight_quantize(algo='llm.int8')")
    xf = x.astype(jnp.float32)
    k = x.shape[-1]

    if outlier_indices is not None:
        import numpy as _np
        idx = _np.asarray(outlier_indices, _np.int32)   # concrete -> static
        keep = _np.ones((k,), bool)
        keep[idx] = False
        x_in = xf * jnp.asarray(keep, jnp.float32)
    else:
        amax_k = jnp.max(jnp.abs(xf),
                         axis=tuple(range(x.ndim - 1)))           # [k]
        outlier = amax_k > threshold                    # traced mask
        x_in = jnp.where(outlier, 0.0, xf)

    # int8 path: per-token absmax quantization of the non-outlier channels
    a_scale = jnp.max(jnp.abs(x_in), axis=-1, keepdims=True) / 127.0
    a_scale = jnp.maximum(a_scale, 1e-10)
    xq = jnp.clip(jnp.round(x_in / a_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, weight, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)               # [..., n] int32
    out = acc.astype(jnp.float32) * a_scale * scale     # dequant both sides

    if outlier_indices is not None:
        # small static-slice fp matmul: [.., o] x [o, n]
        x_out = jnp.take(x, jnp.asarray(idx), axis=-1).astype(x.dtype)
        w_cols = jnp.take(weight, jnp.asarray(idx), axis=1)
        w_out = (w_cols.astype(jnp.float32) * scale[:, None]).astype(x.dtype)
    else:
        # masked full-shape fp matmul (exact; extra dense GEMM — see doc)
        x_out = jnp.where(outlier, xf, 0.0).astype(x.dtype)
        w_out = (weight.astype(jnp.float32) * scale[:, None]).astype(x.dtype)
    out = out + jax.lax.dot_general(
        x_out, w_out, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    out = out.astype(x.dtype)
    if bias is not None:
        out = out + jnp.asarray(bias, x.dtype)
    return out
