"""Common layers.

Reference: python/paddle/nn/layer/{common.py,norm.py,conv.py,transformer.py,
activation.py}. Weight layouts follow the reference: Linear weight is
[in_features, out_features]; Conv2D weight is [out_c, in_c/groups, kh, kw].
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from . import functional as F
from . import initializer as I
from .layer import Layer, Parameter, Buffer, get_default_dtype


class Linear(Layer):
    def __init__(self, in_features: int, out_features: int, bias_attr=True,
                 weight_attr=None, name=None, dtype=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        # None -> create_parameter's chain: global initializer if set
        # (set_global_initializer), else XavierUniform
        init_w = weight_attr if isinstance(weight_attr, I.Initializer) else None
        self.weight = self.create_parameter([in_features, out_features],
                                            dtype=dtype, initializer=init_w)
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], dtype=dtype, is_bias=True)
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, sparse: bool = False,
                 weight_attr=None, dtype=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        init_w = weight_attr if isinstance(weight_attr, I.Initializer) else None
        self.weight = self.create_parameter([num_embeddings, embedding_dim],
                                            default_initializer=I.Normal(0.0, 1.0),
                                            dtype=dtype, initializer=init_w)

    def forward(self, ids):
        return F.embedding(ids, self.weight, self.padding_idx)


class Dropout(Layer):
    def __init__(self, p: float = 0.5, mode: str = "upscale_in_train",
                 rng_name: str = "global_seed"):
        super().__init__()
        self.p = p
        self.mode = mode
        self.rng_name = rng_name

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode,
                         rng_name=self.rng_name)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon: float = 1e-5,
                 weight_attr=True, bias_attr=True, dtype=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(self.normalized_shape, dtype=dtype,
                                                initializer=I.Constant(1.0))
        else:
            self.add_parameter("weight", None)
        if bias_attr is not False:
            self.bias = self.create_parameter(self.normalized_shape, dtype=dtype,
                                              is_bias=True)
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)


class RMSNorm(Layer):
    """Reference analogue: paddle.incubate.nn.functional.fused_rms_norm
    wrapped as a layer (used by Llama/ERNIE blocks)."""

    def __init__(self, hidden_size: int, epsilon: float = 1e-6, dtype=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], dtype=dtype,
                                            initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class BatchNorm2D(Layer):
    def __init__(self, num_features: int, momentum: float = 0.9,
                 epsilon: float = 1e-5, weight_attr=True, bias_attr=True,
                 data_format: str = "NCHW", dtype=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter([num_features], dtype=dtype,
                                                initializer=I.Constant(1.0))
        else:
            self.add_parameter("weight", None)
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], dtype=dtype, is_bias=True)
        else:
            self.add_parameter("bias", None)
        self.register_buffer("_mean", jnp.zeros([num_features], jnp.float32))
        self.register_buffer("_variance", jnp.ones([num_features], jnp.float32))

    def forward(self, x):
        if self.training:
            out, new_mean, new_var = F.batch_norm(
                x, self._mean, self._variance, self.weight, self.bias,
                training=True, momentum=self.momentum, epsilon=self.epsilon,
                data_format=self.data_format)
            # NOTE: buffer updates are side effects; under the functional
            # bridge these persist only outside jit/grad traces — storing a
            # tracer would leak it into later calls (trainer carries BN
            # stats through state instead).
            import jax as _jax
            if not isinstance(new_mean, _jax.core.Tracer):
                self._buffers["_mean"].value = new_mean
                self._buffers["_variance"].value = new_var
            return out
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=False, epsilon=self.epsilon,
                            data_format=self.data_format)


BatchNorm = BatchNorm2D


class GroupNorm(Layer):
    def __init__(self, num_groups: int, num_channels: int, epsilon: float = 1e-5,
                 weight_attr=True, bias_attr=True, data_format: str = "NCHW",
                 dtype=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter([num_channels], dtype=dtype,
                                                initializer=I.Constant(1.0))
        else:
            self.add_parameter("weight", None)
        if bias_attr is not False:
            self.bias = self.create_parameter([num_channels], dtype=dtype, is_bias=True)
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class Conv2D(Layer):
    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, dilation=1, groups: int = 1,
                 bias_attr=True, data_format: str = "NCHW", dtype=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k[0] * k[1] // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k[0], k[1]], dtype=dtype,
            default_initializer=I.KaimingUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], dtype=dtype, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, output_padding=0, dilation=1, groups: int = 1,
                 bias_attr=True, data_format: str = "NCHW", dtype=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.output_padding = output_padding
        self.data_format = data_format
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, k[0], k[1]], dtype=dtype,
            default_initializer=I.KaimingUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], dtype=dtype, is_bias=True)
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding, self.dilation,
                                  self.groups, self.data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask: bool = False, ceil_mode: bool = False,
                 data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.return_mask = return_mask
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class Flatten(Layer):
    def __init__(self, start_axis: int = 1, stop_axis: int = -1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ..tensor import flatten as _flatten
        return _flatten(x, self.start_axis, self.stop_axis)


# activation layers ---------------------------------------------------------

class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class GELU(Layer):
    def __init__(self, approximate: bool = False):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class SiLU(Layer):
    def forward(self, x):
        return F.silu(x)


class Sigmoid(Layer):
    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return F.tanh(x)


class Softmax(Layer):
    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope: float = 0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class Hardswish(Layer):
    def forward(self, x):
        return F.hardswish(x)


class Hardsigmoid(Layer):
    def forward(self, x):
        return F.hardsigmoid(x)


class Mish(Layer):
    def forward(self, x):
        return F.mish(x)


# losses --------------------------------------------------------------------

class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index: int = -100, reduction: str = "mean",
                 soft_label: bool = False, label_smoothing: float = 0.0):
        super().__init__()
        self.loss_weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.label_smoothing = label_smoothing

    def forward(self, logits, labels):
        return F.cross_entropy(logits, labels, weight=self.loss_weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction, soft_label=self.soft_label,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction: str = "mean", pos_weight=None):
        super().__init__()
        self.loss_weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.loss_weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction: str = "mean", delta: float = 1.0):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index: int = -100, reduction: str = "mean"):
        super().__init__()
        self.loss_weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, log_probs, labels):
        return F.nll_loss(log_probs, labels, self.loss_weight, self.ignore_index,
                          self.reduction)


class BatchNorm1D(BatchNorm2D):
    """BN over [N, C] or [N, C, L] (reference: nn.BatchNorm1D). The shared
    functional core normalizes over all non-channel dims, so only the
    accepted ranks differ from 2D."""

    def forward(self, x):
        if x.ndim not in (2, 3):
            raise ValueError(f"BatchNorm1D expects rank 2 or 3, got {x.ndim}")
        return super().forward(x)


class BatchNorm3D(BatchNorm2D):
    def forward(self, x):
        if x.ndim != 5:
            raise ValueError(f"BatchNorm3D expects rank 5, got {x.ndim}")
        return super().forward(x)


class SyncBatchNorm(BatchNorm2D):
    """Cross-replica BN (reference: nn.SyncBatchNorm backed by collective
    kernels). Under GSPMD the batch axis is sharded and XLA computes the
    jnp.mean/var reductions over the *global* batch automatically, so the
    plain BN math is already synchronized; kept as a distinct class for
    convert_sync_batchnorm parity.

    reference: python/paddle/nn/layer/norm.py SyncBatchNorm
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively swap BatchNorm*D sublayers for SyncBatchNorm."""
        if isinstance(layer, BatchNorm2D) and not isinstance(layer, SyncBatchNorm):
            new = cls(layer.num_features, momentum=layer.momentum,
                      epsilon=layer.epsilon, data_format=layer.data_format)
            # copy through the Parameter/Buffer objects — attribute access
            # (layer.weight) unwraps to the raw array, which has no .value
            for k, p in layer._parameters.items():
                if p is not None and k in new._parameters:
                    new._parameters[k].value = p.value
            for k in ("_mean", "_variance"):
                new._buffers[k].value = layer._buffers[k].value
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class Conv1D(Layer):
    """reference: nn.Conv1D (weight [out, in/groups, k])."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, dilation=1, groups: int = 1,
                 bias_attr=True, data_format: str = "NCL", dtype=None):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        self.stride, self.padding, self.dilation, self.groups = \
            stride, padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, k], dtype=dtype,
            default_initializer=I.KaimingUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], dtype=dtype, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(Layer):
    """reference: nn.Conv3D (weight [out, in/groups, kd, kh, kw])."""

    def __init__(self, in_channels: int, out_channels: int, kernel_size,
                 stride=1, padding=0, dilation=1, groups: int = 1,
                 bias_attr=True, data_format: str = "NCDHW", dtype=None):
        super().__init__()
        k = ((kernel_size,) * 3 if isinstance(kernel_size, int)
             else tuple(kernel_size))
        self.stride, self.padding, self.dilation, self.groups = \
            stride, padding, dilation, groups
        self.data_format = data_format
        fan_in = in_channels * k[0] * k[1] * k[2] // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *k], dtype=dtype,
            default_initializer=I.KaimingUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], dtype=dtype, is_bias=True,
                default_initializer=I.Uniform(-bound, bound))
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor: int, data_format: str = "NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)
