"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py —
SimpleRNNCell/LSTMCell/GRUCell, the RNN sequence wrapper, and the
multi-layer SimpleRNN/LSTM/GRU with bidirectional support).

TPU-native: the time loop is ``lax.scan`` (one compiled step, unrolled by
XLA onto the MXU — never a Python loop over timesteps); gate matmuls are
fused into single [d, 4h]/[d, 3h] projections; state is explicit (initial
states in, final states out) so the layers jit/vmap/grad cleanly.
Batch-first [B, T, D] by default like the reference (time_major=False).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from . import initializer as I
from .layer import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN",
           "LSTM", "GRU"]


class _CellBase(Layer):
    def __init__(self, input_size: int, hidden_size: int, n_gates: int,
                 activation=None, dtype=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-k, k)
        self.weight_ih = self.create_parameter(
            [input_size, n_gates * hidden_size], dtype=dtype, initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, n_gates * hidden_size], dtype=dtype, initializer=init)
        self.bias_ih = self.create_parameter([n_gates * hidden_size],
                                             dtype=dtype, initializer=init)
        self.bias_hh = self.create_parameter([n_gates * hidden_size],
                                             dtype=dtype, initializer=init)

    def _gates(self, x, h):
        return (x @ self.weight_ih + self.bias_ih
                + h @ self.weight_hh + self.bias_hh)


class SimpleRNNCell(_CellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh) (reference SimpleRNNCell)."""

    def __init__(self, input_size: int, hidden_size: int,
                 activation: str = "tanh", dtype=None):
        super().__init__(input_size, hidden_size, 1, dtype=dtype)
        self.activation = jnp.tanh if activation == "tanh" else jax.nn.relu

    def forward(self, x, states=None):
        h = states if states is not None else jnp.zeros(
            (x.shape[0], self.hidden_size), x.dtype)
        h_new = self.activation(self._gates(x, h))
        return h_new, h_new

    def init_state(self, batch, dtype):
        return jnp.zeros((batch, self.hidden_size), dtype)


class LSTMCell(_CellBase):
    """i,f,g,o gate order (reference LSTMCell). states = (h, c)."""

    def __init__(self, input_size: int, hidden_size: int, dtype=None):
        super().__init__(input_size, hidden_size, 4, dtype=dtype)

    def forward(self, x, states=None):
        if states is None:
            states = self.init_state(x.shape[0], x.dtype)
        h, c = states
        gates = self._gates(x, h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)

    def init_state(self, batch, dtype):
        z = jnp.zeros((batch, self.hidden_size), dtype)
        return (z, z)


class GRUCell(_CellBase):
    """r,z,c gate order with the reference's (and cuDNN's) candidate form:
    c = tanh(W_ic x + b_ic + r * (W_hc h + b_hc))."""

    def __init__(self, input_size: int, hidden_size: int, dtype=None):
        super().__init__(input_size, hidden_size, 3, dtype=dtype)

    def forward(self, x, states=None):
        h = states if states is not None else jnp.zeros(
            (x.shape[0], self.hidden_size), x.dtype)
        xg = x @ self.weight_ih + self.bias_ih
        hg = h @ self.weight_hh + self.bias_hh
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        h_new = (1.0 - z) * c + z * h
        return h_new, h_new

    def init_state(self, batch, dtype):
        return jnp.zeros((batch, self.hidden_size), dtype)


def _reverse_sequence(x_tbd, sequence_length):
    """Reverse each sequence within its own length (tf.reverse_sequence):
    x is [T, B, D]; padding positions stay in place."""
    T = x_tbd.shape[0]
    t = jnp.arange(T)[:, None]                       # [T, 1]
    lens = jnp.asarray(sequence_length)[None, :]     # [1, B]
    src = jnp.where(t < lens, lens - 1 - t, t)       # [T, B]
    return jnp.take_along_axis(x_tbd, src[:, :, None], axis=0)


class RNN(Layer):
    """Sequence wrapper running a cell over time with lax.scan
    (reference: nn.RNN). Returns (outputs, final_states).

    ``sequence_length`` masks padded timesteps: the state freezes at each
    sequence's true end (final states match the reference), padded outputs
    are zeros, and is_reverse reverses each sequence within its own length.
    """

    def __init__(self, cell, is_reverse: bool = False,
                 time_major: bool = False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)  # [T,B,D]
        if self.is_reverse:
            x = (_reverse_sequence(x, sequence_length)
                 if sequence_length is not None else x[::-1])
        batch = x.shape[1]
        state = (initial_states if initial_states is not None
                 else self.cell.init_state(batch, x.dtype))
        seq_len = (jnp.asarray(sequence_length)
                   if sequence_length is not None else None)

        def step(carry, inp):
            prev_state, t = carry
            x_t = inp
            out, new_state = self.cell(x_t, prev_state)
            if seq_len is not None:
                active = (t < seq_len)[:, None]
                new_state = jax.tree.map(
                    lambda n, p: jnp.where(active, n, p), new_state,
                    prev_state)
                out = jnp.where(active, out, jnp.zeros_like(out))
            return (new_state, t + 1), out

        (final_state, _), outs = jax.lax.scan(step, (state, jnp.int32(0)), x)
        if self.is_reverse:
            outs = (_reverse_sequence(outs, sequence_length)
                    if sequence_length is not None else outs[::-1])
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, final_state


class _MultiLayerRNN(Layer):
    """num_layers × (optionally bidirectional) stack (reference SimpleRNN/
    LSTM/GRU 'direction' = forward|bidirect)."""

    _cell_cls = None

    def __init__(self, input_size: int, hidden_size: int, num_layers: int = 1,
                 direction: str = "forward", time_major: bool = False,
                 dropout: float = 0.0, dtype=None, **cell_kwargs):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"bad direction {direction!r}")
        self.bidirectional = direction != "forward"
        self.num_layers = num_layers
        self.time_major = time_major
        self.hidden_size = hidden_size
        self.dropout = dropout
        layers_f, layers_b = [], []
        in_size = input_size
        for _ in range(num_layers):
            layers_f.append(RNN(self._cell_cls(in_size, hidden_size,
                                               dtype=dtype, **cell_kwargs),
                                time_major=True))
            if self.bidirectional:
                layers_b.append(RNN(self._cell_cls(in_size, hidden_size,
                                                   dtype=dtype, **cell_kwargs),
                                    is_reverse=True, time_major=True))
            in_size = hidden_size * (2 if self.bidirectional else 1)
        from .layer import LayerList
        self.layers_f = LayerList(layers_f)
        self.layers_b = LayerList(layers_b) if self.bidirectional else None

    def _is_lstm(self):
        return isinstance(self.layers_f[0].cell, LSTMCell)

    def _per_layer_states(self, initial_states):
        """Accept the REFERENCE format — stacked tensors
        [num_layers*D, B, H] ((h, c) pair for LSTM, single h otherwise;
        nn/layer/rnn.py LSTM doc) — or a legacy per-layer list; return
        per-(layer, direction) cell states."""
        L, D = self.num_layers, 2 if self.bidirectional else 1

        def _stacked(a):
            return (hasattr(a, "ndim") and a.ndim == 3
                    and a.shape[0] == L * D)
        if self._is_lstm():
            h0c0 = tuple(initial_states)
            if len(h0c0) == 2 and all(_stacked(a) for a in h0c0):
                h0, c0 = h0c0
                return [tuple((h0[li * D + d], c0[li * D + d])
                              for d in range(D)) if D == 2
                        else (h0[li], c0[li]) for li in range(L)]
        elif _stacked(initial_states):
            h0 = initial_states
            return [tuple(h0[li * D + d] for d in range(D)) if D == 2
                    else h0[li] for li in range(L)]
        return list(initial_states)       # legacy per-layer list

    def _stack_finals(self, finals):
        """Per-(layer, direction) cell states -> the reference's stacked
        [num_layers*D, B, H] tensors ((h, c) for LSTM, h otherwise)."""
        D = 2 if self.bidirectional else 1
        flat = []
        for st in finals:
            flat.extend(st if D == 2 else (st,))
        if self._is_lstm():
            return (jnp.stack([s[0] for s in flat]),
                    jnp.stack([s[1] for s in flat]))
        return jnp.stack(flat)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs if self.time_major else jnp.swapaxes(inputs, 0, 1)
        per_layer = (self._per_layer_states(initial_states)
                     if initial_states is not None else None)
        finals = []
        for li in range(self.num_layers):
            init = per_layer[li] if per_layer is not None else None
            if self.bidirectional:
                init_f, init_b = init if init is not None else (None, None)
                out_f, st_f = self.layers_f[li](
                    x, initial_states=init_f, sequence_length=sequence_length)
                out_b, st_b = self.layers_b[li](
                    x, initial_states=init_b, sequence_length=sequence_length)
                x = jnp.concatenate([out_f, out_b], axis=-1)
                finals.append((st_f, st_b))
            else:
                x, st_f = self.layers_f[li](
                    x, initial_states=init, sequence_length=sequence_length)
                finals.append(st_f)
            if self.dropout > 0 and self.training and li < self.num_layers - 1:
                # inter-layer dropout (reference: the dropout arg of
                # SimpleRNN/LSTM/GRU applies between stacked layers)
                from . import functional as F
                x = F.dropout(x, p=self.dropout, training=True)
        outs = x if self.time_major else jnp.swapaxes(x, 0, 1)
        return outs, self._stack_finals(finals)


class SimpleRNN(_MultiLayerRNN):
    _cell_cls = SimpleRNNCell


class LSTM(_MultiLayerRNN):
    _cell_cls = LSTMCell


class GRU(_MultiLayerRNN):
    _cell_cls = GRUCell
