"""paddle.nn.clip module-path parity: the gradient-clip classes live in
optimizer/clip.py (one implementation, shared by the optimizer plumbing);
this module mirrors the reference import path python/paddle/nn/clip.py."""

from ..optimizer.clip import (ClipGradBase, ClipGradByGlobalNorm,
                              ClipGradByNorm, ClipGradByValue)

__all__ = ["ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue"]


def clip_by_norm(x, max_norm, name=None):
    """reference: nn/clip.py clip_by_norm:39 — scale x so its l2 norm is
    at most max_norm."""
    import jax.numpy as jnp
    arr = jnp.asarray(x)
    norm = jnp.sqrt(jnp.sum(arr * arr))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      1.0)
    return arr * scale


def merge_selected_rows(x, name=None):
    raise NotImplementedError(
        "SelectedRows is LoD/PS-era storage; dense grads only on TPU "
        "(docs/DESIGN_DECISIONS.md)")


def get_tensor_from_selected_rows(x, name=None):
    raise NotImplementedError(
        "SelectedRows is LoD/PS-era storage; dense grads only on TPU "
        "(docs/DESIGN_DECISIONS.md)")


def set_gradient_clip(clip, param_list=None, program=None):
    """Deprecated static-mode global clip setter (reference nn/clip.py:1087
    warns to pass grad_clip to the optimizer instead — same guidance
    here); stores the clip on the default program for parity."""
    import warnings
    warnings.warn(
        "set_gradient_clip is deprecated: pass grad_clip=... to the "
        "optimizer constructor instead (reference issues the same "
        "warning)", stacklevel=2)
    from ..static import default_main_program
    default_main_program().__dict__["_gradient_clip"] = (clip, param_list)
