"""Parameter initializers.

Reference: python/paddle/nn/initializer/ (Constant, Normal, TruncatedNormal,
Uniform, XavierNormal/Uniform, KaimingNormal/Uniform, Assign). Initializers
draw from the global RNG tracker (core/rng.py) so model construction is
reproducible via ``paddle_tpu.seed``.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import rng_tracker, GLOBAL_STREAM


def _key():
    tr = rng_tracker()
    if not tr.has(GLOBAL_STREAM):
        tr.add(GLOBAL_STREAM, 0)
    return tr.next_key(GLOBAL_STREAM)


def _fan_in_out(shape: Sequence[int]):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c/groups, *k]: fan = channels * receptive field
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = jnp.asarray(self.value, dtype=dtype)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        x = jax.random.normal(_key(), shape, dtype=jnp.float32)
        return (x * self.std + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        x = jax.random.truncated_normal(_key(), -2.0, 2.0, shape, dtype=jnp.float32)
        return (x * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        x = jax.random.uniform(_key(), shape, dtype=jnp.float32,
                               minval=self.low, maxval=self.high)
        return x.astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        x = jax.random.uniform(_key(), shape, dtype=jnp.float32,
                               minval=-limit, maxval=limit)
        return x.astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        x = jax.random.normal(_key(), shape, dtype=jnp.float32) * std
        return x.astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, negative_slope: float = 0.0, nonlinearity: str = "leaky_relu"):
        self.a = negative_slope

    def __call__(self, shape, dtype):
        fan_in, _ = _fan_in_out(shape)
        gain = math.sqrt(2.0 / (1 + self.a ** 2))
        limit = gain * math.sqrt(3.0 / fan_in)
        x = jax.random.uniform(_key(), shape, dtype=jnp.float32,
                               minval=-limit, maxval=limit)
        return x.astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, negative_slope: float = 0.0, nonlinearity: str = "leaky_relu"):
        self.a = negative_slope

    def __call__(self, shape, dtype):
        fan_in, _ = _fan_in_out(shape)
        gain = math.sqrt(2.0 / (1 + self.a ** 2))
        std = gain / math.sqrt(fan_in)
        x = jax.random.normal(_key(), shape, dtype=jnp.float32) * std
        return x.astype(dtype)
