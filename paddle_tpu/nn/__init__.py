"""paddle_tpu.nn — module system + layers.

Reference: python/paddle/nn/ (Layer base at nn/layer/layers.py; layer zoo
under nn/layer/). See layer.py for the functional-bridge design that replaces
the eager autograd engine.
"""

from . import functional
from . import initializer
from .layer import (Layer, Parameter, Buffer, Sequential, LayerList, LayerDict,
                    set_default_dtype, get_default_dtype)
from .common import (
    Linear, Embedding, Dropout, LayerNorm, RMSNorm, BatchNorm, BatchNorm1D,
    BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    GroupNorm, Conv1D, Conv2D, Conv3D, Conv2DTranspose, PixelShuffle, MaxPool2D, AvgPool2D, AdaptiveAvgPool2D,
    Flatten, ReLU, GELU, SiLU, Sigmoid, Tanh, Softmax, LeakyReLU, Hardswish,
    Hardsigmoid, Mish, CrossEntropyLoss, MSELoss, L1Loss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, NLLLoss,
)

from .rnn import (SimpleRNNCell, LSTMCell, GRUCell, RNN, SimpleRNN,
                  LSTM, GRU)
from .transformer import (MultiHeadAttention, TransformerEncoderLayer,
                          TransformerEncoder, TransformerDecoderLayer,
                          TransformerDecoder, Transformer)
