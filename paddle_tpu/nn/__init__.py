"""paddle_tpu.nn — module system + layers.

Reference: python/paddle/nn/ (Layer base at nn/layer/layers.py; layer zoo
under nn/layer/). See layer.py for the functional-bridge design that replaces
the eager autograd engine.
"""

from . import functional
from . import initializer
from .layer import (Layer, Parameter, Buffer, Sequential, LayerList, LayerDict,
                    set_default_dtype, get_default_dtype)
from .common import (
    Linear, Embedding, Dropout, LayerNorm, RMSNorm, BatchNorm, BatchNorm1D,
    BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    GroupNorm, Conv1D, Conv2D, Conv3D, Conv2DTranspose, PixelShuffle, MaxPool2D, AvgPool2D, AdaptiveAvgPool2D,
    Flatten, ReLU, GELU, SiLU, Sigmoid, Tanh, Softmax, LeakyReLU, Hardswish,
    Hardsigmoid, Mish, CrossEntropyLoss, MSELoss, L1Loss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, NLLLoss,
)

from .rnn import (SimpleRNNCell, LSTMCell, GRUCell, RNN, SimpleRNN,
                  LSTM, GRU)
from .transformer import (MultiHeadAttention, TransformerEncoderLayer,
                          TransformerEncoder, TransformerDecoderLayer,
                          TransformerDecoder, Transformer)

# -- round-3 parity batch: activation/pool/loss/container long tail ---------
from .layers_extras import (
    Identity, CELU, ELU, GLU, Hardshrink, Hardtanh, LogSigmoid, LogSoftmax,
    Maxout, ReLU6, SELU, Silu, Softplus, Softshrink, Softsign, Swish,
    Tanhshrink, ThresholdedReLU, Softmax2D, PReLU, RReLU,
    AvgPool1D, AvgPool3D, MaxPool1D, MaxPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    Pad1D, Pad2D, Pad3D, ZeroPad2D, ChannelShuffle, PixelUnshuffle,
    Unflatten, Fold, Unfold, Upsample, UpsamplingBilinear2D,
    UpsamplingNearest2D,
    AlphaDropout, Dropout2D, Dropout3D,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LocalResponseNorm,
    SpectralNorm, CosineSimilarity, PairwiseDistance, Bilinear,
    ParameterList, Conv1DTranspose, Conv3DTranspose,
    BCELoss, CosineEmbeddingLoss, HingeEmbeddingLoss, MarginRankingLoss,
    PoissonNLLLoss, GaussianNLLLoss, MultiLabelSoftMarginLoss,
    MultiMarginLoss, SoftMarginLoss, TripletMarginLoss,
    TripletMarginWithDistanceLoss, CTCLoss, RNNTLoss, HSigmoidLoss,
    BiRNN, RNNCellBase, BeamSearchDecoder, dynamic_decode,
)
from ..optimizer.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                              ClipGradByValue)
from . import utils
from . import clip
from . import decode
from . import quant

from . import loss  # noqa: E402  (doctest path paddle.nn.loss)

# reference layout: nn/layer/{common,conv,norm,...}.py + nn/functional/*.py
# are separate files; register those import paths onto this consolidated
# namespace (doctest/recipe idiom: `from paddle.nn.layer.transformer import ...`)
from ..utils import register_submodule_aliases as _rsa
import sys as _sys
from . import transformer as _transformer, rnn as _rnn, loss as _loss
_self = _sys.modules[__name__]
_rsa(__name__ + ".layer", {
    "common": _self, "conv": _self, "norm": _self, "pooling": _self,
    "activation": _self, "distance": _self, "vision": _self,
    "transformer": _transformer, "rnn": _rnn, "loss": _loss,
})
_rsa(__name__ + ".functional", {
    "activation": functional, "common": functional, "conv": functional,
    "loss": functional, "norm": functional, "pooling": functional,
    "vision": functional, "input": functional, "distance": functional,
    "extension": functional,
})
