"""Layer: the module system.

TPU-native analogue of the reference's ``paddle.nn.Layer``
(reference: python/paddle/nn/layer/layers.py — parameter/buffer/sublayer
registration, state_dict, hooks, train/eval mode) re-designed for JAX's
functional model: a Layer holds parameters as pytree leaves and exposes a
*functional bridge* (``functional_call`` / ``functional``) that temporarily
binds an external params pytree and runs ``forward`` — so the same
dygraph-looking module code works under ``jax.jit`` / ``jax.grad`` /
``shard_map`` without a separate "apply" definition.

Differences from the reference, by design:
- No GradNode graph / autograd engine (reference paddle/fluid/eager/): JAX
  vjp/jvp provide autodiff over the functional bridge.
- Parameters are immutable jax Arrays; "in-place" updates replace the leaf.
- Sharding metadata lives on the Parameter wrapper (``dims_mapping``-like
  PartitionSpec), consumed by paddle_tpu.parallel when placing the model on a
  Mesh (reference analogue: DistTensor's TensorDistAttr,
  paddle/phi/core/distributed/auto_parallel/dist_attr.h).
"""

from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype_mod
from ..core.rng import rng_tracker

_default_dtype = jnp.float32


def set_default_dtype(d) -> None:
    """Mirrors ``paddle.set_default_dtype``."""
    global _default_dtype
    _default_dtype = _dtype_mod.convert_dtype(d)


def get_default_dtype():
    return _default_dtype


class Parameter:
    """A trainable leaf: jax Array + metadata.

    Reference analogue: ``paddle.base.framework.Parameter`` / EagerParamBase
    (python/paddle/base/framework.py) — holds trainable flag, optimize
    attributes, and (here) the sharding PartitionSpec used by the parallel
    layer instead of DistTensor dist_attr.
    """

    __slots__ = ("value", "trainable", "sharding", "name", "is_distributed")

    def __init__(self, value: jax.Array, trainable: bool = True,
                 sharding: Optional[Tuple] = None, name: str = ""):
        self.value = value
        self.trainable = trainable
        # PartitionSpec-like tuple of mesh-axis names (or None) per dim.
        self.sharding = sharding
        self.name = name
        # set True by tensor-parallel layers: this param is already a local
        # shard along a TP axis (reference: param.is_distributed in mp_layers).
        self.is_distributed = False

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    def numpy(self):
        return np.asarray(self.value)

    # array-likeness: jnp/np ops consume Parameters directly (reference
    # Parameters ARE tensors; e.g. `x * self.params[i]` in containers)
    def __jax_array__(self):
        return jnp.asarray(self.value)

    def __array__(self, dtype=None):
        return np.asarray(self.value, dtype=dtype)

    def __mul__(self, o):
        return jnp.asarray(self.value) * o

    __rmul__ = __mul__

    def __add__(self, o):
        return jnp.asarray(self.value) + o

    __radd__ = __add__

    def __matmul__(self, o):
        return jnp.asarray(self.value) @ o

    def __rmatmul__(self, o):
        return o @ jnp.asarray(self.value)

    def __repr__(self):
        return (f"Parameter(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, trainable={self.trainable}, "
                f"sharding={self.sharding})")


class Buffer:
    """Non-trainable persistent state (reference: Layer.register_buffer)."""

    __slots__ = ("value", "persistable", "name")

    def __init__(self, value: jax.Array, persistable: bool = True, name: str = ""):
        self.value = value
        self.persistable = persistable
        self.name = name


class Layer:
    """Base module. See module docstring for the functional-bridge design."""

    def __init__(self, name_scope: Optional[str] = None,
                 dtype: Optional[str] = None):
        # reference signature Layer.__init__(name_scope=None,
        # dtype="float32"); name_scope feeds full_name(), dtype is the
        # layer's default parameter dtype
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_forward_pre_hooks", OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", OrderedDict())
        object.__setattr__(self, "_name_scope", name_scope)
        object.__setattr__(self, "_layer_dtype", dtype)

    def full_name(self) -> str:
        base = self._name_scope or type(self).__name__.lower()
        return base

    # -- registration ------------------------------------------------------

    def create_parameter(self, shape, dtype=None, initializer=None,
                         trainable: bool = True, is_bias: bool = False,
                         sharding: Optional[Tuple] = None,
                         default_initializer=None) -> Parameter:
        """Create (but not yet attach) a Parameter. Assign it to an attribute
        to register it, mirroring the reference's create_parameter +
        add_parameter flow (python/paddle/nn/layer/layers.py).

        Precedence: ``initializer`` (user/model-explicit, wins always) >
        the set_global_initializer override > ``default_initializer``
        (the layer's curated default) > Xavier/zeros."""
        from . import initializer as init_mod
        dtype = _dtype_mod.convert_dtype(dtype) if dtype is not None else _default_dtype
        from ..base import LazyGuard
        if LazyGuard._active:
            # abstract init: shape/dtype only, no weight materialization
            value = jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                         jnp.dtype(dtype))
            return Parameter(value, trainable=trainable, sharding=sharding)
        if initializer is None:
            initializer = init_mod._global_default(is_bias)
        if initializer is None:
            initializer = default_initializer
        if initializer is None:
            initializer = init_mod.Constant(0.0) if is_bias else init_mod.XavierUniform()
        value = initializer(shape, dtype)
        return Parameter(value, trainable=trainable, sharding=sharding)

    def add_parameter(self, name: str, param: Optional[Parameter]) -> Optional[Parameter]:
        self._parameters[name] = param
        return param

    def register_buffer(self, name: str, value, persistable: bool = True) -> None:
        if value is not None and not isinstance(value, Buffer):
            value = Buffer(jnp.asarray(value), persistable=persistable)
        self._buffers[name] = value

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    # -- attribute protocol ------------------------------------------------

    def __setattr__(self, name: str, value: Any) -> None:
        if isinstance(value, Parameter):
            if not value.name:
                value.name = name
            self._parameters[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Buffer):
            if not value.name:
                value.name = name
            self._buffers[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            self._sub_layers[name] = value
            self.__dict__.pop(name, None)
        else:
            # plain attribute; shadow any previous registration
            for d in (self._parameters, self._buffers, self._sub_layers):
                d.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        # only called when normal lookup fails
        d = self.__dict__
        params = d.get("_parameters")
        if params is not None and name in params:
            p = params[name]
            return None if p is None else p.value
        bufs = d.get("_buffers")
        if bufs is not None and name in bufs:
            b = bufs[name]
            return None if b is None else b.value
        subs = d.get("_sub_layers")
        if subs is not None and name in subs:
            return subs[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    def __delattr__(self, name: str) -> None:
        for d in (self._parameters, self._buffers, self._sub_layers):
            if name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- traversal ---------------------------------------------------------

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield p, sub
            yield from sub.named_sublayers(prefix=p)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix: str = ""
                         ) -> Iterator[Tuple[str, Parameter]]:
        for name, p in self._parameters.items():
            if p is not None:
                yield (f"{prefix}.{name}" if prefix else name), p
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sp = f"{prefix}.{name}" if prefix else name
            yield from sub.named_parameters(prefix=sp)

    def parameters(self) -> List[Parameter]:
        out = []
        for n, p in self.named_parameters():
            # Stamp the dotted path (deliberate mutation on read): list-form
            # optimizer binding keys by p.name, and those keys must match
            # the dotted grads layer_grad/raw_parameters of THIS root
            # produce. Names are relative to the queried root, so an
            # optimizer built from a CONCATENATION of sublayer lists can
            # collide — Optimizer.__init__ rejects that loudly.
            p.name = n
            out.append(p)
        return out

    def named_buffers(self, prefix: str = "") -> Iterator[Tuple[str, Buffer]]:
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sp = f"{prefix}.{name}" if prefix else name
            yield from sub.named_buffers(prefix=sp)

    def buffers(self) -> List[Buffer]:
        return [b for _, b in self.named_buffers()]

    # -- state dict --------------------------------------------------------

    def state_dict(self, include_non_persistable_buffer: bool = False
                   ) -> Dict[str, jax.Array]:
        """Flat name → Array dict (reference: Layer.state_dict)."""
        out: Dict[str, jax.Array] = OrderedDict()
        for name, p in self.named_parameters():
            out[name] = p.value
        for name, b in self.named_buffers():
            if b.persistable or include_non_persistable_buffer:
                out[name] = b.value
        return out

    def set_state_dict(self, state_dict: Dict[str, Any], strict: bool = True) -> None:
        own_params = dict(self.named_parameters())
        own_bufs = dict(self.named_buffers())
        missing = []
        for name, value in state_dict.items():
            value = jnp.asarray(value)
            if name in own_params:
                p = own_params[name]
                if tuple(p.value.shape) != tuple(value.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: have {tuple(p.value.shape)}, "
                        f"loading {tuple(value.shape)}")
                p.value = value.astype(p.value.dtype)
            elif name in own_bufs:
                own_bufs[name].value = value
            else:
                missing.append(name)
        if strict and missing:
            raise KeyError(f"Unexpected keys in state_dict: {missing}")

    load_dict = set_state_dict

    # -- functional bridge -------------------------------------------------

    def raw_parameters(self) -> Dict[str, jax.Array]:
        """Trainable leaves as a flat dict pytree — the thing you grad over."""
        return OrderedDict((n, p.value) for n, p in self.named_parameters()
                           if p.trainable)

    def raw_state(self) -> Dict[str, jax.Array]:
        """All leaves (params + buffers)."""
        out = OrderedDict((n, p.value) for n, p in self.named_parameters())
        for n, b in self.named_buffers():
            out[n] = b.value
        return out

    @contextlib.contextmanager
    def _bind(self, leaves: Dict[str, jax.Array]):
        """Temporarily swap in external leaf values (tracers under jit)."""
        params = dict(self.named_parameters())
        bufs = dict(self.named_buffers())
        saved: List[Tuple[Any, jax.Array]] = []
        try:
            for name, v in leaves.items():
                tgt = params.get(name) or bufs.get(name)
                if tgt is None:
                    raise KeyError(f"functional_call: unknown leaf {name!r}")
                saved.append((tgt, tgt.value))
                tgt.value = v
            yield
        finally:
            for tgt, old in saved:
                tgt.value = old

    def functional_call(self, leaves: Dict[str, jax.Array], *args, **kwargs):
        """Run forward with ``leaves`` bound in place of stored values.

        This is the jit/grad entry point:
            params = layer.raw_parameters()
            loss = jax.grad(lambda p: layer.functional_call(p, x).sum())(params)
        """
        with self._bind(leaves):
            return self(*args, **kwargs)

    def functional(self) -> Callable:
        """Return ``fn(params, *args, **kwargs)`` — a pure function view."""
        def fn(leaves, *args, **kwargs):
            return self.functional_call(leaves, *args, **kwargs)
        return fn

    # -- hooks (reference: Layer.register_forward_{pre,post}_hook) ---------

    def register_forward_pre_hook(self, hook: Callable):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook: Callable):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- mode / dtype ------------------------------------------------------

    def train(self) -> "Layer":
        object.__setattr__(self, "training", True)
        for l in self.sublayers():
            object.__setattr__(l, "training", True)
        return self

    def eval(self) -> "Layer":
        object.__setattr__(self, "training", False)
        for l in self.sublayers():
            object.__setattr__(l, "training", False)
        return self

    def to(self, dtype=None, device=None) -> "Layer":
        """Cast floating-point leaves (reference: Layer.to / amp O2 cast)."""
        if dtype is not None:
            dt = _dtype_mod.convert_dtype(dtype)
            for _, p in self.named_parameters():
                if jnp.issubdtype(p.value.dtype, jnp.floating):
                    p.value = p.value.astype(dt)
            for _, b in self.named_buffers():
                if jnp.issubdtype(b.value.dtype, jnp.floating):
                    b.value = b.value.astype(dt)
        if device is not None:
            if isinstance(device, str) or isinstance(device, int):
                from ..device import _resolve
                device = _resolve(device)
            elif hasattr(device, "jax_device"):     # Place classes
                device = device.jax_device()
            for _, p in self.named_parameters():
                p.value = jax.device_put(p.value, device)
            for _, b in self.named_buffers():
                b.value = jax.device_put(b.value, device)
        return self

    def astype(self, dtype) -> "Layer":
        return self.to(dtype=dtype)

    def _cast_except(self, dtype, excluded_layers) -> "Layer":
        """Cast all floating leaves except those owned by a layer whose
        type is in ``excluded_layers`` (reference Layer.float/half
        contract — e.g. keep norm layers fp32 under a half() sweep)."""
        if not excluded_layers:
            return self.to(dtype=dtype)
        excluded = tuple(excluded_layers) if isinstance(
            excluded_layers, (list, tuple)) else (excluded_layers,)
        for layer in self.sublayers(include_self=True):
            if isinstance(layer, excluded):
                continue
            for p in layer._parameters.values():
                if p is not None and jnp.issubdtype(p.value.dtype,
                                                    jnp.floating):
                    p.value = p.value.astype(dtype)
            for b in layer._buffers.values():
                if b is not None and jnp.issubdtype(b.value.dtype,
                                                    jnp.floating):
                    b.value = b.value.astype(dtype)
        return self

    def float(self, excluded_layers=None) -> "Layer":
        return self._cast_except("float32", excluded_layers)

    def half(self, excluded_layers=None) -> "Layer":
        return self._cast_except("float16", excluded_layers)

    def bfloat16(self, excluded_layers=None) -> "Layer":
        return self._cast_except("bfloat16", excluded_layers)

    def children(self):
        """Immediate sublayers (reference: Layer.children)."""
        for _, layer in self.named_children():
            yield layer

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def to_static_state_dict(self, destination=None, include_sublayers=True,
                             use_hook=True):
        """Reference: Layer.to_static_state_dict — the static-graph-shaped
        state dict. Trace-based capture keeps one state layout, so this is
        state_dict() (parameters + buffers) under the legacy name."""
        return self.state_dict()

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- call --------------------------------------------------------------

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        lines = []
        extra = self.extra_repr()
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub)
            sub_repr = ("\n  ".join(sub_repr.split("\n")))
            lines.append(f"({name}): {sub_repr}")
        body = ""
        if extra and not lines:
            body = extra
        elif lines:
            body = "\n  " + "\n  ".join(([extra] if extra else []) + lines) + "\n"
        return f"{type(self).__name__}({body})"


class _HookHandle:
    _next_id = 0

    def __init__(self, registry):
        self.id = _HookHandle._next_id
        _HookHandle._next_id += 1
        self._registry = registry

    def remove(self):
        self._registry.pop(self.id, None)


# -- containers (reference: python/paddle/nn/layer/container.py) ------------

class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and not isinstance(layers[0], Layer):
            layers = tuple(layers[0])
        for i, l in enumerate(layers):
            if isinstance(l, tuple):
                name, l = l
            else:
                name = str(i)
            self.add_sublayer(name, l)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x

    def __getitem__(self, idx):
        # reference Sequential supports both positional and named access
        # (container.py Sequential example: model1[0], model2['l1'])
        if isinstance(idx, str):
            return self._sub_layers[idx]
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, layer: Layer) -> "LayerList":
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index: int, sublayer: Layer) -> None:
        """Insert ``sublayer`` before ``index`` (reference:
        nn/layer/container.py LayerList.insert — same bounds contract)."""
        n = len(self._sub_layers)
        if not (isinstance(index, int) and -n <= index < max(n, 1)):
            raise AssertionError(
                f"index should be an integer in range [{-n}, {n})")
        if index < 0:
            index += n
        for i in range(n, index, -1):
            self._sub_layers[str(i)] = self._sub_layers[str(i - 1)]
        self._sub_layers[str(index)] = sublayer

    def extend(self, sublayers) -> "LayerList":
        offset = len(self)
        for i, sublayer in enumerate(sublayers):
            self.add_sublayer(str(offset + i), sublayer)
        return self

    def __setitem__(self, idx: int, layer: Layer):
        idx = idx if idx >= 0 else len(self) + idx
        self._sub_layers[str(idx)] = layer

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for k, v in (sublayers.items() if isinstance(sublayers, dict) else sublayers):
                self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __contains__(self, key):
        return key in self._sub_layers

    def __iter__(self):
        # dict-like: iterate KEYS (reference container.py LayerDict
        # example: `for k in layers_dict: layers_dict[k]`)
        return iter(self._sub_layers)

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def clear(self):
        self._sub_layers.clear()

    def update(self, sublayers):
        """Merge key/layer pairs, overwriting existing keys (reference:
        container.py LayerDict.update)."""
        assert isinstance(sublayers, (dict, LayerDict)) or hasattr(
            sublayers, "__iter__"), \
            "sublayers should be a dict/LayerDict or iterable of pairs"
        if isinstance(sublayers, (dict, LayerDict)):
            for k, v in sublayers.items():
                self.add_sublayer(k, v)
        else:
            for k, v in sublayers:
                self.add_sublayer(k, v)

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def __len__(self):
        return len(self._sub_layers)
