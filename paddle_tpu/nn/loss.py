"""paddle.nn.loss module path (reference: nn/layer/loss.py is also
importable as paddle.nn.loss in doctests) — re-export the loss layers."""

from .layers_extras import *  # noqa: F401,F403
from . import layers_extras as _le

# pull every *Loss class exposed anywhere on paddle_tpu.nn
def _collect():
    import paddle_tpu.nn as _nn
    out = {}
    for name in dir(_nn):
        if name.endswith("Loss") or name in ("CrossEntropyLoss", "MSELoss",
                                             "L1Loss", "NLLLoss", "BCELoss",
                                             "KLDivLoss", "SmoothL1Loss"):
            out[name] = getattr(_nn, name)
    return out

globals().update(_collect())
del _collect
