"""Audio functionals (reference: python/paddle/audio/functional/):
windows, mel scale conversion, filterbanks, stft power spectra, dct."""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "compute_fbank_matrix", "stft", "power_to_db", "create_dct"]


def get_window(window, win_length: int, fftbins: bool = True,
               dtype: str = "float32"):
    """reference: functional/window.py get_window — the full registered
    set (hamming/hann/gaussian/general_gaussian/exponential/triang/
    bohman/blackman/cosine/tukey/taylor). Parameterized kinds take the
    reference's tuple form, e.g. ('gaussian', std). Periodic (fftbins)
    windows are the symmetric (M+1)-point window truncated by one —
    scipy's construction, which the reference wraps."""
    args = ()
    if isinstance(window, (tuple, list)):
        window, *args = window
    if window in ("gaussian", "exponential") and not args:
        raise ValueError(f"The {window!r} window needs one or more "
                         f"parameters — pass a tuple")
    n = win_length
    M = n + 1 if fftbins else n          # build symmetric, then truncate
    k = jnp.arange(M)

    def _sym():
        if window in ("hann", "hanning"):
            return 0.5 - 0.5 * jnp.cos(2 * math.pi * k / (M - 1))
        if window == "hamming":
            return 0.54 - 0.46 * jnp.cos(2 * math.pi * k / (M - 1))
        if window == "blackman":
            return (0.42 - 0.5 * jnp.cos(2 * math.pi * k / (M - 1))
                    + 0.08 * jnp.cos(4 * math.pi * k / (M - 1)))
        if window in ("rect", "boxcar", "ones"):
            return jnp.ones((M,))
        if window == "triang":
            nn = jnp.arange(1, (M + 1) // 2 + 1)
            if M % 2 == 0:
                half = (2 * nn - 1) / M
                return jnp.concatenate([half, half[::-1]])
            half = 2 * nn / (M + 1)
            return jnp.concatenate([half, half[-2::-1]])
        if window == "cosine":
            return jnp.sin(math.pi / M * (k + 0.5))
        if window == "gaussian":
            std = float(args[0])
            return jnp.exp(-0.5 * ((k - (M - 1) / 2) / std) ** 2)
        if window == "general_gaussian":
            p, sig = float(args[0]), float(args[1])
            return jnp.exp(-0.5 * jnp.abs((k - (M - 1) / 2) / sig)
                           ** (2 * p))
        if window == "exponential":
            center = (args[0] if len(args) > 1 and args[0] is not None
                      else (M - 1) / 2)
            tau = float(args[-1])
            return jnp.exp(-jnp.abs(k - center) / tau)
        if window == "bohman":
            x = jnp.abs(2 * k / (M - 1) - 1)
            w = (1 - x) * jnp.cos(math.pi * x) + jnp.sin(math.pi * x) / math.pi
            return w.at[0].set(0.0).at[-1].set(0.0)
        if window == "tukey":
            alpha = float(args[0]) if args else 0.5
            if alpha <= 0:
                return jnp.ones((M,))
            if alpha >= 1:
                return 0.5 - 0.5 * jnp.cos(2 * math.pi * k / (M - 1))
            width = int(alpha * (M - 1) / 2.0)
            edge = 0.5 * (1 + jnp.cos(math.pi * (-1 + 2.0 * k / alpha
                                                 / (M - 1))))
            tail = 0.5 * (1 + jnp.cos(math.pi * (-2.0 / alpha + 1
                                                 + 2.0 * k / alpha
                                                 / (M - 1))))
            w = jnp.ones((M,))
            w = jnp.where(k < width + 1, edge, w)
            return jnp.where(k >= M - width - 1, tail, w)
        if window == "taylor":
            nbar = int(args[0]) if args else 4
            sll = float(args[1]) if len(args) > 1 else 30.0
            B = 10 ** (sll / 20)
            A = math.log(B + math.sqrt(B ** 2 - 1)) / math.pi
            s2 = nbar ** 2 / (A ** 2 + (nbar - 0.5) ** 2)
            ma = jnp.arange(1, nbar)

            def coef(mi):
                num = jnp.prod(1 - mi ** 2 / s2
                               / (A ** 2 + (ma - 0.5) ** 2))
                den = jnp.prod(jnp.where(ma != mi, 1 - mi ** 2 / ma ** 2,
                                         1.0))
                return ((-1) ** (mi + 1)) * num / (2 * den)
            Fm = jnp.stack([coef(float(mi)) for mi in range(1, nbar)])
            xi = (k - (M - 1) / 2) / M
            w = jnp.sum(Fm[:, None]
                        * jnp.cos(2 * math.pi * ma[:, None] * xi[None, :]),
                        axis=0)
            w = 1 + 2 * w
            # normalize by the CENTER value W(xi=0)=1+2*sum(Fm), not the
            # sample max (even M has no sample at the center)
            return w / (1 + 2 * jnp.sum(Fm))
        raise ValueError(f"unknown window {window!r}")

    w = _sym()
    return (w[:-1] if fftbins else w).astype(dtype)


def hz_to_mel(freq, htk: bool = False):
    freq = jnp.asarray(freq, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + freq / 700.0)
    # slaney
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(freq >= min_log_hz,
                     min_log_mel + jnp.log(jnp.maximum(freq, 1e-10)
                                           / min_log_hz) / logstep, mels)


def mel_to_hz(mel, htk: bool = False):
    mel = jnp.asarray(mel, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)), freqs)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0, f_max: float = 11025.0,
                    htk: bool = False):
    mels = jnp.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: str = "slaney"):
    """Triangular mel filterbank [n_mels, n_fft//2+1]
    (reference functional.compute_fbank_matrix)."""
    f_max = f_max or sr / 2
    fft_freqs = jnp.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return weights


def stft(x, n_fft: int = 512, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window: str = "hann",
         center: bool = True, pad_mode: str = "reflect"):
    """[..., T] → complex [..., n_fft//2+1, frames]."""
    from .. import fft as pfft
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    x = jnp.asarray(x)
    wdt = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else "float32"
    w = get_window(window, win_length, dtype=wdt)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad, n_fft - win_length - pad))
    if center:
        pad_width = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad_width, mode=pad_mode)
    T = x.shape[-1]
    n_frames = 1 + (T - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])              # [frames, n_fft]
    frames = x[..., idx] * w                          # [..., frames, n_fft]
    spec = pfft.rfft(frames, axis=-1)                 # [..., frames, bins]
    return jnp.swapaxes(spec, -1, -2)


def power_to_db(magnitude, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    log_spec = 10.0 * jnp.log10(jnp.maximum(magnitude, amin))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return log_spec


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho"):
    """DCT-II matrix [n_mels, n_mfcc] (reference functional.create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        dct = dct * jnp.where(k == 0, 1.0 / math.sqrt(n_mels),
                              math.sqrt(2.0 / n_mels))
    else:
        dct = dct * 2.0
    return dct
