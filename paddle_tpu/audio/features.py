"""Audio feature layers (reference: python/paddle/audio/features/layers.py)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..nn.layer import Layer
from . import functional as AF


class Spectrogram(Layer):
    """|STFT|^power (reference features/layers.py Spectrogram)."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length
        self.win_length = win_length
        self.window = window
        self.power = power
        self.center = center
        self.pad_mode = pad_mode

    def forward(self, x):
        spec = AF.stft(x, self.n_fft, self.hop_length, self.win_length,
                       self.window, self.center, self.pad_mode)
        return jnp.abs(spec) ** self.power


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode)
        fb = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk, norm)
        self.register_buffer("fbank", fb)

    def forward(self, x):
        spec = self.spectrogram(x)                    # [..., bins, frames]
        return jnp.einsum("mf,...ft->...mt", self.fbank, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        # full reference signature (features/layers.py MFCC:352); the
        # stft/window/mel knobs flow through LogMelSpectrogram
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, n_fft=n_fft,
                                         hop_length=hop_length,
                                         win_length=win_length,
                                         window=window, power=power,
                                         center=center, pad_mode=pad_mode,
                                         n_mels=n_mels,
                                         f_min=f_min, f_max=f_max,
                                         htk=htk, norm=norm,
                                         ref_value=ref_value, amin=amin,
                                         top_db=top_db)
        self.register_buffer("dct", AF.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        mel = self.log_mel(x)                          # [..., n_mels, frames]
        return jnp.einsum("mk,...mt->...kt", self.dct, mel)
