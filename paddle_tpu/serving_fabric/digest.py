"""Prefix digests: the compact routing signal a replica advertises.

The router's PREFIX-AFFINITY decision needs to know "which replica's
radix tree already holds the longest prefix of this prompt" WITHOUT
shipping the tree (or the prompt) anywhere. Each replica folds its
tree's top into a :class:`PrefixDigest` on the heartbeat: a set of
ROLLING page fingerprints — ``fp_0 = seed``, ``fp_{i+1} =
blake2b(fp_i || tokens of page i)`` — one entry per page boundary along
every root path. The router replays the same rolling chain over a
queued prompt and the longest ``fp_i`` present in a replica's set IS
the number of whole pages that replica's tree matched (modulo 64-bit
collisions, which only ever cost one misroute, never correctness — the
replica's own admission re-matches exactly).

Properties that make this the right wire shape:

* **Chain-structured, not positional.** A fingerprint commits to the
  ENTIRE token history before it, so two trees sharing page 3's tokens
  but not pages 0-2 can't alias — a plain per-page hash set would.
* **Top-of-tree under a cap.** ``token_paths`` enumerates breadth-first
  and the builder stops at ``max_entries``, so a digest truncates from
  the LEAVES inward: the shared system prompts that drive affinity live
  at the top and survive any cap.
* **Staleness is bounded, not prevented.** A digest is a snapshot at
  ``epoch``; between heartbeats the tree may evict (match overestimates
  → the miss costs one suffix prefill at the routed replica) or grow
  (underestimate → a tie falls back to least-loaded). Both degrade
  toward the non-affinity baseline, never below it.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Optional

import numpy as np

__all__ = ["PrefixDigest"]

_FP_SEED = 0x9E3779B97F4A7C15        # golden-ratio constant; any fixed seed


def _page_fp(parent_fp: int, page_tokens: np.ndarray) -> int:
    """fp of one page given its predecessor chain — blake2b (stable
    across processes/platforms, unlike hash()) truncated to 64 bits."""
    h = hashlib.blake2b(parent_fp.to_bytes(8, "little")
                        + np.ascontiguousarray(page_tokens,
                                               np.int32).tobytes(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "little")


class PrefixDigest:
    """A replica radix tree's routing fingerprint; see module doc."""

    __slots__ = ("page_size", "fps", "epoch", "hit_rate")

    def __init__(self, page_size: int, fps: Iterable[int] = (),
                 epoch: int = 0, hit_rate: Optional[float] = None):
        self.page_size = int(page_size)
        self.fps = {int(f) for f in fps}
        self.epoch = int(epoch)
        # the replica's live pt_serving_prefix_hit_rate reading rides
        # along: a router can deprioritize a replica whose tree is
        # nominally matching but not actually hitting (thrash)
        self.hit_rate = hit_rate

    # -- construction --------------------------------------------------------

    @classmethod
    def from_cache(cls, cache, max_pages: int = 32,
                   max_entries: int = 1024,
                   hit_rate: Optional[float] = None) -> "PrefixDigest":
        """Fold ``cache`` (a ``RadixPrefixCache``) into a digest:
        rolling fps at every page boundary of every root path,
        breadth-first, capped at ``max_entries`` (top-of-tree wins).
        The walk carries the parent fp down the tree, so every page is
        hashed exactly ONCE — a shared system-prompt top is not
        re-hashed per descendant leaf, which matters because the tree's
        epoch (the rebuild trigger) moves on most admissions."""
        ps = cache.page_size
        fps: set = set()
        # (node, fp entering the node, pages already above it)
        frontier = [(c, _FP_SEED, 0)
                    for c in cache.root.children.values()]
        while frontier and len(fps) < max_entries:
            nxt = []
            for node, fp, depth in frontier:
                for i in range(len(node.pages)):
                    if depth >= max_pages or len(fps) >= max_entries:
                        break
                    fp = _page_fp(fp, node.tokens[i * ps:(i + 1) * ps])
                    fps.add(fp)
                    depth += 1
                else:
                    nxt.extend((c, fp, depth)
                               for c in node.children.values())
            frontier = nxt
        return cls(ps, fps, epoch=cache.epoch, hit_rate=hit_rate)

    # -- matching ------------------------------------------------------------

    def match_pages(self, tokens) -> int:
        """Whole pages of ``tokens`` the source tree held at digest
        time: the rolling chain is replayed until its first absence —
        the router-side estimate of the replica's prefix hit."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        fp, n = _FP_SEED, 0
        for i in range(len(toks) // self.page_size):
            fp = _page_fp(fp, toks[i * self.page_size:
                                   (i + 1) * self.page_size])
            if fp not in self.fps:
                break
            n += 1
        return n

    # -- wire form (JSON-safe) -----------------------------------------------

    def to_dict(self) -> Dict[str, object]:
        return {"page_size": self.page_size, "epoch": self.epoch,
                "hit_rate": self.hit_rate,
                "fps": sorted(self.fps)}

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "PrefixDigest":
        return cls(d["page_size"], d.get("fps", ()),
                   epoch=d.get("epoch", 0), hit_rate=d.get("hit_rate"))

    def __len__(self) -> int:
        return len(self.fps)

    def __repr__(self):
        return (f"PrefixDigest(pages={len(self.fps)}, "
                f"epoch={self.epoch}, hit_rate={self.hit_rate})")
