"""Per-tenant weighted fair admission at the router.

The router-level generalization of ``inference.admission`` (whose
``SLOAdmissionPolicy`` stays the per-replica LEAF of the policy tree:
this module decides WHICH tenant's request leaves the global queue,
each replica's policy still decides when its engine takes it). Three
mechanisms, all priced in the cost unit PR 7 established — admitted
UNCACHED-SUFFIX tokens, i.e. prefill work the fabric will actually buy:

* **Weighted fairness** (start-time fair queuing): each tenant carries
  a virtual finish time advanced by ``admitted_cost / weight`` on every
  admission; the eligible request of the LOWEST-vtime tenant goes
  first, so long-run token share converges to the weight ratio without
  any windowed accounting. A new/idle tenant's vtime is clamped up to
  the current minimum so it can't bank idle credit into a burst.
* **Token-bucket quotas**: a tenant's bucket refills ``rate_per_tick``
  each router tick up to ``burst``; a request is eligible only while
  the bucket covers its priced cost (one admission may overdraw to a
  negative balance so a single over-burst request larger than the
  bucket can still eventually run — it then pays the debt in refill
  ticks). ``rate_per_tick=None`` = unmetered.
* **Starvation bound**: any request passed over ``starvation_ticks``
  times is forced through next, quota or not — same contract as the
  per-replica policy's bound, one level up.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence

__all__ = ["TenantSpec", "TenantFairPolicy"]


@dataclass
class TenantSpec:
    """One tenant's share contract."""
    weight: float = 1.0
    rate_per_tick: Optional[float] = None   # uncached tokens/tick; None = ∞
    burst: Optional[float] = None           # bucket cap; default 8× rate

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got "
                             f"{self.weight}")
        if self.rate_per_tick is not None and self.burst is None:
            self.burst = 8.0 * float(self.rate_per_tick)


class TenantFairPolicy:
    """select()/note_admitted() over the ROUTER's queue of
    FabricRequests (anything with ``.tenant``); see module doc.
    Unknown tenants get ``default`` (weight 1, unmetered)."""

    def __init__(self, tenants: Optional[Dict[str, TenantSpec]] = None,
                 default: Optional[TenantSpec] = None,
                 starvation_ticks: int = 256):
        self.tenants = dict(tenants or {})
        self.default = default or TenantSpec()
        self.starvation_ticks = int(starvation_ticks)
        self._vtime: Dict[str, float] = {}
        self._bucket: Dict[str, float] = {}
        self._skips: Dict[object, int] = {}   # _key(req) -> passes skipped
        self.admitted: Dict[str, int] = {}    # per-tenant requests
        self.admitted_tokens: Dict[str, float] = {}
        self.deferred: Dict[str, int] = {}    # select() passes deferred

    def spec(self, tenant: str) -> TenantSpec:
        return self.tenants.get(tenant, self.default)

    @staticmethod
    def _key(req) -> object:
        """Stable identity for the skip map: the router's fid when the
        request has one — id() reuse after a released request could
        otherwise hand a NEW request an inherited near-starvation count
        and let it bypass its tenant's quota."""
        fid = getattr(req, "fid", None)
        return id(req) if fid is None else ("fid", fid)

    # -- clock ---------------------------------------------------------------

    def tick(self) -> None:
        """One router scheduling pass: refill every metered bucket —
        including buckets of UNKNOWN tenants running on a metered
        ``default`` spec (they only exist in ``_bucket``; refilling
        just the configured tenants would drain them once and block
        them forever)."""
        for t in set(self.tenants) | set(self._bucket):
            spec = self.spec(t)
            if spec.rate_per_tick is None:
                continue
            cur = self._bucket.get(t, float(spec.burst))
            self._bucket[t] = min(float(spec.burst),
                                  cur + float(spec.rate_per_tick))

    def _bucket_covers(self, tenant: str, cost: float) -> bool:
        spec = self.spec(tenant)
        if spec.rate_per_tick is None:
            return True
        if float(spec.burst) <= 0.0:
            return False          # zero quota: only starvation admits
        return self._bucket.get(tenant, float(spec.burst)) >= min(
            cost, float(spec.burst))
        # (a request pricier than the whole burst is admittable at a
        # FULL bucket — it overdraws and repays; otherwise it could
        # never run at all)

    # -- selection -----------------------------------------------------------

    def select(self, queue: Sequence, price: Callable[[object], float]
               ) -> Optional[int]:
        """Index of the request to release next, or None to defer all
        this pass. ``price(req)`` → predicted uncached-suffix tokens."""
        if not queue:
            return None
        # NO pruning against ``queue`` here: the router passes a
        # filtered VIEW (capacity-blocked requests excluded), and
        # dropping an absent request's counter would reset the
        # starvation clock of exactly the requests waiting hardest.
        # Stale ids of long-gone requests are swept only when the map
        # outgrows any plausible live queue.
        if len(self._skips) > 4 * len(queue) + 4096:
            live = {self._key(r) for r in queue}
            self._skips = {k: v for k, v in self._skips.items()
                           if k in live}
        for i, req in enumerate(queue):
            if self._skips.get(self._key(req), 0) >= self.starvation_ticks:
                return i
        # eligible = bucket-covered; among those, lowest tenant vtime,
        # FIFO within a tenant (first queue hit for that tenant)
        best_i, best_key = None, None
        seen_tenants: set = set()
        for i, req in enumerate(queue):
            t = req.tenant
            if t in seen_tenants:
                continue              # FIFO within tenant
            seen_tenants.add(t)
            if not self._bucket_covers(t, max(1.0, float(price(req)))):
                continue
            key = (self._vtime.get(t, 0.0), i)
            if best_key is None or key < best_key:
                best_i, best_key = i, key
        if best_i is None:
            for req in queue:
                k = self._key(req)
                self._skips[k] = self._skips.get(k, 0) + 1
                self.deferred[req.tenant] = \
                    self.deferred.get(req.tenant, 0) + 1
        return best_i

    def note_admitted(self, queue: Sequence, chosen: int,
                      cost: float) -> None:
        """The router really dispatched ``queue[chosen]`` at ``cost``
        uncached tokens: advance the tenant's vtime, drain its bucket,
        charge a skip to everyone passed over."""
        req = queue[chosen]
        t = req.tenant
        spec = self.spec(t)
        cost = max(1.0, float(cost))
        floor = min((self._vtime.get(r.tenant, 0.0) for r in queue),
                    default=0.0)
        # idle-credit clamp: a tenant can't return from idle with an
        # ancient vtime and lock everyone else out while it catches up
        vt = max(self._vtime.get(t, 0.0), floor)
        self._vtime[t] = vt + cost / float(spec.weight)
        if spec.rate_per_tick is not None:
            self._bucket[t] = self._bucket.get(
                t, float(spec.burst)) - cost
        self.admitted[t] = self.admitted.get(t, 0) + 1
        self.admitted_tokens[t] = self.admitted_tokens.get(t, 0.0) + cost
        self._skips.pop(self._key(req), None)
        for i, r in enumerate(queue):
            if i != chosen:
                k = self._key(r)
                self._skips[k] = self._skips.get(k, 0) + 1
