"""Replica: one ContinuousBatchingEngine behind the fabric verb set.

A replica is the server side of :class:`~.transport.FabricTransport`:
it owns an engine (with an ``engine=<name>`` metric label so N replicas
in one process never merge registry series), answers the heartbeat with
a load/latency snapshot plus its :class:`~.digest.PrefixDigest`, and
exposes the KV-page handoff pair (extract/adopt) the disaggregation
path rides on. Roles:

* ``"both"`` (default) — takes any traffic;
* ``"decode"`` — never assigned a disaggregated prefill job;
* ``"prefill"`` — ONLY takes prefill jobs (cold long prompts routed for
  chunked prefill + handoff); excluded from normal routing while any
  both/decode replica is alive.

The digest is rebuilt lazily: only when the tree's mutation epoch moved
since the last heartbeat — a hot steady-state tree costs one dict
lookup per status call, not a tree walk.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..inference.generation import GenerationConfig
from ..observability.tracing import TRACER as _TRACE
from .digest import PrefixDigest

__all__ = ["Replica", "build_replicas"]

_KNOB_FIELDS = ("do_sample", "temperature", "top_k", "top_p",
                "eos_token_id")


class Replica:
    """See module doc. ``engine`` should be constructed with
    ``name=<this name>`` (build_replicas does) so its registry series
    carry the replica label."""

    def __init__(self, engine, name: str, role: str = "both",
                 digest_max_pages: int = 32,
                 digest_max_entries: int = 1024):
        if role not in ("both", "decode", "prefill"):
            raise ValueError(f"unknown replica role {role!r}")
        self.engine = engine
        self.name = name
        self.role = role
        self.digest_max_pages = int(digest_max_pages)
        self.digest_max_entries = int(digest_max_entries)
        self._digest: Optional[dict] = None
        self._digest_epoch = -1
        self._lat_cache: tuple = (-1, {})
        self._spec_k0 = int(getattr(engine, "spec_k", 0))

    # -- fabric verb set -----------------------------------------------------

    def submit(self, req: dict) -> int:
        """Router payload → engine.submit. Absent knobs mean the
        engine's default GenerationConfig — the pass-through contract
        the 1-replica parity anchor rides on."""
        knobs = req.get("knobs")
        gc = None
        if knobs:
            base = self.engine.cfg
            vals = {k: knobs.get(k, getattr(base, k))
                    for k in _KNOB_FIELDS}
            gc = GenerationConfig(max_new_tokens=base.max_new_tokens,
                                  seed=base.seed, **vals)
        return self.engine.submit(
            np.asarray(req["prompt"], np.int32),
            max_new_tokens=req.get("max_new_tokens"),
            generation_config=gc,
            rseed=req.get("rseed"),
            replay_prefix=req.get("replay"),
            trace=req.get("trace"))

    def poll(self) -> dict:
        """One scheduler tick + completions. Emissions are NEW tokens
        only (a replay prefix is never re-emitted); ``finished`` maps
        rid → the FULL stream including any replay prefix, which is the
        router's authoritative copy."""
        emitted = self.engine.step() if self.engine.has_work() else []
        finished = self.engine.take_finished()
        if finished:
            # drain boundary with retirements: refresh the replica's
            # registry series (per-engine labels) + sentry tick
            self.engine.publish_metrics()
        out = {"emitted": [[int(r), int(t)] for r, t in emitted],
               "finished": {int(r): np.asarray(v).tolist()
                            for r, v in finished.items()}}
        # piggyback finished replica-side spans: over TCP this replica
        # never owns a trace root, so drain_for_wire ships them to the
        # router for stitching; in-proc (shared tracer) it's a no-op
        tr = getattr(self.engine, "_tracer", None) or _TRACE
        if tr.enabled:
            spans = tr.drain_for_wire()
            if spans:
                out["spans"] = spans
        return out

    def status(self) -> dict:
        eng = self.engine
        # the router heartbeats every step: percentiles over the 10k/
        # 100k windows must not run per tick — they only change when a
        # request retires (same epoch-keyed discipline as the digest)
        key = eng._requests_retired
        if self._lat_cache[0] != key:
            self._lat_cache = (key, eng.latency_stats())
        lat = self._lat_cache[1]
        active = sum(s is not None for s in eng._slots)
        out = {"name": self.name, "role": self.role,
               "max_batch": eng.max_batch,
               "active": active,
               "free_slots": eng.max_batch - active,
               "queued": len(eng._queue),
               "free_pages": len(eng._free),
               "total_pages": eng._total_pages,
               "itl_p99_s": lat.get("itl_p99_s"),
               "ttft_p99_s": lat.get("ttft_p99_s"),
               "prefix_hit_rate": None,
               "digest": None}
        if eng._prefix is not None:
            ps = eng.prefix_stats()
            out["prefix_hit_rate"] = ps.get("prefix_hit_rate")
            if eng._prefix.epoch != self._digest_epoch:
                self._digest = PrefixDigest.from_cache(
                    eng._prefix, max_pages=self.digest_max_pages,
                    max_entries=self.digest_max_entries,
                    hit_rate=out["prefix_hit_rate"]).to_dict()
                self._digest_epoch = eng._prefix.epoch
            elif self._digest is not None:
                self._digest["hit_rate"] = out["prefix_hit_rate"]
            out["digest"] = self._digest
        return out

    def extract(self, tokens) -> Optional[dict]:
        return self.engine.serialize_pages(np.asarray(tokens, np.int32))

    def adopt(self, payload: dict) -> int:
        return len(self.engine.adopt_pages(payload))

    def cancel(self, rid: int) -> bool:
        """Kill local ``rid`` now, freeing its slot/pages (front-door
        deadline miss / client disconnect / slow-loris eviction)."""
        return bool(self.engine.cancel(int(rid)))

    def configure(self, knobs: dict) -> dict:
        """Apply runtime knobs; returns what actually took effect.

        ``spec_k``: brownout draft-budget cap, clamped to
        ``[1, construction-time spec_k]`` — never toggled through 0
        (the draft history only exists when the engine was built
        speculative, and the 0↔k edge would flip executable shapes
        mid-run). ``None`` restores the construction-time value. The
        decode executable cache keys on the (spec_k+1) block width, so
        a shrink is a cache switch, not a recompile storm, and spec
        output stays verification-exact at any k."""
        applied: Dict[str, object] = {}
        if "spec_k" in knobs and self._spec_k0 > 0:
            want = knobs["spec_k"]
            if want is None:
                self.engine.spec_k = self._spec_k0
            else:
                self.engine.spec_k = max(1, min(int(want),
                                                self._spec_k0))
            applied["spec_k"] = self.engine.spec_k
        return applied


def build_replicas(model, n: int, roles: Optional[List[str]] = None,
                   names: Optional[List[str]] = None,
                   replica_cls=Replica, **engine_kwargs) -> List[Replica]:
    """N same-model in-process replicas (the CI/bench fabric shape).
    ``engine_kwargs`` go to every ContinuousBatchingEngine;
    ``prefix_cache`` defaults ON — affinity routing and handoff both
    need the tree."""
    from ..inference.serving import ContinuousBatchingEngine
    engine_kwargs.setdefault("prefix_cache", True)
    roles = list(roles or ["both"] * n)
    if len(roles) != n:
        raise ValueError(f"{n} replicas need {n} roles, got {len(roles)}")
    names = list(names or [f"r{i}" for i in range(n)])
    if len(set(names)) != n:
        raise ValueError(f"replica names must be unique: {names}")
    return [replica_cls(
        ContinuousBatchingEngine(model, name=names[i], **engine_kwargs),
        names[i], role=roles[i]) for i in range(n)]
