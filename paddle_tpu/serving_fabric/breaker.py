"""Per-replica circuit breaker: a transport wrapper that makes a HUNG
replica indistinguishable from a crashed one (ISSUE 16).

PR 12's failover only fires when an op RAISES — a replica that accepts
the connection and then never answers stalls the router forever, which
under real traffic is the common failure (GC pause, wedged accelerator,
network partition half-open). :class:`BreakerTransport` wraps any
:class:`~.transport.FabricTransport` and adds:

* **Op-class timeouts** — each verb runs on a worker thread and must
  answer within its class budget (a poll that moves a scheduler tick
  gets more than a status heartbeat; extract/adopt move KV pages and
  get the most). A miss raises :class:`~.transport.ReplicaDown`, the
  exact signal PR 12's replay-exact failover already handles — the
  breaker converts "hung" into "crashed" and the recovery machinery
  downstream needs no new cases.
* **The breaker lifecycle** — a trip OPENs the replica (ops fail fast,
  no thread spent) for ``open_cooldown_s``; then HALF-OPEN: the
  router's probe loop calls :meth:`probe`, which must see
  ``probe_successes`` consecutive good status+poll round-trips before
  the breaker CLOSEs and the router readmits. The probe runs a real
  ``poll`` on purpose: a wedged replica can keep heartbeating
  (``status`` is served off cached gauges) while its tick loop is
  stuck — readmission must demonstrate *progress*, not liveness.
* **Serialized access** — one lock per replica held for the duration of
  each op. Engines are not thread-safe; the lock means a stuck op
  leaves followers queued (they time out waiting, which is correct:
  the replica IS unavailable) instead of racing the engine. A follower
  that never got the lock gives up without touching the replica, so an
  abandoned op can't fire late against a recovered engine.

Wrap order: ``BreakerTransport(InProcTransport(...))`` or
``BreakerTransport(TcpTransport(...))``. Chaos hooks (``kill``,
``hang``, ``unhang``) and any other inner extras pass through via
``__getattr__``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..observability.metrics import REGISTRY as _REG
from ..observability.tracing import TRACER as _TRACE
from .transport import FabricTransport, ReplicaDown

__all__ = ["BreakerTransport", "DEFAULT_OP_TIMEOUTS"]

# Generous by default (first ops pay jit compiles on the CPU CI shape);
# tests and latency-sensitive deployments pass tighter budgets.
DEFAULT_OP_TIMEOUTS: Dict[str, float] = {
    "submit": 10.0, "poll": 30.0, "status": 5.0,
    "extract": 60.0, "adopt": 60.0, "cancel": 10.0, "configure": 10.0,
}


class _State:
    __slots__ = ("mode", "open_until", "successes", "why")

    def __init__(self):
        self.mode = "closed"            # closed | open | half-open
        self.open_until = 0.0
        self.successes = 0
        self.why = ""


class BreakerTransport(FabricTransport):
    """See module doc."""

    def __init__(self, inner: FabricTransport,
                 op_timeouts: Optional[Dict[str, float]] = None,
                 open_cooldown_s: float = 1.0,
                 probe_successes: int = 2,
                 probe_timeout_s: float = 2.0,
                 clock=time.monotonic):
        self.inner = inner
        self.op_timeouts = dict(DEFAULT_OP_TIMEOUTS)
        if op_timeouts:
            self.op_timeouts.update(
                {k: float(v) for k, v in op_timeouts.items()})
        self.open_cooldown_s = float(open_cooldown_s)
        self.probe_successes = int(probe_successes)
        # probes get their OWN (tight) budget: a probe against a
        # still-hung replica must not stall the router's pass for a
        # full op budget — the fabric has healthy replicas to drive
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        self._states: Dict[str, _State] = {}
        self._locks: Dict[str, threading.RLock] = {}
        self._meta = threading.Lock()
        self.trips = 0

    # -- plumbing ------------------------------------------------------------

    def __getattr__(self, item):
        # chaos hooks (kill/hang/unhang), alive(), close(), ... pass
        # through to the wrapped transport
        return getattr(self.inner, item)

    def _st(self, name: str) -> _State:
        with self._meta:
            st = self._states.get(name)
            if st is None:
                st = self._states[name] = _State()
                self._locks[name] = threading.RLock()
            return st

    def _lock(self, name: str) -> threading.RLock:
        self._st(name)
        return self._locks[name]

    def _trip(self, name: str, why: str) -> None:
        st = self._st(name)
        st.mode = "open"
        st.open_until = self._clock() + self.open_cooldown_s
        st.successes = 0
        st.why = why
        self.trips += 1
        if _REG.enabled:
            _REG.counter("pt_frontdoor_breaker_open_total",
                         "circuit-breaker trips (hung or crashed "
                         "replica opened)").inc(replica=name)

    def _run(self, name: str, op: str, fn, trip: bool = True,
             timeout: Optional[float] = None):
        """Run ``fn`` under the replica lock on a worker thread with the
        op-class budget. Timeout / inner ReplicaDown trip the breaker
        (unless ``trip=False``: probe handles its own state)."""
        st = self._st(name)
        if st.mode == "open" and trip:
            if self._clock() < st.open_until:
                raise ReplicaDown(
                    name, f"breaker open ({st.why}); "
                          f"probe due in "
                          f"{max(0.0, st.open_until - self._clock()):.2f}s")
            # cooldown elapsed but not yet probed healthy: still fail
            # fast — only probe() readmits
            raise ReplicaDown(name, f"breaker open ({st.why}); "
                                    f"awaiting half-open probe")
        if timeout is None:
            timeout = self.op_timeouts.get(op, 30.0)
        lock = self._lock(name)
        box: dict = {}
        done = threading.Event()

        def work():
            # bounded wait for the lock: if a stuck op holds it past
            # our own budget, give up WITHOUT touching the replica —
            # a late fire against a recovered engine would race it
            if not lock.acquire(timeout=timeout * 2):
                box["e"] = ReplicaDown(
                    name, f"{op}: queued behind a stuck op")
                done.set()
                return
            try:
                box["r"] = fn()
            except BaseException as e:       # noqa: BLE001 — relayed
                box["e"] = e
            finally:
                lock.release()
                done.set()

        t = threading.Thread(target=work, daemon=True,
                             name=f"breaker-{name}-{op}")
        t.start()
        done.wait(timeout)
        if not done.is_set():
            why = f"{op} exceeded {timeout:g}s op budget (hung)"
            if trip:
                self._trip(name, why)
            raise ReplicaDown(name, why)
        err = box.get("e")
        if err is not None:
            if isinstance(err, ReplicaDown) and trip:
                self._trip(name, str(err))
            raise err
        return box["r"]

    # -- breaker lifecycle ---------------------------------------------------

    def state(self, name: str) -> str:
        return self._st(name).mode

    def retry_after_ms(self, name: Optional[str] = None
                       ) -> Optional[float]:
        """Soonest half-open window: remaining cooldown for ``name``,
        or the minimum across all open breakers. None = nothing open
        (the caller falls back to its own default)."""
        now = self._clock()
        names = [name] if name is not None else list(self._states)
        waits = [max(0.0, self._states[n].open_until - now) * 1000.0
                 for n in names
                 if n in self._states
                 and self._states[n].mode in ("open", "half-open")]
        return min(waits) if waits else None

    def probe(self, name: str) -> bool:
        """Half-open probe; True once the breaker CLOSEd and the router
        may readmit ``name``. Call periodically for replicas the router
        holds as dead — cheap while the cooldown runs (no I/O)."""
        st = self._st(name)
        if st.mode == "closed":
            return True
        if st.mode == "open" and self._clock() < st.open_until:
            return False
        st.mode = "half-open"
        try:
            # liveness AND progress: a wedged tick loop can still
            # answer status, so the probe drives a real poll through
            # the probe budget
            self._run(name, "status",
                      lambda: self.inner.status(name), trip=False,
                      timeout=self.probe_timeout_s)
            self._run(name, "poll",
                      lambda: self.inner.poll(name), trip=False,
                      timeout=self.probe_timeout_s)
        except Exception as e:               # noqa: BLE001 — any fault
            st.mode = "open"
            st.open_until = self._clock() + self.open_cooldown_s
            st.successes = 0
            st.why = f"half-open probe failed: {e}"
            return False
        st.successes += 1
        if st.successes >= self.probe_successes:
            st.mode = "closed"
            st.open_until = 0.0
            st.successes = 0
            st.why = ""
            return True
        return False

    def open_names(self) -> List[str]:
        return [n for n, st in self._states.items()
                if st.mode in ("open", "half-open")]

    # -- verb set ------------------------------------------------------------

    def replica_names(self) -> List[str]:
        return self.inner.replica_names()

    def submit(self, name, req):
        # each breaker-mediated submit ATTEMPT is a sibling span under
        # the request's trace (req carries the wire context) — retries
        # and hedges show up side by side, tagged with their outcomes
        sp = None
        if _TRACE.enabled and isinstance(req, dict) and req.get("trace"):
            sp = _TRACE.start("breaker::attempt", parent=req["trace"],
                              tags={"replica": name, "op": "submit",
                                    "mode": self._st(name).mode})
        try:
            out = self._run(name, "submit",
                            lambda: self.inner.submit(name, req))
        except BaseException as e:           # noqa: BLE001 — relayed
            if sp is not None:
                sp.tag(outcome=type(e).__name__).end()
            raise
        if sp is not None:
            sp.tag(outcome="ok").end()
        return out

    def poll(self, name):
        return self._run(name, "poll", lambda: self.inner.poll(name))

    def status(self, name):
        return self._run(name, "status",
                         lambda: self.inner.status(name))

    def extract(self, name, tokens):
        return self._run(name, "extract",
                         lambda: self.inner.extract(name, tokens))

    def adopt(self, name, payload):
        return self._run(name, "adopt",
                         lambda: self.inner.adopt(name, payload))

    def cancel(self, name, rid):
        return self._run(name, "cancel",
                         lambda: self.inner.cancel(name, rid))

    def configure(self, name, knobs):
        return self._run(name, "configure",
                         lambda: self.inner.configure(name, knobs))
