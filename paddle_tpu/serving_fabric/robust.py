"""Front-door robustness vocabulary (ISSUE 16): typed rejections,
jittered backoff, and the load-shedding ladder.

Production serving treats overload and partial failure as the normal
case — admission must be able to say NO, and every no must be *typed*
(the client learns what happened and when to retry) and *bounded* (a
refusal costs the fabric nothing). Three pieces:

* **Typed rejections** — :class:`FabricRejected` subclasses carrying
  ``kind`` + ``retry_after_ms``. They subclass RuntimeError so code
  written against the PR 12 fabric ("every replica is down" is fatal)
  keeps working, while the front door and :class:`~.client.FabricClient`
  branch on the type: ``Overloaded``/``AllReplicasDown`` are retryable
  with a server-suggested delay, ``DeadlineExceeded`` is not.
* **Backoff** — full-jitter exponential delay (the AWS architecture-blog
  shape, same policy the resilience PR's checkpoint I/O retry uses):
  ``uniform(0, min(cap, base * 2^attempt))``, floored by any server
  ``retry_after`` hint so a herd of rejected clients decorrelates
  *above* the server's own recovery estimate.
* **LoadShedder** — the ladder the router consults at submit and each
  scheduling pass. Signals are the same ones the PR 10 sentry watches
  (global queue depth, router-boundary TTFT/ITL p99); the response is
  graduated: level 1 SHEDS the lowest-weight tenants (weights from
  :class:`~.fair.TenantFairPolicy` — paying tenants keep flowing),
  level 2 BROWNS OUT (additionally defer cold prefills and cap replica
  ``spec_k`` so the fabric spends its FLOPs on admitted decodes).
  Escalation needs ``breach_ticks`` consecutive bad passes and recovery
  needs ``recover_ticks`` good ones — no flapping at the threshold.
"""

from __future__ import annotations

import random
from typing import Dict, Optional

from ..observability.metrics import REGISTRY as _REG

__all__ = ["FabricRejected", "Overloaded", "AllReplicasDown",
           "DeadlineExceeded", "Backoff", "LoadShedder"]


class FabricRejected(RuntimeError):
    """Base of every typed front-door refusal. ``retry_after_ms`` is
    the server's recovery estimate (None = caller's own policy)."""

    kind = "rejected"

    def __init__(self, msg: str, retry_after_ms: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_ms = (None if retry_after_ms is None
                               else float(retry_after_ms))

    def to_wire(self) -> dict:
        out = {"kind": self.kind, "error": str(self)}
        if self.retry_after_ms is not None:
            out["retry_after_ms"] = self.retry_after_ms
        return out


class Overloaded(FabricRejected):
    """Admission said no: the shed ladder is active for this tenant (or
    the global queue hit its hard cap). Retry after the hint."""
    kind = "overloaded"


class AllReplicasDown(FabricRejected):
    """Every replica is dead or breaker-open. Retryable when a breaker
    transport is probing (``retry_after_ms`` = the soonest half-open
    window); fatal-for-now otherwise."""
    kind = "all_down"


class DeadlineExceeded(FabricRejected):
    """The request's TTFT or total deadline passed; the fabric cancelled
    it and freed its slot/pages. Not retryable — the budget is spent."""
    kind = "deadline"


class Backoff:
    """Full-jitter exponential backoff: attempt ``n`` sleeps
    ``uniform(0, min(cap, base * 2^n))`` seconds, floored by any server
    retry_after hint. Deterministic under a seeded rng (tests)."""

    def __init__(self, base_s: float = 0.05, cap_s: float = 2.0,
                 rng: Optional[random.Random] = None):
        if base_s <= 0 or cap_s < base_s:
            raise ValueError(f"need 0 < base_s <= cap_s, got "
                             f"({base_s}, {cap_s})")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self._rng = rng or random.Random()

    def delay_s(self, attempt: int,
                retry_after_ms: Optional[float] = None) -> float:
        hi = min(self.cap_s, self.base_s * (2.0 ** max(0, attempt)))
        d = self._rng.uniform(0.0, hi)
        if retry_after_ms is not None:
            d = max(d, retry_after_ms / 1000.0)
        return d


class LoadShedder:
    """See module doc. The router owns one and calls:

    * ``observe(queue_depth, lat)`` once per scheduling pass (``lat``
      is the router's ``latency_stats()`` dict, may be empty);
    * ``admit(tenant, weight, queue_depth)`` at submit — raises
      :class:`Overloaded` when the ladder sheds this tenant or the
      queue hit ``queue_cap``;
    * ``defer_cold(uncached_tokens)`` at dispatch — True while the
      brownout level defers this cold prefill.

    ``level`` is 0 (normal), 1 (shed), 2 (brownout). Tenants at the
    MAXIMUM weight seen are never shed by level 1; level 2 sheds every
    tenant below the max and defers cold prefills at or above
    ``cold_defer_tokens``. ``spec_k_cap`` is the brownout draft budget
    the router pushes to replicas via ``transport.configure``."""

    def __init__(self, queue_depth_hi: int = 32, queue_depth_lo: int = 8,
                 queue_cap: Optional[int] = 256,
                 ttft_p99_ceiling_s: Optional[float] = None,
                 itl_p99_ceiling_s: Optional[float] = None,
                 breach_ticks: int = 2, recover_ticks: int = 8,
                 cold_defer_tokens: int = 256, spec_k_cap: int = 1,
                 retry_after_ms: float = 250.0):
        if queue_depth_lo > queue_depth_hi:
            raise ValueError("need queue_depth_lo <= queue_depth_hi")
        self.queue_depth_hi = int(queue_depth_hi)
        self.queue_depth_lo = int(queue_depth_lo)
        self.queue_cap = None if queue_cap is None else int(queue_cap)
        self.ttft_p99_ceiling_s = ttft_p99_ceiling_s
        self.itl_p99_ceiling_s = itl_p99_ceiling_s
        self.breach_ticks = int(breach_ticks)
        self.recover_ticks = int(recover_ticks)
        self.cold_defer_tokens = int(cold_defer_tokens)
        self.spec_k_cap = int(spec_k_cap)
        self.retry_after_ms = float(retry_after_ms)
        self.level = 0
        self._bad = 0
        self._good = 0
        self._max_weight = 1.0
        self.shed: Dict[str, int] = {}      # tenant -> rejections
        self.transitions = 0

    # -- signals -------------------------------------------------------------

    def _breached(self, queue_depth: int, lat: dict) -> bool:
        if queue_depth >= self.queue_depth_hi:
            return True
        if self.ttft_p99_ceiling_s is not None:
            v = lat.get("ttft_p99_s")
            if v is not None and v > self.ttft_p99_ceiling_s:
                return True
        if self.itl_p99_ceiling_s is not None:
            v = lat.get("itl_p99_s")
            if v is not None and v > self.itl_p99_ceiling_s:
                return True
        return False

    def observe(self, queue_depth: int, lat: Optional[dict] = None
                ) -> int:
        """One scheduling pass: update the ladder, return the level."""
        if self._breached(queue_depth, lat or {}):
            self._bad += 1
            self._good = 0
            if self._bad >= self.breach_ticks and self.level < 2:
                self.level += 1
                self._bad = 0
                self.transitions += 1
        else:
            self._bad = 0
            if self.level and queue_depth <= self.queue_depth_lo:
                self._good += 1
                if self._good >= self.recover_ticks:
                    self.level -= 1
                    self._good = 0
                    self.transitions += 1
            else:
                self._good = 0
        if _REG.enabled:
            _REG.gauge("pt_frontdoor_shed_level",
                       "load-shedding ladder level (0=normal, 1=shed, "
                       "2=brownout)").set(self.level)
        return self.level

    # -- decisions -----------------------------------------------------------

    def admit(self, tenant: str, weight: float,
              queue_depth: int) -> None:
        """Raise :class:`Overloaded` when this submission must be shed;
        return silently otherwise."""
        self._max_weight = max(self._max_weight, float(weight))
        why = None
        if self.queue_cap is not None and queue_depth >= self.queue_cap:
            why = (f"global queue at hard cap ({self.queue_cap}); "
                   f"shedding all tenants")
        elif self.level >= 1 and float(weight) < self._max_weight:
            why = (f"shed level {self.level}: tenant {tenant!r} "
                   f"(weight {weight}) below the protected tier "
                   f"({self._max_weight})")
        if why is None:
            return
        self.shed[tenant] = self.shed.get(tenant, 0) + 1
        if _REG.enabled:
            _REG.counter("pt_frontdoor_shed_total",
                         "submissions rejected by the shed ladder").inc(
                tenant=tenant)
        raise Overloaded(why, retry_after_ms=self.retry_after_ms)

    def defer_cold(self, uncached_tokens: int) -> bool:
        """Brownout: True while a cold prefill this expensive should
        keep waiting in the global queue (running decodes keep their
        ITL; the queue's fairness machinery still orders the wait)."""
        return (self.level >= 2
                and uncached_tokens >= self.cold_defer_tokens)

    def stats(self) -> Dict[str, object]:
        return {"level": self.level, "transitions": self.transitions,
                "shed": dict(self.shed)}
