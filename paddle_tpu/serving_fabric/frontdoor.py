"""FrontDoor: the concurrent streaming server in front of a
:class:`~.router.ServingFabric` (ISSUE 16 tentpole).

PR 12's TCP transport connects the ROUTER to its replicas; nothing yet
connects CLIENTS to the router. This module is that edge, built for the
traffic assumptions of the north star (many concurrent clients, some of
them slow, dead, or duplicated):

* **Framing** — length-bounded newline-JSON, the same wire idiom as the
  replica transport. A line over ``max_line_bytes`` closes the
  connection (unbounded-buffer defense); a bounded line that fails to
  parse (torn frame) gets an ``error`` event and the connection LIVES —
  one corrupt request must not kill a multiplexed client's other
  streams. Every server event carries a per-connection ``seq`` so
  clients can assert ordered, gapless delivery.
* **Streaming** — one driver thread steps the fabric and fans committed
  tokens out to per-connection OUTBOXES as drains commit them. Outboxes
  are bounded queues serviced by per-connection writer threads: a
  slow-loris client (reads stalled, outbox full) never blocks the
  driver — its requests are CANCELLED (slot/pages freed through the
  engine's one ``_free_slot`` path) and the connection is closed.
  Mid-stream disconnect does the same via the reader thread.
* **Idempotent retry (dedupe)** — clients name requests with their own
  ``id``. The server keeps a per-id stream record (rseed = the first
  attempt's fabric id, committed tokens) surviving the connection, so
  a retry RESUMES: resubmitted with the original rseed and the
  committed tokens as ``replay_prefix``, the engine never re-emits the
  prefix and the retry delivers exactly the tokens the client lacks
  (``have``) — zero duplicated, zero lost. A retry while the previous
  connection still lives is a TAKEOVER (the new connection owns the
  stream; the old one is told), which is what makes the client's
  hedged attempt safe: at most one attempt owns a stream.
* **Typed refusals** — admission errors (:class:`~.robust.Overloaded`,
  :class:`~.robust.AllReplicasDown`) and deadline cancellations surface
  as ``reject`` events carrying ``kind`` + ``retry_after_ms``; nothing
  is silently dropped and no client fault can raise out of the server
  loops.

Wire protocol (client → server)::

    {"op": "submit", "id": "req-1", "prompt": [...],
     "max_new_tokens": 32, "tenant": "t0", "knobs": {...},
     "ttft_deadline_ms": 500, "deadline_ms": 10000, "have": 0}
    {"op": "cancel", "id": "req-1"}
    {"op": "ping"}

Server → client events (all carry ``seq``)::

    {"ev": "ack",       "id", "seq"}
    {"ev": "tok",       "id", "seq", "toks": [..]}      # incremental
    {"ev": "done",      "id", "seq", "toks": [..], "n": total}
    {"ev": "reject",    "id", "seq", "kind", "error", "retry_after_ms"}
    {"ev": "cancelled", "id", "seq", "reason"}
    {"ev": "error",           "seq", "error"}           # torn frame
    {"ev": "pong",            "seq"}
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability.metrics import REGISTRY as _REG
from ..observability.tracing import TRACER as _TRACE
from .robust import FabricRejected
from .router import ServingFabric

__all__ = ["FrontDoor"]


class _Conn:
    """One client connection: reader thread (ops), writer thread
    (bounded outbox), per-connection event sequence."""

    def __init__(self, sock: socket.socket, outbox_max: int):
        self.sock = sock
        self.outbox: "queue.Queue" = queue.Queue(maxsize=outbox_max)
        self.seq = 0
        self.lock = threading.Lock()     # seq + liveness
        self.open = True
        self.ids: set = set()            # stream ids this conn owns
        # writer-blocked-in-sendall marker: the OS absorbs small event
        # volumes into socket buffers, so a slow-loris peer shows up as
        # a sendall that never returns long before the outbox fills —
        # the driver checks this age in _flush
        self.writing_since: Optional[float] = None

    def send(self, ev: dict) -> bool:
        """Enqueue an event; False when the outbox is FULL (slow
        client) or the connection already closed — never blocks."""
        with self.lock:
            if not self.open:
                return False
            ev = dict(ev)
            ev["seq"] = self.seq
            self.seq += 1
            try:
                self.outbox.put_nowait(ev)
            except queue.Full:
                return False
            return True

    def close(self) -> None:
        with self.lock:
            if not self.open:
                return
            self.open = False
        try:
            self.outbox.put_nowait(None)      # wake the writer
        except queue.Full:
            pass                              # writer drains to the None
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class _Stream:
    """Per-client-id dedupe record; survives its connection so a retry
    resumes instead of restarting."""

    def __init__(self, sid: str, fid: int, rseed: int, prompt,
                 max_new_tokens: int, tenant: str = "default",
                 knobs: Optional[dict] = None,
                 ttft_deadline_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None):
        self.sid = sid
        self.fid = fid                   # current fabric id
        self.rseed = rseed               # sampling identity: FIRST fid
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.tenant = str(tenant)
        self.knobs = knobs
        self.ttft_deadline_ms = ttft_deadline_ms
        self.deadline_ms = deadline_ms
        self.toks: List[int] = []        # committed full stream
        self.state = "active"            # active | orphaned | done | failed
        self.conn: Optional[_Conn] = None
        self.sent = 0                    # toks shipped to current conn
        self.error: Optional[dict] = None     # reject event body
        # distributed-tracing root span (ISSUE 19): minted at submit
        # when the tracer is live, ended once at done/failed. None on
        # untraced streams — every tracing touch guards on this.
        self.tspan = None


class FrontDoor:
    """See module doc. ``fabric`` is driven ONLY by this object's
    driver thread once :meth:`start` runs — external step()/run() calls
    would race it (engines are not thread-safe; one RLock serializes
    every fabric touch)."""

    def __init__(self, fabric: ServingFabric, host: str = "127.0.0.1",
                 port: int = 0, max_line_bytes: int = 1 << 20,
                 outbox_max: int = 256,
                 poll_interval_s: float = 0.001,
                 write_stall_s: float = 10.0,
                 sndbuf: Optional[int] = None):
        self.fabric = fabric
        self.max_line_bytes = int(max_line_bytes)
        self.outbox_max = int(outbox_max)
        self.poll_interval_s = float(poll_interval_s)
        # a writer blocked in sendall longer than this is a slow-loris
        # peer (TCP window closed); sndbuf (when set) shrinks the
        # server-side send buffer so tests hit that state cheaply
        self.write_stall_s = float(write_stall_s)
        self.sndbuf = sndbuf
        self._last_idle_probe = 0.0
        self._flock = threading.RLock()       # every fabric touch
        self._streams: Dict[str, _Stream] = {}
        self._by_fid: Dict[int, _Stream] = {}
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.retries = 0                      # resumed submissions
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FrontDoor":
        for fn, nm in ((self._accept_loop, "accept"),
                       (self._drive_loop, "drive")):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"frontdoor-{nm}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            c.close()
        for t in self._threads:
            t.join(timeout=5.0)

    # -- accept / per-connection threads -------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.25)
                sock, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            if self.sndbuf is not None:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                int(self.sndbuf))
            conn = _Conn(sock, self.outbox_max)
            with self._conns_lock:
                self._conns.add(conn)
            for fn, nm in ((self._read_loop, "read"),
                           (self._write_loop, "write")):
                threading.Thread(target=fn, args=(conn,), daemon=True,
                                 name=f"frontdoor-{nm}").start()

    def _write_loop(self, conn: _Conn) -> None:
        try:
            while True:
                ev = conn.outbox.get()
                if ev is None:
                    return
                conn.writing_since = time.monotonic()
                conn.sock.sendall(json.dumps(ev).encode() + b"\n")
                conn.writing_since = None
        except OSError:
            self._drop_conn(conn, reason="write_error")
        finally:
            pass

    def _read_loop(self, conn: _Conn) -> None:
        f = conn.sock.makefile("rb")
        reason = "eof"
        try:
            while not self._stop.is_set():
                line = f.readline(self.max_line_bytes + 1)
                if not line:
                    break
                if (len(line) > self.max_line_bytes
                        or not line.endswith(b"\n")):
                    reason = "overlong_frame"
                    break
                if not line.strip():
                    continue
                try:
                    msg = json.loads(line)
                    if not isinstance(msg, dict):
                        raise ValueError("frame is not an object")
                except ValueError as e:
                    # torn frame: typed error, connection SURVIVES
                    conn.send({"ev": "error",
                               "error": f"bad frame: {e}"})
                    continue
                try:
                    self._handle(conn, msg)
                except Exception as e:    # noqa: BLE001 — client input
                    conn.send({"ev": "error",   # must never kill loops
                               "error": f"{type(e).__name__}: {e}"})
        except OSError:
            reason = "reset"
        finally:
            self._drop_conn(conn, reason=reason)

    def _drop_conn(self, conn: _Conn, reason: str) -> None:
        """Connection teardown: cancel its live fabric requests (frees
        slots/pages NOW) but KEEP the dedupe records — a retry on a new
        connection resumes them."""
        with self._conns_lock:
            if conn not in self._conns:
                return
            self._conns.discard(conn)
        conn.close()
        with self._flock:
            for sid in list(conn.ids):
                st = self._streams.get(sid)
                if st is None or st.conn is not conn:
                    continue
                st.conn = None
                if st.state == "active":
                    st.state = "orphaned"
                    self.fabric.cancel(st.fid,
                                       error="client_disconnect")
                    self._by_fid.pop(st.fid, None)
        if _REG.enabled:
            _REG.counter("pt_frontdoor_disconnects_total",
                         "client connections dropped").inc(
                reason=reason)

    # -- op handling (reader threads) ----------------------------------------

    def _handle(self, conn: _Conn, msg: dict) -> None:
        op = msg.get("op")
        if op == "ping":
            conn.send({"ev": "pong"})
            return
        if op == "cancel":
            sid = str(msg.get("id"))
            with self._flock:
                st = self._streams.get(sid)
                if st is not None and st.state == "active":
                    self.fabric.cancel(st.fid, error="client_cancel")
                    self._by_fid.pop(st.fid, None)
                    st.state = "orphaned"
            conn.send({"ev": "cancelled", "id": sid,
                       "reason": "client_cancel"})
            return
        if op != "submit":
            conn.send({"ev": "error", "error": f"unknown op {op!r}"})
            return
        sid = msg.get("id")
        if not isinstance(sid, str) or not sid:
            conn.send({"ev": "error", "error": "submit needs a "
                                               "string id"})
            return
        have = max(0, int(msg.get("have", 0)))
        with self._flock:
            st = self._streams.get(sid)
            if st is not None:
                self._resume(conn, st, have)
                return
            # the trace root is minted HERE — the FrontDoor edge — and
            # its context rides the fabric request (explicit injection;
            # contextvars would stop at the TCP hop). A client-supplied
            # trace_id joins its trace to ours for end-to-end logs.
            root = None
            if _TRACE.enabled:
                tid = msg.get("trace_id")
                root = _TRACE.start(
                    "frontdoor::request",
                    trace_id=str(tid) if tid else None,
                    tags={"id": sid,
                          "tenant": str(msg.get("tenant", "default"))})
                acc = _TRACE.start("frontdoor::submit", parent=root)
            try:
                fid = self.fabric.submit(
                    np.asarray(msg["prompt"], np.int32),
                    int(msg["max_new_tokens"]),
                    tenant=str(msg.get("tenant", "default")),
                    knobs=msg.get("knobs"),
                    ttft_deadline_ms=msg.get("ttft_deadline_ms"),
                    deadline_ms=msg.get("deadline_ms"),
                    trace=None if root is None else root.ctx)
            except FabricRejected as e:
                if root is not None:
                    acc.tag(outcome="rejected").end()
                    root.tag(state="rejected").end()
                conn.send({"ev": "reject", "id": sid, **e.to_wire()})
                return
            if root is not None:
                acc.tag(outcome="ok").end()
            st = _Stream(sid, fid, rseed=fid, prompt=msg["prompt"],
                         max_new_tokens=int(msg["max_new_tokens"]),
                         tenant=str(msg.get("tenant", "default")),
                         knobs=msg.get("knobs"),
                         ttft_deadline_ms=msg.get("ttft_deadline_ms"),
                         deadline_ms=msg.get("deadline_ms"))
            st.conn = conn
            st.tspan = root
            self._streams[sid] = st
            self._by_fid[fid] = st
            conn.ids.add(sid)
            # ack INSIDE the lock: the driver (also behind the lock)
            # must not flush a tok event ahead of the ack
            conn.send({"ev": "ack", "id": sid})

    def _resume(self, conn: _Conn, st: _Stream, have: int) -> None:
        """A submit for an id we know: dedupe. Ship what the client
        lacks; re-admit to the fabric only when the stream is orphaned
        mid-generation. Caller holds the fabric lock."""
        prev = st.conn
        st.conn = conn
        st.sent = min(have, len(st.toks))
        conn.ids.add(st.sid)
        # every dedupe attempt is a SIBLING span under the stream's
        # root, tagged with its outcome — hedge-as-takeover is visible
        # as resume(takeover) next to the still-running first attempt
        rsp = None
        if st.tspan is not None and _TRACE.enabled:
            rsp = _TRACE.start("frontdoor::resume", parent=st.tspan,
                               tags={"have": have})
        if prev is not None and prev is not conn:
            # hedge/takeover: exactly one attempt owns a stream
            prev.ids.discard(st.sid)
            prev.send({"ev": "cancelled", "id": st.sid,
                       "reason": "taken_over"})
            if st.state == "active":
                # the old attempt's fabric request keeps running and
                # this connection now receives it — nothing to resubmit
                if rsp is not None:
                    rsp.tag(outcome="takeover").end()
                conn.send({"ev": "ack", "id": st.sid})
                self._flush(st)
                self.retries += 1
                self._count_retry()
                return
        if st.state in ("done", "failed"):
            if rsp is not None:
                rsp.tag(outcome="replayed").end()
            conn.send({"ev": "ack", "id": st.sid})
            self._flush(st)
            self._finish_events(st)
            self.retries += 1
            self._count_retry()
            return
        if st.state == "orphaned":
            # resume: original rseed + committed tokens as the replay
            # prefix — the engine re-emits nothing, the client receives
            # exactly what it lacks. The retry gets fresh deadline
            # budgets (its clock restarted with the new attempt).
            try:
                fid = self.fabric.submit(
                    st.prompt, st.max_new_tokens,
                    tenant=st.tenant, knobs=st.knobs,
                    ttft_deadline_ms=st.ttft_deadline_ms,
                    deadline_ms=st.deadline_ms,
                    rseed=st.rseed, replay=list(st.toks),
                    trace=(None if st.tspan is None
                           else st.tspan.ctx))
            except FabricRejected as e:
                if rsp is not None:
                    rsp.tag(outcome="rejected").end()
                st.conn = None
                conn.ids.discard(st.sid)
                conn.send({"ev": "reject", "id": st.sid,
                           **e.to_wire()})
                return
            st.fid = fid
            st.state = "active"
            self._by_fid[fid] = st
            if rsp is not None:
                rsp.tag(outcome="resubmit", replay=len(st.toks))
        if rsp is not None:
            if "outcome" not in rsp.tags:
                rsp.tag(outcome="reattach")
            rsp.end()
        conn.send({"ev": "ack", "id": st.sid})
        self._flush(st)
        self.retries += 1
        self._count_retry()

    @staticmethod
    def _count_retry() -> None:
        if _REG.enabled:
            _REG.counter("pt_frontdoor_retries_total",
                         "deduped resubmissions resumed").inc()

    # -- driver thread -------------------------------------------------------

    def _drive_loop(self) -> None:
        while not self._stop.is_set():
            with self._flock:
                worked = self._drive_once()
            # ALWAYS yield between passes, not only when idle: reader
            # and teardown threads contend for _flock, and a hot
            # release→reacquire loop starves them under continuous
            # traffic (CPython hands the GIL back to the releaser) —
            # a mid-stream disconnect would then not cancel until the
            # stream drained on its own. The busy yield is a fraction
            # of the idle one: long enough for a blocked waiter to
            # take the lock, short against a decode step.
            time.sleep(self.poll_interval_s
                       if not worked else self.poll_interval_s / 4.0)

    def _drive_once(self) -> bool:
        """One fabric pass + fan-out; caller holds the lock. Returns
        False when the fabric was idle (the loop then sleeps)."""
        if not self.fabric.has_work():
            # keep breaker readmission moving while idle: a replica
            # that recovers between waves must not stay quarantined
            # until the next request arrives (throttled — probes are
            # real status+poll round-trips)
            if getattr(self.fabric, "_dead", None):
                now = time.monotonic()
                if now - self._last_idle_probe >= 0.05:
                    self._last_idle_probe = now
                    self.fabric.probe_recovery()
            return False
        try:
            delivered = self.fabric.step()
        except FabricRejected:
            # every replica down mid-run: requests stay queued; the
            # probe loop inside step() readmits when a breaker closes.
            # Clients see progress stall, their deadlines (or retries
            # against a recovered fabric) decide — the server must not
            # crash its own driver.
            time.sleep(self.poll_interval_s)
            return True
        arrived: Dict[int, List[int]] = {}
        for fid, tok in delivered:
            arrived.setdefault(fid, []).append(int(tok))
        for fid, toks in arrived.items():
            st = self._by_fid.get(fid)
            if st is None:
                continue
            if st.tspan is not None and not st.toks and _TRACE.enabled:
                # the TTFT stamp the critical-path walk attributes:
                # first token committed at the client-facing edge
                st.tspan.event("first_tok")
            st.toks.extend(toks)
            self._flush(st)
        for fid, result in self.fabric.take_finished().items():
            st = self._by_fid.pop(fid, None)
            if st is None:
                continue
            if result is not None:
                st.toks = [int(t) for t in np.asarray(result).ravel()]
                st.state = "done"
            else:
                err = self.fabric.failed.get(fid, "rejected")
                if st.state == "orphaned" or err in (
                        "client_disconnect", "client_cancel"):
                    continue        # we cancelled it; nothing to report
                st.state = "failed"
                kind = ("deadline"
                        if err.startswith("deadline_exceeded")
                        else "rejected")
                # retry hint 0: the deadline clock restarts with the
                # retry, so there is nothing to wait out
                st.error = {"kind": kind, "error": err,
                            "retry_after_ms": 0.0}
            self._flush(st)
            self._finish_events(st)
        return True

    def _flush(self, st: _Stream) -> None:
        """Ship ``toks[sent:]`` to the owning connection; a full outbox
        here IS the slow-loris signal — cancel + drop."""
        conn = st.conn
        if conn is None or st.sent >= len(st.toks):
            return
        since = conn.writing_since
        if since is not None and \
                time.monotonic() - since > self.write_stall_s:
            self._evict_slow(st, conn)
            return
        dsp = None
        if st.tspan is not None and _TRACE.enabled:
            dsp = _TRACE.start("frontdoor::drain", parent=st.tspan)
        pend = st.toks[st.sent:]
        if conn.send({"ev": "tok", "id": st.sid, "toks": pend}):
            st.sent = len(st.toks)
            if dsp is not None:
                dsp.tag(n=len(pend)).end()
        else:
            if dsp is not None:
                dsp.tag(n=len(pend), outcome="slow_evict").end()
            self._evict_slow(st, conn)

    def _finish_events(self, st: _Stream) -> None:
        root = st.tspan
        if root is not None:
            # root end assembles the trace: ingested replica spans,
            # flagged orphans and all. Ended exactly once (replayed
            # resumes re-enter here with tspan already cleared).
            st.tspan = None
            root.tag(state=st.state, n=len(st.toks)).end()
        conn = st.conn
        if conn is None:
            return
        if st.state == "done":
            conn.send({"ev": "done", "id": st.sid, "toks": [],
                       "n": len(st.toks)})
        elif st.state == "failed" and st.error is not None:
            conn.send({"ev": "reject", "id": st.sid, **st.error})

    def _evict_slow(self, st: _Stream, conn: _Conn) -> None:
        """The outbox stayed full: the peer stopped reading. Cancel its
        requests (slots/pages free NOW for clients that do read) and
        sever the connection; the dedupe record stays for a retry."""
        if st.state == "active":
            st.state = "orphaned"
            self.fabric.cancel(st.fid, error="slow_client")
            self._by_fid.pop(st.fid, None)
        st.conn = None
        with self._conns_lock:
            self._conns.discard(conn)
        conn.close()
        if _REG.enabled:
            _REG.counter("pt_frontdoor_disconnects_total",
                         "client connections dropped").inc(
                reason="slow")

    # -- introspection -------------------------------------------------------

    def stream_states(self) -> Dict[str, str]:
        with self._flock:
            return {sid: st.state for sid, st in self._streams.items()}
