"""FabricClient: the retry-correct client of a :class:`~.frontdoor.
FrontDoor` (ISSUE 16).

The front door makes retries SAFE (per-id dedupe + replay resume); this
client makes them AUTOMATIC:

* **Jittered exponential backoff** — :class:`~.robust.Backoff` full
  jitter, floored by any server ``retry_after_ms`` hint, so a rejected
  herd decorrelates above the server's own recovery estimate.
* **Idempotent resubmission** — every attempt carries the SAME client
  id and ``have`` = tokens already received; the server resumes the
  stream via its dedupe record (original rseed + replay prefix), so a
  retry after a mid-stream disconnect delivers exactly the missing
  suffix — zero duplicated, zero lost tokens, asserted by seq/count
  checks here.
* **Hedged attempt on TTFT-deadline miss** — when ``hedge_after_s`` is
  set and no first token arrives in time, the client abandons the
  silent connection and re-attaches on a fresh one. The server's
  single-owner takeover semantics make this the correct form of a
  hedge: a parallel second attempt would immediately steal the stream
  from the first anyway, so at most one socket ever owns it and the
  "race" collapses to fail over fast.

Retryable: ``overloaded`` / ``all_down`` rejections (server says when),
connection faults (reset, EOF, refused — the door may be restarting),
and hedge timeouts. NOT retryable: ``deadline`` (the budget is spent)
and application rejects — those raise typed immediately.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import time
from typing import Callable, List, Optional

from .robust import (AllReplicasDown, Backoff, DeadlineExceeded,
                     FabricRejected, Overloaded)

__all__ = ["FabricClient", "ClientResult"]

_KIND_EXC = {"overloaded": Overloaded, "all_down": AllReplicasDown,
             "deadline": DeadlineExceeded}
_uniq = itertools.count()


class ClientResult:
    """Outcome of one generate(): the token stream plus the client-side
    robustness ledger the tests assert on."""

    def __init__(self, tokens: List[int], attempts: int,
                 retries: int, hedged: int, rejects: List[dict]):
        self.tokens = tokens
        self.attempts = attempts
        self.retries = retries
        self.hedged = hedged
        self.rejects = rejects          # typed rejections absorbed


class FabricClient:
    """See module doc. One client may run many sequential requests;
    each concurrent stream wants its own client (one socket each)."""

    def __init__(self, host: str, port: int,
                 connect_timeout_s: float = 5.0,
                 io_timeout_s: float = 60.0,
                 max_attempts: int = 5,
                 backoff: Optional[Backoff] = None,
                 hedge_after_s: Optional[float] = None,
                 max_line_bytes: int = 1 << 20):
        self.host, self.port = host, int(port)
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self.max_attempts = int(max_attempts)
        self.backoff = backoff or Backoff()
        self.hedge_after_s = hedge_after_s
        self.max_line_bytes = int(max_line_bytes)

    # -- wire plumbing -------------------------------------------------------

    def _connect(self):
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.connect_timeout_s)
        s.settimeout(self.io_timeout_s)
        return s, s.makefile("rb")

    @staticmethod
    def _send(sock, msg: dict) -> None:
        sock.sendall(json.dumps(msg).encode() + b"\n")

    def _recv(self, f) -> dict:
        line = f.readline(self.max_line_bytes + 1)
        if not line:
            raise ConnectionError("server closed the connection")
        if len(line) > self.max_line_bytes or not line.endswith(b"\n"):
            raise ConnectionError("overlong server frame")
        return json.loads(line)

    # -- the request loop ----------------------------------------------------

    def generate(self, prompt, max_new_tokens: int,
                 tenant: str = "default",
                 knobs: Optional[dict] = None,
                 ttft_deadline_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 request_id: Optional[str] = None,
                 on_token: Optional[Callable[[int], None]] = None,
                 trace_id: Optional[str] = None) -> ClientResult:
        """Run one streaming request to completion through every
        robustness path; returns the full token stream. Raises the
        typed rejection when attempts are exhausted or the refusal is
        terminal (``deadline``). ``trace_id`` joins this request to a
        caller-owned distributed trace (the front door mints one per
        request otherwise, when tracing is on)."""
        sid = request_id or f"c{os.getpid()}-{next(_uniq)}"
        toks: List[int] = []
        seq_next: Optional[int] = None
        attempts = retries = hedged = 0
        rejects: List[dict] = []
        last_exc: Optional[Exception] = None
        while attempts < self.max_attempts:
            attempts += 1
            if attempts > 1:
                retries += 1
            sock = f = None
            try:
                sock, f = self._connect()
                if self.hedge_after_s is not None and not toks:
                    # TTFT hedge window: a silent server past this
                    # budget is abandoned for a fresh attempt
                    sock.settimeout(self.hedge_after_s)
                self._send(sock, {
                    "op": "submit", "id": sid,
                    "prompt": [int(t) for t in prompt],
                    "max_new_tokens": int(max_new_tokens),
                    "tenant": tenant, "knobs": knobs,
                    "ttft_deadline_ms": ttft_deadline_ms,
                    "deadline_ms": deadline_ms, "have": len(toks),
                    "trace_id": trace_id})
                seq_next = None
                while True:
                    try:
                        ev = self._recv(f)
                    except socket.timeout:
                        if self.hedge_after_s is not None and not toks:
                            hedged += 1
                            raise ConnectionError("ttft hedge fired")
                        raise
                    # per-connection seq: ordered and gapless, or the
                    # transport lied to us
                    s = ev.get("seq")
                    if s is not None:
                        if seq_next is not None and s != seq_next:
                            raise ConnectionError(
                                f"seq gap: got {s}, wanted {seq_next}")
                        seq_next = s + 1
                    kind = ev.get("ev")
                    if kind == "tok" and ev.get("id") == sid:
                        new = [int(t) for t in ev.get("toks", ())]
                        toks.extend(new)
                        if toks and sock.gettimeout() != \
                                self.io_timeout_s:
                            sock.settimeout(self.io_timeout_s)
                        if on_token is not None:
                            for t in new:
                                on_token(t)
                    elif kind == "done" and ev.get("id") == sid:
                        toks.extend(int(t) for t in ev.get("toks", ()))
                        n = int(ev.get("n", len(toks)))
                        if len(toks) != n:
                            raise ConnectionError(
                                f"stream short: {len(toks)}/{n} tokens")
                        return ClientResult(toks, attempts, retries,
                                            hedged, rejects)
                    elif kind == "reject" and ev.get("id") == sid:
                        exc = _KIND_EXC.get(ev.get("kind"),
                                            FabricRejected)(
                            ev.get("error", "rejected"),
                            retry_after_ms=ev.get("retry_after_ms"))
                        if isinstance(exc, (Overloaded,
                                            AllReplicasDown)):
                            rejects.append(ev)
                            last_exc = exc
                            raise exc          # → backoff + retry
                        raise exc              # terminal: propagate
                    elif kind == "cancelled" and ev.get("id") == sid:
                        # takeover by another attempt of OURS would be
                        # a client bug (one generate per id); treat as
                        # a dropped attempt and retry
                        raise ConnectionError(
                            f"server cancelled: {ev.get('reason')}")
                    # ack / pong / other-id events: keep reading
            except (Overloaded, AllReplicasDown) as e:
                time.sleep(self.backoff.delay_s(attempts - 1,
                                                e.retry_after_ms))
            except (OSError, ValueError, ConnectionError) as e:
                last_exc = e
                time.sleep(self.backoff.delay_s(attempts - 1))
            finally:
                for c in (f, sock):
                    if c is not None:
                        try:
                            c.close()
                        except OSError:
                            pass
        raise (last_exc if isinstance(last_exc, FabricRejected)
               else FabricRejected(
                   f"request {sid!r} failed after "
                   f"{attempts} attempts: {last_exc}"))
