"""ServingFabric: the front door over N replicas.

One router owns the GLOBAL request queue and drives every replica
through a :class:`~.transport.FabricTransport`. Per scheduler pass
(``step()``):

1. **Heartbeat** — refresh each replica's status (load, pool, latency
   percentiles, prefix digest) and run the ITL hysteresis: a replica
   whose ``itl_p99`` breaches the target goes HOT (affinity stops
   pinning it) and only cools once it recovers past the band — no
   flapping at the threshold.
2. **Release + route** — the per-tenant weighted fair policy (when
   installed) picks which request leaves the global queue; routing then
   picks the replica: ``affinity`` routes to the longest
   digest-matched prefix (ties and cold prompts fall back to
   least-loaded = free slots × free pages), ``least-loaded`` and
   ``round-robin`` are the baselines the bench compares against.
   Dispatch is capacity-gated (a replica is only handed requests while
   it has free slots), so the global queue — where fairness and SLO
   policy live — stays the ONE place requests wait.
3. **Disaggregation** — a cold prompt whose priced uncached suffix
   reaches ``disagg_threshold_tokens`` is routed to a PREFILL-role
   replica first (budget 1 token); on completion its KV pages + radix
   path cross to a decode replica via serialize_pages → adopt_pages
   (seeding that replica's tree — the transfer IS a future prefix hit)
   and the real request is submitted there, where admission
   prefix-hits and decode ITL never sees the long prefill.
4. **Poll + failover** — drain every replica one engine tick; any op
   raising :class:`ReplicaDown` re-queues that replica's in-flight
   requests at the FRONT with ``replay_prefix=`` the tokens already
   delivered and the ORIGINAL ``rseed`` — the survivor re-prefills the
   prefix (cheap when its tree holds it) and continues the stream
   token-identically with the remaining budget. Zero duplicates (the
   engine never re-emits a replay prefix), zero losses (the router's
   delivered list is authoritative).

Everything observable publishes through the PR 4 registry under
``pt_fabric_*`` (per-replica/per-tenant label sets) and the matching
sentry pack is ``observability.sentry.fabric_rules()``.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observability.metrics import REGISTRY as _REG
from ..observability.sentry import sentry as _sentry
from ..observability.tracing import TRACER as _TRACE, TraceContext
from .digest import PrefixDigest
from .fair import TenantFairPolicy
from .robust import AllReplicasDown, LoadShedder
from .transport import FabricTransport, ReplicaDown

__all__ = ["FabricRequest", "ServingFabric"]


@dataclass
class FabricRequest:
    """One logical request as the router tracks it across replicas."""
    fid: int
    prompt: np.ndarray
    max_new_tokens: int
    tenant: str = "default"
    knobs: Optional[dict] = None
    state: str = "queued"       # queued | prefill | decode | done | failed
    error: Optional[str] = None      # set when state == "failed"
    replica: Optional[str] = None
    local_rid: Optional[int] = None
    # sampling-stream identity override: a retry of an earlier logical
    # request reuses ITS rseed so the continuation is token-identical
    rseed: Optional[int] = None
    ttft_deadline_ms: Optional[float] = None
    deadline_ms: Optional[float] = None
    delivered: List[int] = field(default_factory=list)
    result: Optional[np.ndarray] = None
    prefill_done: bool = False
    handoff_pages: int = 0
    readmissions: int = 0
    submit_t: float = 0.0
    first_tok_t: float = 0.0
    last_emit_t: float = 0.0
    done_t: float = 0.0
    itl_gaps: List[float] = field(default_factory=list)
    # distributed tracing (ISSUE 19): ``trace`` is the request's span
    # (child of the frontdoor root, or itself a root on a bare fabric),
    # ``tqueue`` the open queue-wait span, ``tctx`` the wire dict
    # replica payloads carry. All None on untraced requests — every
    # tracing touch on the hot path guards on that one attribute.
    trace: Optional[object] = None
    tqueue: Optional[object] = None
    tctx: Optional[dict] = None


class ServingFabric:
    """Router + replica pool; see module doc.

    ``policy`` — "affinity" (default), "least-loaded" or "round-robin".
    ``fair`` — optional :class:`TenantFairPolicy`; None releases FIFO.
    ``itl_p99_target_s`` — per-replica ITL SLO driving the affinity
    hysteresis (None disables it).
    ``hysteresis_band`` — a hot replica cools only below
    ``target × (1 - band)``.
    ``disagg_threshold_tokens`` — priced uncached suffix at or above
    this routes through a prefill-role replica first (None disables
    disaggregation).
    ``affinity_min_pages`` — digest matches shorter than this count as
    cold (least-loaded fallback)."""

    POLICIES = ("affinity", "least-loaded", "round-robin")

    def __init__(self, transport: FabricTransport,
                 policy: str = "affinity",
                 fair: Optional[TenantFairPolicy] = None,
                 itl_p99_target_s: Optional[float] = None,
                 hysteresis_band: float = 0.25,
                 disagg_threshold_tokens: Optional[int] = None,
                 affinity_min_pages: int = 1,
                 shedder: Optional[LoadShedder] = None,
                 default_retry_after_ms: float = 250.0,
                 name: Optional[str] = None):
        if policy not in self.POLICIES:
            raise ValueError(f"unknown policy {policy!r}; pick one of "
                             f"{self.POLICIES}")
        self.transport = transport
        self.policy = policy
        # fabric identity: same rule the engines follow with engine= —
        # two routers in one process (a bench A/B) must not merge
        # their pt_fabric_* series
        self.name = name or ""
        self._flabels: Dict[str, str] = ({"fabric": self.name}
                                         if self.name else {})
        self.fair = fair
        self.itl_p99_target_s = itl_p99_target_s
        self.hysteresis_band = float(hysteresis_band)
        self.disagg_threshold_tokens = disagg_threshold_tokens
        self.affinity_min_pages = int(affinity_min_pages)
        self.shedder = shedder
        self.default_retry_after_ms = float(default_retry_after_ms)
        self._browned = False
        # local rids a dead replica still held: on breaker readmission
        # they are best-effort cancelled so the recovered engine stops
        # burning pages on streams a survivor already re-owns
        self._stale_rids: Dict[str, List[int]] = {}
        self._fid = 0
        self._reqs: Dict[int, FabricRequest] = {}
        self._queue: deque = deque()
        self._assign: Dict[Tuple[str, int], int] = {}
        self._status: Dict[str, dict] = {}
        self._digests: Dict[str, PrefixDigest] = {}
        self._dead: set = set()
        self._hot: set = set()
        self._outstanding: Dict[str, int] = {}
        self._rr = 0
        # lifetime telemetry (plain attrs; registry mirrors on events)
        self.routed: Dict[str, int] = {}
        self.affinity_hits = 0
        self.misrouted = 0
        self.cold_routes = 0
        self.handoffs = 0
        self.handoff_bytes = 0
        self.handoff_failures = 0
        self.readmitted = 0
        self.failed: Dict[int, str] = {}    # fid -> replica rejection
        # fid -> (epoch signature, price): _est_uncached runs several
        # times per request per pass (fair price, dispatch cost, the
        # disagg gate); the blake2b chain replay only changes when a
        # digest epoch or the replay length moves
        self._price_memo: Dict[int, tuple] = {}
        self._latencies = deque(maxlen=10_000)
        self._itl_gaps = deque(maxlen=100_000)

    # -- public API ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               tenant: str = "default",
               knobs: Optional[dict] = None,
               ttft_deadline_ms: Optional[float] = None,
               deadline_ms: Optional[float] = None,
               rseed: Optional[int] = None,
               replay: Optional[List[int]] = None,
               trace: Optional[TraceContext] = None) -> int:
        """Queue one request; returns its fabric id. ``knobs`` (optional
        dict of do_sample/temperature/top_k/top_p/eos_token_id)
        overrides the replica engines' default GenerationConfig. The
        fabric id doubles as the sampling-stream identity (``rseed``),
        so a request's sampled tokens are the same whichever replica —
        or sequence of replicas, after a failover — serves it.

        ISSUE 16 lifecycle knobs: ``ttft_deadline_ms`` / ``deadline_ms``
        bound time-to-first-token and total latency (a miss CANCELs the
        request, frees its slot/pages and fails it typed). ``rseed`` +
        ``replay`` let an idempotent RETRY of an earlier logical request
        resume its exact stream: same rseed ⇒ same sampling keys, the
        replay prefix is never re-emitted, so the retry delivers exactly
        the tokens the first attempt didn't.

        Raises :class:`~.robust.AllReplicasDown` when no replica is
        reachable and :class:`~.robust.Overloaded` when the shed ladder
        refuses this tenant — both carry ``retry_after_ms``."""
        if not self._alive_names():
            raise AllReplicasDown(
                "serving fabric: every replica is down; submission "
                "refused", retry_after_ms=self._retry_after_ms())
        if self.shedder is not None:
            w = (self.fair.spec(tenant).weight
                 if self.fair is not None else 1.0)
            self.shedder.admit(str(tenant), w, len(self._queue))
        ids = np.asarray(prompt, np.int32).reshape(-1)
        req = FabricRequest(self._fid, ids, int(max_new_tokens),
                            tenant=str(tenant), knobs=knobs,
                            rseed=rseed,
                            ttft_deadline_ms=ttft_deadline_ms,
                            deadline_ms=deadline_ms)
        if replay:
            req.delivered = [int(t) for t in replay]
        req.submit_t = time.perf_counter()
        if _TRACE.enabled:
            # ``trace`` (the frontdoor root's context) parents this
            # request's span; a bare fabric submit mints its own root.
            # The queue span opens NOW: fair-admission wait is part of
            # the queue hop, readmissions add sibling queue spans.
            sp = _TRACE.start("fabric::request", parent=trace,
                              tags={"fid": req.fid,
                                    "tenant": req.tenant})
            req.trace = sp
            req.tctx = sp.ctx.to_wire()
            req.tqueue = _TRACE.start("fabric::queue", parent=sp,
                                      tags={"readmission": 0})
        self._fid += 1
        self._reqs[req.fid] = req
        self._queue.append(req)
        return req.fid

    def cancel(self, fid: int, error: str = "cancelled") -> bool:
        """Terminate ``fid`` NOW (client disconnect, slow-loris
        eviction, deadline miss): dequeue it, cancel it replica-side so
        its slot/pages free through the engine's one ``_free_slot``
        path, and fail it with ``error``. True when it existed and had
        not already finished."""
        req = self._reqs.get(fid)
        if req is None or req.state in ("done", "failed"):
            return False
        try:
            self._queue.remove(req)
        except ValueError:
            pass
        if req.replica is not None and req.local_rid is not None:
            self._assign.pop((req.replica, req.local_rid), None)
            self._outstanding[req.replica] = max(
                0, self._outstanding.get(req.replica, 0) - 1)
            try:
                self.transport.cancel(req.replica, req.local_rid)
            except Exception:       # noqa: BLE001 — replica down or
                pass                # transport can't cancel: the slot
                                    # is reaped with the replica instead
        req.state, req.error = "failed", error
        self._trace_done(req)
        return True

    # -- tracing hooks (ISSUE 19) --------------------------------------------

    @staticmethod
    def _trace_done(req: FabricRequest, **tags) -> None:
        """Terminal state: close the request's open spans exactly once.
        Ending the span completes the trace when the fabric owns the
        root (no frontdoor above)."""
        sp = req.trace
        if sp is None:
            return
        req.trace = None
        q = req.tqueue
        if q is not None:
            req.tqueue = None
            q.tag(outcome=req.state).end()
        sp.tag(state=req.state,
               error=req.error, readmissions=req.readmissions, **tags)
        sp.end()

    @staticmethod
    def _trace_route(req: FabricRequest, t0: float, name: str,
                     how: str) -> None:
        """The route DECISION as a span: [dispatch-pass entry → submit
        accepted], tagged with the policy's verdict (affinity hit /
        spill / cold / rr / ll / prefill / disagg)."""
        if req.trace is not None and _TRACE.enabled:
            sp = _TRACE.start("fabric::route", parent=req.trace,
                              start=t0, tags={"replica": name,
                                              "how": how})
            sp.end()

    def has_work(self) -> bool:
        return any(r.state not in ("done", "failed")
                   for r in self._reqs.values())

    def step(self) -> List[Tuple[int, int]]:
        """One fabric pass: heartbeat → breaker probes → shed ladder →
        release+route → poll → deadline sweep. Returns the (fid, token)
        pairs delivered this pass."""
        self._refresh_status()
        self._probe_dead()
        if self.shedder is not None:
            # percentile aggregation only when a latency ceiling is
            # actually armed — queue depth alone is a dict len
            lat = (self.latency_stats()
                   if (self.shedder.ttft_p99_ceiling_s is not None
                       or self.shedder.itl_p99_ceiling_s is not None)
                   else {})
            self._apply_brownout(
                self.shedder.observe(len(self._queue), lat))
        self._dispatch_queue()
        delivered = self._poll_replicas()
        self._enforce_deadlines()
        if _REG.enabled:
            self._tick_gauges()
            _sentry.maybe_tick()
        return delivered

    def take_finished(self) -> Dict[int, Optional[np.ndarray]]:
        """Release every finished request: {fid: full stream} (None for
        a failed one, its error text kept in ``self.failed[fid]``) —
        the streaming front door's harvest; run() is this in a loop."""
        out: Dict[int, Optional[np.ndarray]] = {}
        for fid, r in list(self._reqs.items()):
            if r.state == "done":
                out[fid] = r.result
            elif r.state == "failed":
                out[fid] = None
                self.failed[fid] = r.error or "rejected"
            else:
                continue
            del self._reqs[fid]
        return out

    def run(self) -> Dict[int, np.ndarray]:
        """Drive until every submitted request completes; returns
        {fid: full token stream} for the requests finished by this call
        and releases them (same contract as the engine's run()). A
        request a replica REJECTED at submit (deterministic application
        error, e.g. a prompt no pool can hold) maps to None here and
        its error text is kept in ``self.failed[fid]``."""
        out: Dict[int, Optional[np.ndarray]] = {}
        while self.has_work():
            if not self._alive_names():
                raise AllReplicasDown(
                    "serving fabric: every replica is down with "
                    f"{sum(r.state not in ('done', 'failed') for r in self._reqs.values())}"
                    " requests outstanding",
                    retry_after_ms=self._retry_after_ms())
            self.step()
            out.update(self.take_finished())
        out.update(self.take_finished())
        if _REG.enabled:
            self.publish_metrics()
            _sentry.maybe_tick()
        return out

    # -- heartbeat / hysteresis ----------------------------------------------

    def _alive_names(self) -> List[str]:
        return [n for n in self.transport.replica_names()
                if n not in self._dead]

    def _role(self, name: str) -> str:
        st = self._status.get(name)
        return st.get("role", "both") if st else "both"

    def _app_error(self, name: str, op: str, e: Exception) -> None:
        """A live replica answered an op with an APPLICATION error
        (engine raised, remote answered ok:false). The router owns
        recovery and a broken engine cannot be reasoned with: treat it
        as a failed replica — its requests re-admit on survivors — and
        never let the exception kill the fabric loop."""
        import warnings
        warnings.warn(f"serving fabric: replica {name!r} failed "
                      f"{op} ({e!r}); treating it as down",
                      RuntimeWarning)
        self._on_replica_down(name)

    def _refresh_status(self) -> None:
        for name in self._alive_names():
            try:
                st = self.transport.status(name)
            except ReplicaDown:
                self._on_replica_down(name)
                continue
            except (ValueError, RuntimeError) as e:
                self._app_error(name, "status", e)
                continue
            self._status[name] = st
            d = st.get("digest")
            if d is not None:
                cur = self._digests.get(name)
                if cur is None or cur.epoch != d.get("epoch"):
                    self._digests[name] = PrefixDigest.from_dict(d)
            if self.itl_p99_target_s is not None:
                itl = st.get("itl_p99_s")
                if itl is not None:
                    if itl > self.itl_p99_target_s:
                        self._hot.add(name)
                    elif itl < self.itl_p99_target_s * (
                            1.0 - self.hysteresis_band):
                        self._hot.discard(name)

    def probe_recovery(self) -> None:
        """Public half-open probe pass: drive breaker readmission while
        the fabric is otherwise IDLE (step() probes as part of every
        busy pass, but a recovered replica must not stay quarantined
        just because traffic paused — the front door calls this on its
        idle ticks)."""
        self._probe_dead()

    def _probe_dead(self) -> None:
        """Half-open probing (ISSUE 16): when the transport is breaker-
        wrapped, ask it to probe each replica the router holds as dead;
        a CLOSEd breaker readmits the replica into routing. Stale local
        rids it still held are best-effort cancelled — the survivors
        re-own those streams, the recovered engine must not keep
        burning pages on them. A genuinely crashed replica's probe just
        keeps failing: readmission only ever follows demonstrated
        progress."""
        probe = getattr(self.transport, "probe", None)
        if probe is None or not self._dead:
            return
        for name in sorted(self._dead):
            try:
                ok = bool(probe(name))
            except Exception:       # noqa: BLE001 — a probe must never
                ok = False          # kill the fabric loop
            if not ok:
                continue
            self._dead.discard(name)
            for rid in self._stale_rids.pop(name, ()):
                try:
                    self.transport.cancel(name, rid)
                except Exception:   # noqa: BLE001 — best-effort reap
                    pass
            if _REG.enabled:
                _REG.counter("pt_fabric_replica_readmitted_total",
                             "replicas readmitted after a breaker "
                             "half-open probe succeeded").inc(
                    replica=name, **self._flabels)

    def _retry_after_ms(self, default: Optional[float] = None) -> float:
        """Server-side recovery estimate for typed rejections: the
        breaker's soonest half-open window when one is armed, else the
        configured default."""
        hint = getattr(self.transport, "retry_after_ms", None)
        v = None
        if callable(hint):
            try:
                v = hint()
            except Exception:       # noqa: BLE001 — hint is advisory
                v = None
        if v is None:
            v = (self.default_retry_after_ms
                 if default is None else default)
        return float(v)

    def _apply_brownout(self, level: int) -> None:
        """Level 2 pushes the draft-budget cap to every live replica
        (``spec_k`` shrink: verification-exact, just fewer drafts per
        tick — FLOPs shift from speculation to admitted decodes);
        leaving level 2 restores construction-time values."""
        want = level >= 2
        if want == self._browned:
            return
        knobs = {"spec_k": (self.shedder.spec_k_cap if want else None)}
        for name in self._alive_names():
            try:
                self.transport.configure(name, knobs)
            except Exception:       # noqa: BLE001 — a replica that
                pass                # can't configure just keeps its k
        self._browned = want

    def _enforce_deadlines(self) -> None:
        """Drain-boundary deadline sweep: a request past its TTFT or
        total budget is CANCELLED (slot/pages freed replica-side) and
        fails typed — the budget is spent, finishing late serves
        nobody and the capacity goes to requests that can still make
        theirs."""
        now = time.perf_counter()
        for req in list(self._reqs.values()):
            if req.state in ("done", "failed"):
                continue
            age_ms = (now - req.submit_t) * 1000.0
            kind = None
            if (req.deadline_ms is not None
                    and age_ms > req.deadline_ms):
                kind = "total"
            elif (req.ttft_deadline_ms is not None
                    and req.first_tok_t == 0.0 and not req.delivered
                    and age_ms > req.ttft_deadline_ms):
                kind = "ttft"
            if kind is None:
                continue
            self.cancel(req.fid, error=f"deadline_exceeded:{kind}")
            if _REG.enabled:
                _REG.counter("pt_frontdoor_deadline_miss_total",
                             "requests cancelled past their deadline"
                             ).inc(kind=kind, **self._flabels)

    # -- routing -------------------------------------------------------------

    def _capacity(self, name: str) -> int:
        st = self._status.get(name)
        if st is None:
            return 0
        return st.get("max_batch", 0) - self._outstanding.get(name, 0)

    def _load_score(self, name: str) -> Tuple:
        """Higher = less loaded: free slots × free pages (the ISSUE's
        least-loaded definition), then free slots, then stable name
        order for determinism."""
        st = self._status.get(name) or {}
        free_slots = max(0, self._capacity(name))
        free_pages = st.get("free_pages", 0)
        return (free_slots * (free_pages + 1), free_slots)

    def _least_loaded(self, cands: List[str]) -> str:
        return max(sorted(cands), key=self._load_score)

    def _digest_match(self, name: str, tokens) -> int:
        d = self._digests.get(name)
        return 0 if d is None else d.match_pages(tokens)

    def _est_uncached(self, req: FabricRequest) -> int:
        """Router-side price of admitting ``req`` now: its replay token
        run minus the BEST digest match across serving replicas — the
        same uncached-suffix unit the per-replica admission prices
        with, estimated from heartbeat state."""
        toks = self._replay_tokens(req)
        names = self._serving_names()
        sig = (len(toks), tuple(
            (n, self._digests[n].epoch) for n in names
            if n in self._digests))
        hit = self._price_memo.get(req.fid)
        if hit is not None and hit[0] == sig:
            return hit[1]
        best_pages, ps = 0, None
        for n in names:
            d = self._digests.get(n)
            if d is None:
                continue
            ps = d.page_size
            best_pages = max(best_pages, d.match_pages(toks))
        price = len(toks) if ps is None else max(
            1, len(toks) - best_pages * ps)
        if len(self._price_memo) > 4096:
            self._price_memo.clear()       # bound stale-fid growth
        self._price_memo[req.fid] = (sig, price)
        return price

    @staticmethod
    def _replay_tokens(req: FabricRequest) -> np.ndarray:
        if not req.delivered:
            return req.prompt
        return np.concatenate([req.prompt,
                               np.asarray(req.delivered, np.int32)])

    def _serving_names(self) -> List[str]:
        alive = self._alive_names()
        out = [n for n in alive if self._role(n) in ("both", "decode")]
        # a fabric of ONLY prefill replicas still serves (degenerate
        # deployments / tests) — prefill-role exclusion is a preference
        return out or alive

    def _prefill_names(self) -> List[str]:
        return [n for n in self._alive_names()
                if self._role(n) == "prefill"]

    def _pick(self, req: FabricRequest,
              cands: List[str]) -> Tuple[str, str]:
        """(replica, how) among ``cands`` (all with capacity)."""
        if self.policy == "round-robin":
            name = sorted(cands)[self._rr % len(cands)]
            self._rr += 1
            return name, "rr"
        if self.policy == "least-loaded":
            return self._least_loaded(cands), "ll"
        toks = self._replay_tokens(req)
        matches = {n: self._digest_match(n, toks) for n in cands}
        best = max(matches.values(), default=0)
        if best >= self.affinity_min_pages:
            top = [n for n, m in matches.items() if m == best]
            cool = [n for n in top if n not in self._hot]
            if cool:
                return self._least_loaded(cool), "affinity"
            # the affine replica(s) are past their ITL SLO: hysteresis
            # says spill — prefer any cool replica, even at match 0
            spill = [n for n in cands if n not in self._hot]
            if spill:
                return self._least_loaded(spill), "spill"
            return self._least_loaded(top), "affinity"
        cool = [n for n in cands if n not in self._hot] or cands
        return self._least_loaded(cool), "cold"

    # -- dispatch ------------------------------------------------------------

    def _dispatch_queue(self) -> None:
        if self.fair is not None:
            self.fair.tick()
        # skip-and-continue: a request WAITING on its pinned (affinity)
        # or prefill replica must not head-of-line-block requests that
        # can dispatch elsewhere this pass
        blocked: set = set()
        for _ in range(2 * len(self._queue) + 4):
            view = [r for r in self._queue if id(r) not in blocked]
            if not view:
                return
            if self.fair is not None:
                qi = self.fair.select(view, self._est_uncached)
                if qi is None:
                    return
                req = view[qi]
            else:
                qi, req = 0, view[0]
            cost = self._est_uncached(req)
            if not self._dispatch(req):
                blocked.add(id(req))
                continue
            # a replica REJECTION consumed no capacity: the tenant's
            # bucket/vtime must not be charged for work never performed
            if self.fair is not None and req.state != "failed":
                self.fair.note_admitted(view, qi, cost)
            self._queue.remove(req)

    def _dispatch(self, req: FabricRequest) -> bool:
        """Route + submit ``req``; False when nothing can take it this
        pass (it stays queued)."""
        t_route = time.time() if req.trace is not None else 0.0
        # brownout (shed level 2): cold expensive prefills WAIT — the
        # skip loop keeps cheap/warm requests flowing and running
        # decodes keep their ITL; fairness still orders the wait
        if (self.shedder is not None and not req.delivered
                and self.shedder.defer_cold(self._est_uncached(req))):
            return False
        # disaggregation: a cold long prompt goes to a prefill replica
        # first — unless it already prefilled (handoff done) or was
        # re-admitted with progress (its replay is the expensive part
        # and a survivor may hold its prefix)
        if (self.disagg_threshold_tokens is not None
                and not req.prefill_done and not req.delivered):
            prefill_roles = self._prefill_names()
            serving = self._serving_names()
            if (prefill_roles and serving
                    and self._est_uncached(req)
                    >= self.disagg_threshold_tokens):
                prefills = [n for n in prefill_roles
                            if self._capacity(n) > 0]
                if not prefills:
                    # prefill replicas exist but are momentarily full:
                    # WAIT for one (skip loop keeps others flowing) —
                    # spilling the long cold prefill onto a decode
                    # replica would inflict exactly the ITL breach
                    # disaggregation exists to prevent
                    return False
                name = self._least_loaded(prefills)
                if not self._submit_to(req, name, prefill=True):
                    return False
                if req.state != "failed":
                    self._trace_route(req, t_route, name, "prefill")
                if req.state != "failed" and _REG.enabled:
                    _REG.counter("pt_fabric_routed_total",
                                 "requests routed to a replica").inc(
                        replica=name, how="prefill", **self._flabels)
                return True
        if self.policy == "affinity":
            # affinity PINS: pick over every serving replica; a request
            # whose matched replica is at capacity WAITS for it (the
            # skip loop keeps others flowing) — spilling it cold would
            # replicate its prefix onto another tree and erode the very
            # partitioning affinity exists to build. Hysteresis (hot
            # replicas) stays the escape valve, capacity is not one.
            cands = self._serving_names()
            if not cands:
                return False
            name, how = self._pick(req, cands)
            if self._capacity(name) <= 0:
                if how == "affinity":
                    return False            # wait for the pinned replica
                free = [n for n in cands if self._capacity(n) > 0]
                if not free:
                    return False
                name, how = self._pick(req, free)
                if self._capacity(name) <= 0:
                    return False
        else:
            cands = [n for n in self._serving_names()
                     if self._capacity(n) > 0]
            if not cands:
                return False
            name, how = self._pick(req, cands)
        if not self._submit_to(req, name, prefill=False):
            return False
        if req.state == "failed":
            return True              # rejected at submit: consumed
        self._trace_route(req, t_route, name, how)
        if how == "affinity":
            self.affinity_hits += 1
        elif how == "spill":
            self.misrouted += 1
        else:
            self.cold_routes += 1
        if _REG.enabled:
            _REG.counter("pt_fabric_routed_total",
                         "requests routed to a replica").inc(
                replica=name, how=how, **self._flabels)
        return True

    def _submit_to(self, req: FabricRequest, name: str,
                   prefill: bool) -> bool:
        payload = {"prompt": req.prompt,
                   "max_new_tokens": (1 if prefill
                                      else req.max_new_tokens),
                   "rseed": (req.fid if req.rseed is None
                             else req.rseed),
                   "knobs": req.knobs,
                   "replay": (None if prefill or not req.delivered
                              else list(req.delivered))}
        asp = None
        if req.trace is not None and _TRACE.enabled:
            payload["trace"] = req.tctx
            asp = _TRACE.start("fabric::submit", parent=req.trace,
                               tags={"replica": name,
                                     "attempt": req.readmissions})
        try:
            rid = self.transport.submit(name, payload)
        except ReplicaDown:
            if asp is not None:
                asp.tag(outcome="replica_down").end()
            self._on_replica_down(name)
            return False
        except (ValueError, RuntimeError) as e:
            # an application error (the replica REJECTED the request —
            # e.g. a prompt its pool can never hold) is deterministic:
            # retrying or crashing the whole fabric would strand every
            # other in-flight request. The request fails terminally and
            # surfaces through run()/stats(); the pass continues.
            req.state = "failed"
            req.error = f"{name}: {e}"
            if asp is not None:
                asp.tag(outcome="rejected").end()
                self._trace_done(req)
            if _REG.enabled:
                _REG.counter("pt_fabric_rejected_total",
                             "requests a replica rejected at submit"
                             ).inc(replica=name, **self._flabels)
            return True            # consumed: remove from the queue
        if asp is not None:
            asp.tag(outcome="ok", rid=int(rid)).end()
            q = req.tqueue
            if q is not None and not prefill:
                req.tqueue = None
                q.tag(outcome="admitted", replica=name).end()
        req.state = "prefill" if prefill else "decode"
        req.replica = name
        req.local_rid = int(rid)
        self._assign[(name, int(rid))] = req.fid
        self._outstanding[name] = self._outstanding.get(name, 0) + 1
        self.routed[name] = self.routed.get(name, 0) + 1
        if _REG.enabled:
            _REG.counter("pt_fabric_tenant_admitted_total",
                         "requests released from the global queue").inc(
                tenant=req.tenant, **self._flabels)
        return True

    # -- polling / completion ------------------------------------------------

    def _poll_replicas(self) -> List[Tuple[int, int]]:
        delivered: List[Tuple[int, int]] = []
        for name in list(self._alive_names()):
            try:
                res = self.transport.poll(name)
            except ReplicaDown:
                self._on_replica_down(name)
                continue
            except (ValueError, RuntimeError) as e:
                self._app_error(name, "poll", e)
                continue
            if _TRACE.enabled and res.get("spans"):
                # replica-side spans piggyback on poll responses; the
                # router (which owns the roots) stitches them in
                _TRACE.ingest(res["spans"])
            now = time.perf_counter()
            arrived: Dict[int, List[int]] = {}
            for rid, tok in res.get("emitted", ()):
                fid = self._assign.get((name, int(rid)))
                if fid is None:
                    continue
                req = self._reqs.get(fid)
                if req is None:
                    continue
                if req.state != "decode" or req.replica != name:
                    continue         # prefill probe token: discarded
                arrived.setdefault(fid, []).append(int(tok))
            for fid, toks in arrived.items():
                req = self._reqs[fid]
                if req.trace is not None:
                    req.trace.event("tok", n=len(toks))
                req.delivered.extend(toks)
                if req.first_tok_t == 0.0:
                    req.first_tok_t = now
                if req.last_emit_t:
                    gap = (now - req.last_emit_t) / len(toks)
                    req.itl_gaps.extend([gap] * len(toks))
                req.last_emit_t = now
                delivered.extend((fid, t) for t in toks)
            for rid, toks in res.get("finished", {}).items():
                fid = self._assign.pop((name, int(rid)), None)
                if fid is None:
                    continue
                self._outstanding[name] = max(
                    0, self._outstanding.get(name, 0) - 1)
                req = self._reqs.get(fid)
                if req is None:
                    continue
                if req.state == "prefill" and req.replica == name:
                    self._complete_prefill(req, name)
                elif req.state == "decode" and req.replica == name:
                    req.result = np.asarray(toks, np.int32)
                    # authoritative stream: replay prefix + continuation
                    req.delivered = [int(t) for t in toks]
                    req.state = "done"
                    req.done_t = now
                    self._trace_done(req, replica=name)
                    self._latencies.append(
                        (req.first_tok_t - req.submit_t,
                         req.done_t - req.submit_t, len(toks)))
                    self._itl_gaps.extend(req.itl_gaps)
        return delivered

    def _complete_prefill(self, req: FabricRequest, src: str) -> None:
        """The prefill replica finished its 1-token probe: its tree now
        holds the prompt's full pages. Hand them to a decode replica
        (adopt seeds its tree), then submit the real request there —
        admission prefix-hits, so decode-side prefill work is at most
        one partial page. This placement deliberately SKIPS the
        capacity gate: the pages just landed in that replica's tree and
        waiting in its engine queue is cheaper than re-routing away
        from them."""
        req.prefill_done = True
        payload = None
        hsp = None
        if req.trace is not None and _TRACE.enabled:
            hsp = _TRACE.start("fabric::handoff_extract",
                               parent=req.trace, tags={"src": src})
        try:
            payload = self.transport.extract(src, req.prompt)
        except ReplicaDown:
            self._on_replica_down(src)
        except ValueError:
            payload = None
        if hsp is not None:
            hsp.tag(ok=payload is not None).end()
        cands = [n for n in self._serving_names() if n != src] \
            or self._serving_names()
        if not cands:
            # no decode replica right now: back to the queue (front —
            # it has waited longest)
            req.state, req.replica, req.local_rid = "queued", None, None
            self._queue.appendleft(req)
            return
        name, _how = self._pick(req, cands)
        if payload is not None:
            adp = None
            if req.trace is not None and _TRACE.enabled:
                adp = _TRACE.start("fabric::handoff_adopt",
                                   parent=req.trace,
                                   tags={"src": src, "dst": name})
            try:
                adopted = self.transport.adopt(name, payload)
                self.handoffs += 1
                nbytes = (payload["kv"].nbytes
                          + np.asarray(payload["tokens"]).nbytes)
                self.handoff_bytes += nbytes
                req.handoff_pages = int(adopted)
                if adp is not None:
                    adp.tag(outcome="ok", pages=int(adopted),
                            nbytes=nbytes).end()
                if _REG.enabled:
                    _REG.counter("pt_fabric_handoffs_total",
                                 "prefill→decode KV-page handoffs").inc(
                        src=src, dst=name, **self._flabels)
                    _REG.counter("pt_fabric_handoff_bytes_total",
                                 "KV bytes moved by handoffs").inc(
                        nbytes, src=src, dst=name, **self._flabels)
            except ReplicaDown:
                if adp is not None:
                    adp.tag(outcome="replica_down").end()
                self._on_replica_down(name)
                self.handoff_failures += 1
                self._fail_handoff_counter()
                req.state, req.replica, req.local_rid = \
                    "queued", None, None
                self._queue.appendleft(req)
                return
            except (ValueError, RuntimeError):
                # corrupt transfer or a pool that can't hold the pages:
                # serve COLD rather than stall the request
                if adp is not None:
                    adp.tag(outcome="failed").end()
                self.handoff_failures += 1
                self._fail_handoff_counter()
        else:
            self.handoff_failures += 1
            self._fail_handoff_counter()
        if not self._submit_to(req, name, prefill=False):
            req.state, req.replica, req.local_rid = "queued", None, None
            self._queue.appendleft(req)
        elif req.state != "failed" and _REG.enabled:
            # disagg decode placement is routing too — without this the
            # routed census undercounts exactly the traffic
            # disaggregation exists for
            _REG.counter("pt_fabric_routed_total",
                         "requests routed to a replica").inc(
                replica=name, how="disagg", **self._flabels)

    def _fail_handoff_counter(self) -> None:
        if _REG.enabled:
            _REG.counter("pt_fabric_handoff_failures_total",
                         "handoffs that fell back to cold serving").inc(
                **self._flabels)

    # -- failover ------------------------------------------------------------

    def _on_replica_down(self, name: str) -> None:
        """Replica death: re-queue its in-flight requests (front,
        original order) with their delivered tokens as replay prefixes.
        The re-dispatch happens in this same pass's _dispatch_queue or
        the next — survivors continue every stream token-identically
        with the remaining budget."""
        if name in self._dead:
            return
        self._dead.add(name)
        self._stale_rids[name] = sorted(
            rid for (n, rid) in self._assign if n == name)
        self._status.pop(name, None)
        self._digests.pop(name, None)
        self._hot.discard(name)
        self._outstanding.pop(name, None)
        lost = sorted(fid for (n, _rid), fid in self._assign.items()
                      if n == name)
        self._assign = {k: v for k, v in self._assign.items()
                        if k[0] != name}
        for fid in reversed(lost):
            req = self._reqs[fid]
            if req.state == "done":
                continue
            req.state, req.replica, req.local_rid = "queued", None, None
            req.readmissions += 1
            self.readmitted += 1
            if req.trace is not None and _TRACE.enabled:
                req.trace.event("replica_down")
                if req.tqueue is None:   # sibling queue span: the wait
                    req.tqueue = _TRACE.start(   # after re-admission
                        "fabric::queue", parent=req.trace,
                        tags={"readmission": req.readmissions})
            self._queue.appendleft(req)
            if _REG.enabled:
                _REG.counter(
                    "pt_fabric_readmitted_total",
                    "requests re-admitted after a replica death").inc(
                    tenant=req.tenant, **self._flabels)
        if _REG.enabled:
            _REG.counter("pt_fabric_replica_deaths_total",
                         "replicas lost").inc(replica=name,
                                              **self._flabels)

    # -- telemetry -----------------------------------------------------------

    def _tick_gauges(self) -> None:
        _REG.gauge("pt_fabric_queue_depth",
                   "requests waiting in the global queue").set(
            len(self._queue), **self._flabels)
        _REG.gauge("pt_fabric_replicas_alive",
                   "replicas the router can reach").set(
            len(self._alive_names()), **self._flabels)

    def latency_stats(self) -> Dict[str, float]:
        """Aggregate TTFT / end-to-end / ITL percentiles at the ROUTER
        boundary (what a client of the fabric observes), over the most
        recent 10k retired requests."""
        if not self._latencies:
            return {}
        arr = np.asarray(self._latencies, np.float64)
        out = {"requests": int(arr.shape[0]),
               "tokens": int(arr[:, 2].sum()),
               "ttft_p50_s": float(np.percentile(arr[:, 0], 50)),
               "ttft_p99_s": float(np.percentile(arr[:, 0], 99)),
               "latency_p50_s": float(np.percentile(arr[:, 1], 50)),
               "latency_p99_s": float(np.percentile(arr[:, 1], 99))}
        if self._itl_gaps:
            gaps = np.asarray(self._itl_gaps, np.float64)
            out["itl_p50_s"] = float(np.percentile(gaps, 50))
            out["itl_p99_s"] = float(np.percentile(gaps, 99))
        return out

    def reset_latency_stats(self) -> None:
        self._latencies.clear()
        self._itl_gaps.clear()

    def stats(self) -> Dict[str, object]:
        out = {"queued": len(self._queue),
               "outstanding": dict(self._outstanding),
               "routed": dict(self.routed),
               "affinity_hits": self.affinity_hits,
               "misrouted": self.misrouted,
               "cold_routes": self.cold_routes,
               "handoffs": self.handoffs,
               "handoff_bytes": self.handoff_bytes,
               "handoff_failures": self.handoff_failures,
               "readmitted": self.readmitted,
               "failed": dict(self.failed),
               "replicas_alive": self._alive_names(),
               "replicas_dead": sorted(self._dead),
               "hot": sorted(self._hot)}
        if self.shedder is not None:
            out["shed"] = self.shedder.stats()
        if self.fair is not None:
            out["tenant_admitted"] = dict(self.fair.admitted)
            out["tenant_admitted_tokens"] = {
                k: round(v, 1)
                for k, v in self.fair.admitted_tokens.items()}
            out["tenant_deferred"] = dict(self.fair.deferred)
        return out

    def publish_metrics(self) -> Dict[str, float]:
        """Aggregate percentile gauges + per-tenant counters into the
        registry (the fabric's drain-boundary publish; the per-replica
        engine series publish from the replicas themselves)."""
        lat = self.latency_stats()
        if not _REG.enabled:
            return lat
        for key, metric in (("ttft", "pt_fabric_ttft_seconds"),
                            ("latency", "pt_fabric_latency_seconds"),
                            ("itl", "pt_fabric_itl_seconds")):
            for q in ("p50", "p99"):
                v = lat.get(f"{key}_{q}_s")
                g = _REG.gauge(metric, f"fabric-aggregate {key} "
                                       f"percentile", "s")
                if v is not None:
                    g.set(v, q=q, **self._flabels)
                else:
                    g.clear(q=q, **self._flabels)
        if self.fair is not None:
            g = _REG.gauge("pt_fabric_tenant_admitted_tokens",
                           "uncached-suffix tokens admitted per tenant")
            for t, v in self.fair.admitted_tokens.items():
                g.set(v, tenant=t, **self._flabels)
            c = _REG.gauge("pt_fabric_tenant_deferred",
                           "fair-policy defer passes per tenant")
            for t, v in self.fair.deferred.items():
                c.set(v, tenant=t, **self._flabels)
        self._tick_gauges()
        return lat
