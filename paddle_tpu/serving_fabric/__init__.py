"""paddle_tpu.serving_fabric — router + replica pool + disaggregated
prefill/decode over N ContinuousBatchingEngines (ISSUE 12).

The L6 orchestration layer (reference: ``fleet``/``ps``/``rpc``) for the
serving stack PRs 3/6/7 built inside one engine:

* :class:`ServingFabric` — the front door: global queue, PREFIX-AFFINITY
  routing on replica-advertised digests (least-loaded fallback, ITL
  hysteresis), per-tenant weighted fair admission, prefill/decode
  disaggregation via KV-page handoff, and failover re-admission with
  replay-exact streams.
* :class:`Replica` / :func:`build_replicas` — one engine behind the
  fabric verb set (submit/poll/status/extract/adopt).
* :class:`InProcTransport` / :class:`TcpTransport` — the fleet/rpc
  split: same verbs in-process (tier-1, chaos) or over JSON/TCP.
* :class:`PrefixDigest` — the compact routing signal: rolling page
  fingerprints of a replica's radix-tree top.
* :class:`TenantFairPolicy` / :class:`TenantSpec` — router-level
  weighted fairness + token-bucket quotas priced in uncached-suffix
  tokens.

Front-door robustness (ISSUE 16):

* :class:`FrontDoor` / :class:`FabricClient` — concurrent streaming
  TCP edge with per-id dedupe + replay resume, and the retrying /
  hedging client of it.
* :class:`BreakerTransport` — per-replica circuit breaker (op-class
  timeouts, open → half-open probe → close) wrapping any transport.
* :class:`LoadShedder` + the typed rejections
  (:class:`FabricRejected`, :class:`Overloaded`,
  :class:`AllReplicasDown`, :class:`DeadlineExceeded`) and
  :class:`Backoff` — admission that can say no, typed and bounded.

Quickstart::

    from paddle_tpu.serving_fabric import (ServingFabric, InProcTransport,
                                           build_replicas)

    reps = build_replicas(model, 2, page_size=128, max_len=2048)
    fabric = ServingFabric(InProcTransport(reps), policy="affinity")
    fid = fabric.submit(prompt_ids, max_new_tokens=64, tenant="a")
    out = fabric.run()          # {fid: np.ndarray tokens}
"""

from __future__ import annotations

from .breaker import BreakerTransport
from .client import FabricClient
from .digest import PrefixDigest
from .fair import TenantFairPolicy, TenantSpec
from .frontdoor import FrontDoor
from .replica import Replica, build_replicas
from .robust import (AllReplicasDown, Backoff, DeadlineExceeded,
                     FabricRejected, LoadShedder, Overloaded)
from .router import FabricRequest, ServingFabric
from .transport import (FabricTransport, InProcTransport, ReplicaDown,
                        TcpReplicaServer, TcpTransport, payload_from_wire,
                        payload_to_wire)

__all__ = [
    "ServingFabric", "FabricRequest",
    "Replica", "build_replicas",
    "FabricTransport", "InProcTransport", "TcpTransport",
    "TcpReplicaServer", "ReplicaDown",
    "payload_to_wire", "payload_from_wire",
    "PrefixDigest", "TenantFairPolicy", "TenantSpec",
    "FrontDoor", "FabricClient", "BreakerTransport",
    "LoadShedder", "Backoff", "FabricRejected", "Overloaded",
    "AllReplicasDown", "DeadlineExceeded",
]
