"""FabricTransport: how the router reaches its replicas.

The reference stack splits orchestration (``fleet``) from the byte
mover (``rpc``); this module is that split for the serving fabric. The
router speaks ONE verb set — submit / poll / status / extract / adopt —
against a :class:`FabricTransport`, and two implementations provide it:

* :class:`InProcTransport` — N :class:`~.replica.Replica` objects in one
  process, direct method calls. This is the tier-1/CI shape (CPU, no
  sockets) and the chaos harness's: ``kill()`` drops a replica exactly
  the way a SIGKILL would look from the router's side — every
  subsequent op raises :class:`ReplicaDown`, with no goodbye.
* :class:`TcpTransport` + :class:`TcpReplicaServer` — newline-delimited
  JSON over TCP for multi-host; KV-page handoff payloads cross as
  base64 (:func:`payload_to_wire` / :func:`payload_from_wire`). Thin on
  purpose: framing, encoding and death detection only — routing policy
  never leaks down here.

Every fault surfaces as :class:`ReplicaDown`; the ROUTER owns recovery
(re-admission with the request's remaining budget), transports only
detect.
"""

from __future__ import annotations

import base64
import json
import socket
import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ReplicaDown", "FabricTransport", "InProcTransport",
           "TcpTransport", "TcpReplicaServer", "payload_to_wire",
           "payload_from_wire"]


class ReplicaDown(RuntimeError):
    """The replica is unreachable/dead; the router must fail over."""

    def __init__(self, name: str, why: str = ""):
        super().__init__(f"replica {name!r} is down"
                         + (f": {why}" if why else ""))
        self.name = name


class FabricTransport:
    """The verb set the router drives; every method may raise
    :class:`ReplicaDown` for its replica."""

    def replica_names(self) -> List[str]:
        raise NotImplementedError

    def submit(self, name: str, req: dict) -> int:
        """Queue a request payload on ``name``; returns its local rid.

        Trace propagation contract (ISSUE 19): when distributed tracing
        is on, ``req`` carries a JSON-safe ``"trace"`` key (the wire
        form of :class:`~..observability.tracing.TraceContext`) that
        every transport must deliver verbatim — explicit context
        injection is what lets replica-side spans stitch under the
        router's tree across a process boundary."""
        raise NotImplementedError

    def poll(self, name: str) -> dict:
        """Advance ``name`` one scheduler tick; returns
        ``{"emitted": [[rid, tok], ...], "finished": {rid: [tokens]}}``
        plus, when tracing is on, ``"spans"``: finished replica-side
        span dicts piggybacking home for the router to ingest."""
        raise NotImplementedError

    def status(self, name: str) -> dict:
        """Heartbeat: load, pool, latency gauges + prefix digest."""
        raise NotImplementedError

    def extract(self, name: str, tokens) -> Optional[dict]:
        """serialize_pages on ``name`` for ``tokens`` (handoff source)."""
        raise NotImplementedError

    def adopt(self, name: str, payload: dict) -> int:
        """adopt_pages on ``name``; returns pages adopted."""
        raise NotImplementedError

    # Optional verbs (ISSUE 16). Defaults are safe no-ops so scripted
    # stub transports in tests (and third-party transports) keep
    # working without implementing them: a False/{} answer just means
    # "this transport can't do that", which the router tolerates.

    def cancel(self, name: str, rid: int) -> bool:
        """Terminate local ``rid`` on ``name`` and free its slot/pages
        (deadline miss, client disconnect, slow-loris eviction).
        Returns True when the request existed and was cancelled."""
        return False

    def configure(self, name: str, knobs: dict) -> dict:
        """Push runtime knobs (brownout ``spec_k`` cap, …) to ``name``;
        returns the knobs the replica actually applied."""
        return {}


# ---------------------------------------------------------------------------
# in-process
# ---------------------------------------------------------------------------

class InProcTransport(FabricTransport):
    """N replicas, one process — the tier-1-testable fabric. ``kill``
    simulates replica death for the chaos tests: the object stays (its
    pages/engine die with it conceptually) but every op raises
    :class:`ReplicaDown` from then on."""

    def __init__(self, replicas):
        # accepts a list (names from the replicas) or a dict
        if isinstance(replicas, dict):
            self._replicas = dict(replicas)
        else:
            self._replicas = {r.name: r for r in replicas}
        self._dead: set = set()
        self._hung: Dict[str, threading.Event] = {}

    def _get(self, name: str, op: str = ""):
        if name in self._dead:
            raise ReplicaDown(name, "killed")
        ev = self._hung.get(name)
        if ev is not None and op != "status":
            # the hang failure mode (testing/chaos.hang_replica): the
            # replica heartbeats but never progresses — callers block
            # here exactly like a wedged remote. The engine is NEVER
            # touched by a hung op, so no state mutates during the
            # hang; on release the op reports ReplicaDown (the stalled
            # RPC's answer is lost) and the breaker's half-open probe
            # is what re-establishes service.
            ev.wait()
            raise ReplicaDown(name, "hang released; op abandoned")
        r = self._replicas.get(name)
        if r is None:
            raise ReplicaDown(name, "unknown replica")
        return r

    def replica_names(self) -> List[str]:
        return list(self._replicas)

    def kill(self, name: str) -> None:
        """Drop ``name`` mid-whatever-it-was-doing (chaos helper)."""
        self._dead.add(name)
        ev = self._hung.pop(name, None)
        if ev is not None:
            ev.set()

    def hang(self, name: str) -> None:
        """Wedge ``name`` (chaos helper): ``status`` still answers but
        every other op blocks — crash's evil twin, the failure mode the
        circuit breaker's op-class timeouts exist for."""
        if name not in self._replicas:
            raise ReplicaDown(name, "unknown replica")
        self._hung.setdefault(name, threading.Event())

    def unhang(self, name: str) -> None:
        """Release a hang: blocked ops wake (and report ReplicaDown);
        fresh ops succeed again."""
        ev = self._hung.pop(name, None)
        if ev is not None:
            ev.set()

    def alive(self, name: str) -> bool:
        return name in self._replicas and name not in self._dead

    def submit(self, name, req):
        return self._get(name, "submit").submit(req)

    def poll(self, name):
        return self._get(name, "poll").poll()

    def status(self, name):
        return self._get(name, "status").status()

    def extract(self, name, tokens):
        return self._get(name, "extract").extract(tokens)

    def adopt(self, name, payload):
        return self._get(name, "adopt").adopt(payload)

    def cancel(self, name, rid):
        return self._get(name, "cancel").cancel(rid)

    def configure(self, name, knobs):
        return self._get(name, "configure").configure(knobs)


# ---------------------------------------------------------------------------
# KV-payload wire codec (shared by the TCP transport and any file/queue
# transport a deployment adds)
# ---------------------------------------------------------------------------

def payload_to_wire(payload: dict) -> dict:
    """serialize_pages dict → JSON-safe dict (tokens as list, kv as
    base64 of the raw buffer; shape/dtype/sha256 ride along so the far
    side validates END-TO-END, not per-hop). v2 payloads from an int8
    pool additionally carry the per-page fp32 scales (base64, fp32
    little-endian) — the sha256 covers them, so tampered scales are
    rejected exactly like tampered page bytes."""
    kv = payload["kv"]
    wire = {"fmt": payload["fmt"], "page_size": payload["page_size"],
            "tokens": np.asarray(payload["tokens"],
                                 np.int32).tolist(),
            "dtype": payload["dtype"], "shape": list(payload["shape"]),
            "sha256": payload["sha256"],
            "kv_b64": base64.b64encode(
                np.ascontiguousarray(kv).tobytes()).decode("ascii")}
    if payload.get("scales") is not None:
        sc = np.ascontiguousarray(np.asarray(payload["scales"],
                                             np.float32))
        wire["scales_b64"] = base64.b64encode(sc.tobytes()).decode("ascii")
        wire["scales_shape"] = list(payload["scales_shape"])
    return wire


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def payload_from_wire(wire: dict) -> dict:
    """Inverse of :func:`payload_to_wire`. Decode errors become
    ValueError — the same rejection class adopt_pages raises, so a
    mangled wire payload can't crash the replica loop."""
    try:
        raw = base64.b64decode(wire["kv_b64"])
        kv = np.frombuffer(raw, dtype=_np_dtype(wire["dtype"])) \
            .reshape(wire["shape"])
        scales = None
        if wire.get("scales_b64") is not None:
            scales = np.frombuffer(
                base64.b64decode(wire["scales_b64"]),
                dtype=np.float32).reshape(wire["scales_shape"])
    except Exception as e:
        raise ValueError(f"handoff payload: undecodable wire form "
                         f"({e})")
    out = {"fmt": wire.get("fmt"), "page_size": wire.get("page_size"),
           "tokens": np.asarray(wire.get("tokens", ()), np.int32),
           "kv": kv, "dtype": wire.get("dtype"),
           "shape": list(wire.get("shape", ())),
           "sha256": wire.get("sha256")}
    if scales is not None:
        out["scales"] = scales
        out["scales_shape"] = list(wire.get("scales_shape", ()))
    return out


# ---------------------------------------------------------------------------
# TCP (multi-host)
# ---------------------------------------------------------------------------

class TcpReplicaServer:
    """Host one replica behind newline-delimited JSON on a TCP socket.
    Single-threaded request handling on purpose: the router is the only
    client and the engine is not thread-safe — ops execute in arrival
    order, exactly like the in-proc transport."""

    def __init__(self, replica, host: str = "127.0.0.1", port: int = 0,
                 max_line_bytes: int = 32 << 20):
        self.replica = replica
        self.max_line_bytes = int(max_line_bytes)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(4)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # EVERY live connection, not just the latest: stop() must sever
        # them all or a peer holding an older socket keeps a zombie
        # replica answering after "death"
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def _handle(self, op: str, args: dict):
        if op == "submit":
            return self.replica.submit(args["req"])
        if op == "poll":
            return self.replica.poll()
        if op == "status":
            return self.replica.status()
        if op == "extract":
            payload = self.replica.extract(args["tokens"])
            return None if payload is None else payload_to_wire(payload)
        if op == "adopt":
            return self.replica.adopt(payload_from_wire(args["payload"]))
        if op == "cancel":
            return self.replica.cancel(args["rid"])
        if op == "configure":
            return self.replica.configure(args.get("knobs") or {})
        raise ValueError(f"unknown op {op!r}")

    def serve_forever(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.25)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            with self._conns_lock:
                self._conns.add(conn)
            try:
                with conn:
                    f = conn.makefile("rwb")
                    while not self._stop.is_set():
                        # bounded read: a peer that streams bytes
                        # without ever sending a newline gets cut off
                        # at the cap instead of growing server memory
                        line = f.readline(self.max_line_bytes + 1)
                        if not line:
                            break
                        if (len(line) > self.max_line_bytes
                                or not line.endswith(b"\n")):
                            break
                        try:
                            msg = json.loads(line)
                            result = self._handle(msg.get("op", ""),
                                                  msg.get("args", {}))
                            out = {"ok": True, "result": result}
                        except Exception as e:
                            out = {"ok": False,
                                   "error": f"{type(e).__name__}: {e}"}
                        f.write(json.dumps(out).encode() + b"\n")
                        f.flush()
            except OSError:
                pass
            finally:
                with self._conns_lock:
                    self._conns.discard(conn)

    def start(self) -> "TcpReplicaServer":
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Tear the replica down like a kill: the LISTENER closes and
        every live router connection is severed too — the router's next
        op sees a reset (→ ReplicaDown), not a replica that keeps
        answering through a socket it already held."""
        self._stop.set()
        with self._conns_lock:
            conns = list(self._conns)
        for s in [self._sock] + conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class TcpTransport(FabricTransport):
    """Router-side client: one persistent connection per replica,
    request/response JSON lines. Any socket fault — refused, reset,
    torn mid-line — is :class:`ReplicaDown`; the router decides what to
    do about it."""

    def __init__(self, endpoints: Dict[str, tuple],
                 connect_timeout_s: float = 2.0,
                 op_timeout_s: float = 60.0,
                 max_line_bytes: int = 32 << 20):
        self._endpoints = dict(endpoints)
        self._conns: Dict[str, object] = {}
        self._connect_timeout = float(connect_timeout_s)
        self._op_timeout = float(op_timeout_s)
        self.max_line_bytes = int(max_line_bytes)

    def replica_names(self) -> List[str]:
        return list(self._endpoints)

    def _call(self, name: str, op: str, args: dict):
        # A persistent connection can be STALE (the server restarted
        # since the last op): retry exactly once on a fresh socket in
        # that case, so a rolling replica restart looks like a blip,
        # not ReplicaDown. First-contact failures are never retried —
        # nothing was stale, the replica is genuinely unreachable.
        had_conn = name in self._conns
        try:
            return self._call_once(name, op, args)
        except ReplicaDown:
            if not had_conn:
                raise
            return self._call_once(name, op, args)

    def _call_once(self, name: str, op: str, args: dict):
        try:
            f = self._conns.get(name)
            if f is None:
                host, port = self._endpoints[name]
                s = socket.create_connection(
                    (host, port), timeout=self._connect_timeout)
                s.settimeout(self._op_timeout)
                f = self._conns[name] = s.makefile("rwb")
            f.write(json.dumps({"op": op, "args": args}).encode() + b"\n")
            f.flush()
            line = f.readline(self.max_line_bytes + 1)
            if not line:
                raise ConnectionError("connection closed")
            if (len(line) > self.max_line_bytes
                    or not line.endswith(b"\n")):
                raise ConnectionError("overlong response line")
            resp = json.loads(line)
        except (OSError, ValueError, KeyError) as e:
            self._conns.pop(name, None)
            raise ReplicaDown(name, str(e))
        if not resp.get("ok"):
            # an application error (bad payload) is NOT replica death —
            # re-raise as ValueError so the router treats it as a
            # failed op against a live replica
            raise ValueError(resp.get("error", "remote error"))
        return resp.get("result")

    def submit(self, name, req):
        # numpy arrays → lists for the JSON hop
        wire = dict(req)
        for k in ("prompt", "replay"):
            if wire.get(k) is not None:
                wire[k] = np.asarray(wire[k], np.int32).tolist()
        return self._call(name, "submit", {"req": wire})

    def poll(self, name):
        return self._call(name, "poll", {})

    def status(self, name):
        return self._call(name, "status", {})

    def extract(self, name, tokens):
        wire = self._call(name, "extract",
                          {"tokens": np.asarray(tokens,
                                                np.int32).tolist()})
        return None if wire is None else payload_from_wire(wire)

    def adopt(self, name, payload):
        return self._call(name, "adopt",
                          {"payload": payload_to_wire(payload)})

    def cancel(self, name, rid):
        return bool(self._call(name, "cancel", {"rid": int(rid)}))

    def configure(self, name, knobs):
        return self._call(name, "configure", {"knobs": dict(knobs)})

    def close(self) -> None:
        for f in self._conns.values():
            try:
                f.close()
            except OSError:
                pass
        self._conns.clear()
