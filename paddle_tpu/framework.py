"""Framework-level utilities: device control, save/load, jit.

Reference: python/paddle/device/ (set_device), python/paddle/framework/io.py
(save:721, load:960), python/paddle/jit/api.py (to_static:171).

``jit.to_static`` maps onto jax.jit: the reference's SOT/AST graph capture is
replaced by JAX tracing (every op here is already trace-friendly), so the
decorator only manages static args and an optional AOT-lowered export.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .autograd import no_grad  # re-export


_CURRENT_DEVICE = None


def set_device(device: str):
    """'tpu' | 'cpu' | 'tpu:N' (mirrors paddle.set_device)."""
    global _CURRENT_DEVICE
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    platform = {"gpu": "gpu", "tpu": "tpu", "cpu": "cpu", "xpu": "tpu"}.get(name)
    if platform is None:
        raise ValueError(f"unknown device {device}")
    devs = jax.devices(platform)
    _CURRENT_DEVICE = devs[idx]
    jax.config.update("jax_default_device", _CURRENT_DEVICE)
    return _CURRENT_DEVICE


def get_device() -> str:
    d = _CURRENT_DEVICE or jax.devices()[0]
    return f"{d.platform}:{d.id}"


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    from .ops.registry import device_is_tpu
    return any(device_is_tpu(d) for d in jax.devices())


# -- save / load (reference: python/paddle/framework/io.py:721,960) ----------

def _to_numpy_tree(obj):
    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, obj)


def save(obj: Any, path, protocol: int = 4) -> None:
    """Pickle-based save of (nested) state dicts; jax Arrays stored as
    numpy. ``path`` may be a file path or a writable file object
    (reference: paddle.save supports BytesIO). A static ``Program`` saves
    as its descriptor (feed specs + parameter values) — the recorded
    builders are closures and do not pickle; the executable artifact is
    jit.save. The orbax-backed sharded checkpoint lives in
    paddle_tpu.checkpoint."""
    from .static import Program
    if isinstance(obj, Program):
        # state_dict() force-materializes parameters first (a built but
        # never-run program has no _nn_params yet — saving without this
        # would silently drop every weight)
        params = {k: np.asarray(v)
                  for k, v in obj.state_dict("param").items()}
        obj = {"__pt_program_desc__": True,
               "feed_specs": {n: (tuple(s.shape), str(s.dtype))
                              for n, s in obj._feed_specs.items()},
               "params": params}
    payload = _to_numpy_tree(obj)
    if hasattr(path, "write"):                   # file-like (BytesIO)
        pickle.dump(payload, path, protocol=protocol)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(payload, f, protocol=protocol)


def load(path, return_numpy: bool = False) -> Any:
    if hasattr(path, "read"):                    # file-like (BytesIO)
        obj = pickle.load(path)
    else:
        with open(path, "rb") as f:
            obj = pickle.load(f)
    if isinstance(obj, dict) and obj.get("__pt_program_desc__"):
        from .static import Program, InputSpec
        prog = Program()
        for n, (shape, dtype) in obj["feed_specs"].items():
            prog._feed_specs[n] = InputSpec(shape, dtype, n)
        prog.__dict__["_nn_params"] = dict(obj["params"])
        return prog
    if return_numpy:
        return obj
    return jax.tree.map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, obj)


# jit lives in paddle_tpu/jit/ (to_static + StableHLO export save/load)

# doctest path: paddle.framework.ParamAttr (reference re-export)
from .base import ParamAttr  # noqa: E402,F401
