"""Framework-level utilities: device control, save/load, jit.

Reference: python/paddle/device/ (set_device), python/paddle/framework/io.py
(save:721, load:960), python/paddle/jit/api.py (to_static:171).

``jit.to_static`` maps onto jax.jit: the reference's SOT/AST graph capture is
replaced by JAX tracing (every op here is already trace-friendly), so the
decorator only manages static args and an optional AOT-lowered export.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .autograd import no_grad  # re-export


_CURRENT_DEVICE = None


def set_device(device: str):
    """'tpu' | 'cpu' | 'tpu:N' (mirrors paddle.set_device)."""
    global _CURRENT_DEVICE
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    platform = {"gpu": "gpu", "tpu": "tpu", "cpu": "cpu", "xpu": "tpu"}.get(name)
    if platform is None:
        raise ValueError(f"unknown device {device}")
    devs = jax.devices(platform)
    _CURRENT_DEVICE = devs[idx]
    jax.config.update("jax_default_device", _CURRENT_DEVICE)
    return _CURRENT_DEVICE


def get_device() -> str:
    d = _CURRENT_DEVICE or jax.devices()[0]
    return f"{d.platform}:{d.id}"


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


# -- save / load (reference: python/paddle/framework/io.py:721,960) ----------

def _to_numpy_tree(obj):
    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, obj)


def save(obj: Any, path: str, protocol: int = 4) -> None:
    """Pickle-based save of (nested) state dicts; jax Arrays stored as numpy.
    The orbax-backed sharded checkpoint lives in paddle_tpu.checkpoint."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_numpy_tree(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        return obj
    return jax.tree.map(
        lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, obj)


# -- jit (reference: python/paddle/jit/api.py:171 to_static) -----------------

class _JitNamespace:
    @staticmethod
    def to_static(function=None, input_spec=None, full_graph: bool = True,
                  backend=None, static_argnums=None):
        """Compile a function (or Layer.forward bound method) with jax.jit."""
        def deco(fn):
            if hasattr(fn, "functional"):  # a Layer: jit its functional view
                layer = fn
                pure = layer.functional()
                jitted = jax.jit(pure)
                def call(*args, **kwargs):
                    return jitted(layer.raw_state(), *args, **kwargs)
                call.__wrapped_layer__ = layer
                return call
            return jax.jit(fn, static_argnums=static_argnums)
        if function is None:
            return deco
        return deco(function)

    @staticmethod
    def save(layer, path: str, input_spec=None):
        """Export: save state dict + (optionally) AOT-lowered HLO text.
        Reference analogue: paddle.jit.save (serialized inference program)."""
        save(getattr(layer, "state_dict", lambda: layer)(), path + ".pdparams")
        if input_spec is not None and hasattr(layer, "functional"):
            pure = layer.functional()
            lowered = jax.jit(pure).lower(layer.raw_state(), *input_spec)
            with open(path + ".hlo.txt", "w") as f:
                f.write(lowered.as_text())

    @staticmethod
    def load(path: str):
        return load(path + ".pdparams")


jit = _JitNamespace()
