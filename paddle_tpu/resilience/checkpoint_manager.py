"""CheckpointManager — crash-safe periodic checkpointing with retention.

Reference analogue: python/paddle/base/incubate/checkpoint/auto_checkpoint.py
(``TrainEpochRange`` periodic snapshots + GC) hardened for the preemption
realities of a multi-day TPU pod run. The manager wraps
``paddle_tpu.checkpoint`` (orbax storage) with:

* an **atomic commit protocol** — a ``step_N.PENDING`` sidecar is created
  before the orbax write and a ``_COMMITTED`` marker (carrying the manifest
  checksum) is written inside the step dir only after the write is durable,
  so a crash at ANY point mid-save can never be mistaken for a valid
  checkpoint;
* a **manifest** (`_MANIFEST.json`): every file's size + sha256, verified on
  restore — bit-rot or a torn write quarantines the step instead of loading
  garbage into a 8B-param run;
* **retention**: keep-last-N (rolling window) plus keep-every-M (permanent
  milestones for post-hoc eval);
* **quarantine** of corrupt/uncommitted step dirs under ``_quarantine/`` —
  evidence is preserved, resume falls back to the previous good step;
* **retry with jittered exponential backoff** on transient I/O failures
  (GCS 5xx, NFS hiccups) — a single flaky write must not kill the run.

Single-writer assumption: one manager instance (the rank-0 driver of a
single-program run) owns ``root``; orbax itself fans the actual shard writes
out across hosts.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import re
import shutil
import time
import warnings
from typing import Any, Dict, List, Optional

from .. import checkpoint as _ckpt
from . import reshard as _reshard
from ..observability.goodput import ledger as _ledger
from ..observability.metrics import REGISTRY as _REG

__all__ = ["CheckpointManager", "CheckpointCorruption"]

_STEP_RE = re.compile(r"^step_(\d+)$")
MANIFEST_NAME = "_MANIFEST.json"
COMMIT_MARKER = "_COMMITTED"
QUARANTINE_DIR = "_quarantine"


class CheckpointCorruption(RuntimeError):
    """A checkpoint failed manifest verification (and was quarantined)."""


def _sha256_file(path: str, chunk: int = 1 << 20,
                 watchdog=None) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
            if watchdog is not None:
                watchdog.tick()    # a multi-GB shard hashes for minutes
    return h.hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class CheckpointManager:
    """Crash-safe checkpoint directory of ``step_N`` orbax checkpoints.

    Layout under ``root``::

        step_300/            committed checkpoint (has _MANIFEST + _COMMITTED)
        step_400.PENDING     sidecar: step_400 save is in flight / died
        step_400/            NOT valid until _COMMITTED exists
        _quarantine/         corrupt or uncommitted dirs moved aside

    ``save`` is synchronous by default; with ``async_save=True`` the orbax
    write happens on a background thread and the commit marker is written by
    :meth:`finalize` (called automatically at the next save/restore/close).
    """

    def __init__(self, root: str, *, save_interval_steps: int = 100,
                 keep_last_n: int = 3, keep_every_m: int = 0,
                 async_save: bool = False, max_retries: int = 3,
                 backoff_base_s: float = 0.25, backoff_max_s: float = 30.0,
                 mesh=None, spec_tree=None, plan=None):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.save_interval_steps = max(1, int(save_interval_steps))
        self.keep_last_n = max(1, int(keep_last_n))
        self.keep_every_m = max(0, int(keep_every_m))
        self.async_save = bool(async_save)
        self.max_retries = max(0, int(max_retries))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.mesh = mesh
        self.spec_tree = spec_tree
        # active ShardingPlan: recorded as _PLAN.json in every save; on
        # restore, a saved plan with DIFFERENT axes triggers the reshard
        # path (resilience/reshard.py). None = implicit single-device plan.
        self.plan = plan
        self.last_restored_plan = None
        self._pending: Optional[int] = None
        self._rng = random.Random()
        os.makedirs(self.root, exist_ok=True)
        self._sweep_stale()

    # -- paths -------------------------------------------------------------

    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step)}")

    def _pending_path(self, step: int) -> str:
        return self.step_dir(step) + ".PENDING"

    # -- inventory ---------------------------------------------------------

    def committed_steps(self) -> List[int]:
        """Steps with a commit marker, ascending (uncommitted dirs from a
        crashed save are invisible here by construction)."""
        steps = []
        if not os.path.isdir(self.root):
            return steps
        for name in os.listdir(self.root):
            m = _STEP_RE.match(name)
            if not m:
                continue
            d = os.path.join(self.root, name)
            if os.path.isfile(os.path.join(d, COMMIT_MARKER)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_committed(self) -> Optional[int]:
        steps = self.committed_steps()
        return steps[-1] if steps else None

    # -- save --------------------------------------------------------------

    def save(self, step: int, tree: Dict[str, Any], *,
             async_save: Optional[bool] = None, force: bool = False,
             watchdog=None) -> bool:
        """Checkpoint ``tree`` as ``step_N``. Returns False if the step is
        already committed (and ``force`` is unset). Async saves are
        committed by the next :meth:`finalize`. ``watchdog`` is ticked
        through the synchronous commit (manifest hashing) so a large sync
        save — notably the final preemption save — is not misread as a
        hung step and killed mid-checkpoint."""
        step = int(step)
        t0 = time.perf_counter()
        # host-blocking extent books as checkpoint_save in the goodput
        # ledger (async saves: only the enqueue + previous-save drain —
        # the background write itself never owns the step loop's clock)
        with _ledger().span("checkpoint_save"):
            self.finalize(watchdog=watchdog)    # previous async save first
            if not force and os.path.isfile(
                    os.path.join(self.step_dir(step), COMMIT_MARKER)):
                return False
            use_async = (self.async_save if async_save is None
                         else bool(async_save))
            sdir = self.step_dir(step)
            if os.path.isdir(sdir):     # failed earlier attempt: clear it
                shutil.rmtree(sdir, ignore_errors=True)
            _atomic_write(
                self._pending_path(step),
                json.dumps({"step": step, "ts": time.time()}).encode())
            self._with_retries(
                lambda: _ckpt.save_state_dict(tree, sdir,
                                              async_save=use_async),
                what=f"save step_{step}")
            if use_async:
                self._pending = step
            else:
                self._commit(step, watchdog=watchdog)
        if _REG.enabled:
            mode = "async" if use_async else "sync"
            _REG.counter("pt_checkpoint_saves_total",
                         "checkpoints written").inc(mode=mode)
            _REG.histogram("pt_checkpoint_save_seconds",
                           "host-blocking save duration", "s").observe(
                time.perf_counter() - t0, mode=mode)
        return True

    def finalize(self, watchdog=None) -> Optional[int]:
        """Commit the in-flight async save (if any): wait for durability,
        then write manifest + marker. A background write failure is
        re-raised here (never swallowed) after quarantining the partial
        step dir. ``watchdog`` (a StepWatchdog) is ticked across the wait
        so a hung remote write is still detected as a stall."""
        if self._pending is None:
            return None
        step, self._pending = self._pending, None
        with _ledger().span("checkpoint_save"):
            try:
                _ckpt.wait_until_finished(watchdog=watchdog)
            except Exception:
                self._quarantine(step, "async-save-failed")
                raise
            self._commit(step, watchdog=watchdog)
        return step

    def wait(self, watchdog=None) -> Optional[int]:
        """Alias for :meth:`finalize` (drain pending writes)."""
        return self.finalize(watchdog=watchdog)

    def close(self) -> None:
        self.finalize()

    def _commit(self, step: int, watchdog=None) -> None:
        sdir = self.step_dir(step)
        # record the active plan BEFORE the manifest walk so _PLAN.json is
        # hashed + verified like every other payload file
        self._with_retries(
            lambda: _reshard.write_plan(sdir, self.plan, step),
            what=f"plan step_{step}")
        manifest = self._build_manifest(sdir, step, watchdog=watchdog)
        payload = json.dumps(manifest, sort_keys=True).encode()
        self._with_retries(
            lambda: _atomic_write(os.path.join(sdir, MANIFEST_NAME), payload),
            what=f"manifest step_{step}")
        marker = json.dumps({
            "step": step, "ts": time.time(),
            "manifest_sha256": hashlib.sha256(payload).hexdigest(),
        }, sort_keys=True).encode()
        self._with_retries(
            lambda: _atomic_write(os.path.join(sdir, COMMIT_MARKER), marker),
            what=f"commit step_{step}")
        try:
            os.remove(self._pending_path(step))
        except FileNotFoundError:
            pass
        self._gc()

    @staticmethod
    def _build_manifest(sdir: str, step: int,
                        watchdog=None) -> Dict[str, Any]:
        """Hash every payload file. This runs on the CALLING thread — for a
        multi-GB checkpoint it stalls the step loop for the hash duration
        (the price of an end-to-end integrity check); the watchdog is
        ticked per file so the stall is never misread as a hung step."""
        files = {}
        for dirpath, _dirs, names in os.walk(sdir):
            for name in names:
                if name in (MANIFEST_NAME, COMMIT_MARKER):
                    continue
                if watchdog is not None:
                    watchdog.tick()
                full = os.path.join(dirpath, name)
                rel = os.path.relpath(full, sdir)
                files[rel] = {"size": os.path.getsize(full),
                              "sha256": _sha256_file(full,
                                                     watchdog=watchdog)}
        return {"step": step, "files": files}

    # -- verify / quarantine ------------------------------------------------

    def verify(self, step: int, watchdog=None) -> bool:
        """Recheck a committed step against its manifest: marker parses,
        manifest bytes match the marker's checksum, every listed file exists
        with matching size + sha256. ``watchdog`` is ticked through the
        hashing (mid-fit rollback restores run with the step watchdog
        armed)."""
        sdir = self.step_dir(step)
        try:
            with open(os.path.join(sdir, COMMIT_MARKER), "rb") as f:
                marker = json.loads(f.read())
            with open(os.path.join(sdir, MANIFEST_NAME), "rb") as f:
                payload = f.read()
            if hashlib.sha256(payload).hexdigest() != marker["manifest_sha256"]:
                return False
            manifest = json.loads(payload)
            for rel, meta in manifest["files"].items():
                if watchdog is not None:
                    watchdog.tick()
                full = os.path.join(sdir, rel)
                if not os.path.isfile(full):
                    return False
                if os.path.getsize(full) != meta["size"]:
                    return False
                if _sha256_file(full, watchdog=watchdog) != meta["sha256"]:
                    return False
            return True
        except (OSError, ValueError, KeyError, TypeError):
            return False

    def _quarantine(self, step: int, reason: str) -> None:
        self._quarantine_path(self.step_dir(step), f"step_{step}-{reason}",
                              reason)
        try:
            os.remove(self._pending_path(step))
        except FileNotFoundError:
            pass

    def _quarantine_path(self, path: str, tag: str, reason: str) -> None:
        if not os.path.isdir(path):
            return
        qroot = os.path.join(self.root, QUARANTINE_DIR)
        os.makedirs(qroot, exist_ok=True)
        base = os.path.join(qroot, tag)
        dst, k = base, 0
        while os.path.exists(dst):
            k += 1
            dst = f"{base}-{k}"
        shutil.move(path, dst)
        if _REG.enabled:
            _REG.counter("pt_checkpoint_quarantines_total",
                         "step dirs moved aside as suspect").inc(
                reason=reason)

    def quarantined(self) -> List[str]:
        qroot = os.path.join(self.root, QUARANTINE_DIR)
        if not os.path.isdir(qroot):
            return []
        return sorted(os.listdir(qroot))

    def _sweep_stale(self) -> None:
        """At startup, clean what a crashed predecessor left behind so
        restores see only committed checkpoints:

        * step dirs with a PENDING sidecar and no commit marker
          (crash between orbax write and commit) → quarantine;
        * orphan sidecars (dir never materialized) → delete;
        * torn dirs from a SIGKILL mid-async-save — the orbax tmp dir
          (``step_N.orbax-checkpoint-tmp-*``) that never got renamed, or
          a ``step_N`` dir with neither commit marker nor orbax metadata
          → quarantine if non-empty (evidence), delete if empty — with a
          single aggregate warning, not silent skipping by latest_step."""
        torn: List[str] = []
        for name in list(os.listdir(self.root)):
            if not name.endswith(".PENDING"):
                continue
            stem = name[:-len(".PENDING")]
            m = _STEP_RE.match(stem)
            if m is None:
                continue
            step = int(m.group(1))
            sdir = self.step_dir(step)
            if os.path.isdir(sdir) and not os.path.isfile(
                    os.path.join(sdir, COMMIT_MARKER)):
                self._quarantine(step, "uncommitted")
            else:
                try:
                    os.remove(os.path.join(self.root, name))
                except FileNotFoundError:
                    pass
        for name in list(os.listdir(self.root)):
            full = os.path.join(self.root, name)
            if not (name.startswith("step_") and os.path.isdir(full)):
                continue
            if _STEP_RE.match(name):
                # plain step_N: torn only when neither our commit marker
                # nor orbax's own metadata exists (a complete-but-
                # uncommitted dir still has its sidecar and was handled
                # above; a bare complete orbax dir is left alone)
                if (os.path.isfile(os.path.join(full, COMMIT_MARKER))
                        or _ckpt.is_complete_checkpoint(full)
                        or os.path.isfile(self._pending_path(
                            int(name.split("_", 1)[1])))):
                    continue
            elif ".orbax-checkpoint-tmp" not in name:
                continue            # quarantine tags etc. — not ours
            torn.append(name)
            try:
                empty = not os.listdir(full)
            except OSError:
                empty = False
            if empty:
                shutil.rmtree(full, ignore_errors=True)
            else:
                self._quarantine_path(full, f"{name}-torn", "torn")
        if torn:
            warnings.warn(
                f"CheckpointManager({self.root}): swept {len(torn)} torn "
                f"dir(s) left by a killed save: {sorted(torn)} — "
                f"non-empty ones preserved under {QUARANTINE_DIR}/",
                RuntimeWarning, stacklevel=2)

    # -- restore -----------------------------------------------------------

    def restore(self, like_tree: Dict[str, Any], *, step: Optional[int] = None,
                mesh=None, spec_tree=None, watchdog=None, plan=None):
        """Load the newest committed checkpoint (or ``step``) into the
        structure of ``like_tree``. A step failing manifest verification is
        quarantined and the previous committed step is tried — resume after
        corruption degrades, it does not crash. When the step's recorded
        ``_PLAN.json`` differs from the target plan (``plan`` or
        ``self.plan``), the load goes through the reshard path
        (resilience/reshard.py); the saved plan is surfaced as
        ``self.last_restored_plan``. A ReshardError (infeasible target
        mesh) is permanent and raises — an older step cannot fix an
        indivisible axis. Returns ``(step, tree)`` or ``None`` when
        nothing valid exists."""
        self.finalize(watchdog=watchdog)
        mesh = mesh if mesh is not None else self.mesh
        spec_tree = spec_tree if spec_tree is not None else self.spec_tree
        target_plan = plan if plan is not None else self.plan
        candidates = ([int(step)] if step is not None
                      else list(reversed(self.committed_steps())))
        t0 = time.perf_counter()
        with _ledger().span("restore"):
            for s in candidates:
                if not self.verify(s, watchdog=watchdog):
                    self._quarantine(s, "corrupt")
                    continue
                saved_plan = _reshard.read_plan(self.step_dir(s))
                if (target_plan is not None and not _reshard.plans_equivalent(
                        saved_plan, target_plan)):
                    tree = self._with_retries(
                        lambda s=s, sp=saved_plan: _reshard.load_resharded(
                            self.step_dir(s), like_tree, target_plan,
                            mesh=mesh, source_plan=sp),
                        what=f"reshard step_{s}",
                        no_retry=(_reshard.ReshardError,))
                else:
                    tree = self._with_retries(
                        lambda s=s: _ckpt.load_state_dict(
                            self.step_dir(s), like_tree, mesh=mesh,
                            spec_tree=spec_tree),
                        what=f"restore step_{s}")
                self.last_restored_plan = saved_plan
                if _REG.enabled:
                    _REG.counter("pt_checkpoint_restores_total",
                                 "checkpoint restores").inc()
                    _REG.histogram("pt_checkpoint_restore_seconds",
                                   "verify+load duration", "s").observe(
                        time.perf_counter() - t0)
                return s, tree
        return None

    # -- retention ----------------------------------------------------------

    def _gc(self) -> None:
        steps = self.committed_steps()
        keep = set(steps[-self.keep_last_n:])
        if self.keep_every_m:
            keep.update(s for s in steps if s % self.keep_every_m == 0)
        for s in steps:
            if s not in keep:
                shutil.rmtree(self.step_dir(s), ignore_errors=True)

    # -- retry --------------------------------------------------------------

    def _with_retries(self, fn, what: str = "io", no_retry=()):
        """Run ``fn`` retrying transient failures with jittered exponential
        backoff (the ONE schedule implementation:
        distributed.elastic.backoff_delays). ``no_retry`` exception types
        are permanent (e.g. an infeasible reshard target) and re-raise
        immediately."""
        from ..distributed.elastic import backoff_delays
        delays = backoff_delays(self.backoff_base_s, self.backoff_max_s,
                                self.max_retries, rng=self._rng)
        for attempt in range(self.max_retries + 1):
            try:
                return fn()
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if isinstance(e, no_retry) or attempt >= self.max_retries:
                    raise
                time.sleep(next(delays))
