"""paddle_tpu.resilience — the fault-tolerant training runtime.

Glues the previously disconnected islands (checkpoint/, distributed/elastic,
distributed/watchdog) into one loop that survives host preemption, wedged
collectives, and loss blow-ups:

* :class:`CheckpointManager` — periodic + on-demand saves with an atomic
  commit marker, manifest checksums, quarantine, retention, and
  retry-with-backoff (reference analogue: incubate/checkpoint/
  auto_checkpoint.py hardened for preemption);
* :class:`PreemptionGuard` — SIGTERM/SIGINT → final synchronous checkpoint
  at the next step boundary → exit with :data:`RESUMABLE_EXIT_CODE`
  (reference analogue: fleet/elastic/manager.py signal path);
* :class:`AnomalyGuard` — NaN/Inf + EWMA loss-spike detection driving
  skip / rollback-to-checkpoint / abort policies with bounded budgets.

``Trainer.fit(..., checkpoint_manager=..., resume="auto")`` wires all three
into the step loop; ``distributed/elastic.py`` and ``distributed/launch``
recognize the resumable exit status and relaunch into a resume instead of a
restart.

Import note: this package stays light — preemption/anomaly are stdlib-only
and :class:`CheckpointManager` (which pulls jax/orbax) loads lazily. (The
paddle_tpu PARENT package still initializes on any dotted import, so this
buys zero-added-weight within a loaded process — e.g. elastic's lazy
exit-code lookup — not a jax-free launcher.)
"""

from .preemption import (PreemptionGuard, TrainingPreempted,
                         RESUMABLE_EXIT_CODE)
from .anomaly import AnomalyGuard, DivergenceError

__all__ = ["CheckpointManager", "CheckpointCorruption", "PreemptionGuard",
           "TrainingPreempted", "RESUMABLE_EXIT_CODE", "AnomalyGuard",
           "DivergenceError", "ReshardError", "load_resharded", "read_plan",
           "check_feasible", "PLAN_NAME"]

_RESHARD_NAMES = ("ReshardError", "load_resharded", "read_plan",
                  "write_plan", "check_feasible", "plans_equivalent",
                  "effective_axes", "place_tree", "PLAN_NAME")


def __getattr__(name):
    if name in ("CheckpointManager", "CheckpointCorruption"):
        from . import checkpoint_manager as _cm
        return getattr(_cm, name)
    if name in _RESHARD_NAMES:
        from . import reshard as _rs
        return getattr(_rs, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
