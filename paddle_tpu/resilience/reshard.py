"""Checkpoint resharding: load under a DIFFERENT ShardingPlan (ISSUE 15).

Reference analogue: python/paddle/distributed/checkpoint/load_state_dict.py
reshards flat-param shard files when the load-time parallel topology differs
from save-time. Here the storage engine (orbax/tensorstore) already knows how
to serve arbitrary byte ranges, so resharding collapses into two concerns this
module owns:

* **provenance** — the ``ShardingPlan`` active at save time rides inside the
  committed step dir as ``_PLAN.json`` (hashed into the manifest like every
  other file), so a loader on a different mesh never guesses the source
  layout;
* **feasibility + placement** — before touching bytes, every parameter's
  sharded dims are checked against the TARGET plan's axis sizes (a tp-shrink
  that leaves uneven attention-head remainders is rejected with an error
  naming the axis, not a cryptic GSPMD crash three layers down), then the
  tree is restored with the target plan's PartitionSpecs: per-shard lazily
  through orbax (each device reads exactly its new shard's byte ranges —
  peak host memory stays bounded by one shard), falling back to host-side
  assembly + ``jax.device_put`` when the lazy path is unavailable.

The elastic-resume flow (distributed/elastic.py) calls this through
``CheckpointManager.restore`` whenever the saved plan's axes differ from the
live one; ``tools/reshard.py`` exposes the same machinery offline.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..observability.metrics import REGISTRY as _REG

__all__ = ["PLAN_NAME", "ReshardError", "write_plan", "read_plan",
           "effective_axes", "plans_equivalent", "check_feasible",
           "load_resharded", "place_tree"]

PLAN_NAME = "_PLAN.json"
_PLAN_SCHEMA = "pt-ckpt-plan-v1"


class ReshardError(RuntimeError):
    """The target plan cannot legally host this checkpoint (permanent:
    retrying or falling back to an older step cannot fix an indivisible
    axis — the caller must pick a different mesh)."""


# -- plan sidecar -------------------------------------------------------------

def write_plan(step_dir: str, plan, step: int) -> str:
    """Record the active plan (or the implicit single-device plan, as
    ``null``) inside the step dir. Called by CheckpointManager before the
    manifest is built, so the file is hashed like every other payload."""
    payload = {
        "schema": _PLAN_SCHEMA,
        "step": int(step),
        "implicit_single_device": plan is None,
        "plan": plan.as_dict() if plan is not None else None,
    }
    path = os.path.join(step_dir, PLAN_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(json.dumps(payload, sort_keys=True).encode())
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_plan(step_dir: str):
    """The ShardingPlan recorded at save time, or None (implicit
    single-device plan, a pre-plan checkpoint, or no sidecar at all)."""
    path = os.path.join(step_dir, PLAN_NAME)
    if not os.path.isfile(path):
        return None
    with open(path, "rb") as f:
        payload = json.loads(f.read())
    raw = payload.get("plan")
    if raw is None:
        return None
    from ..distributed.auto_parallel.emit import ShardingPlan
    return ShardingPlan.from_dict(raw)


def effective_axes(plan) -> Dict[str, int]:
    """Mesh axes that actually partition anything (size > 1). Two plans
    with the same effective axes hold identical shard layouts even if one
    carries extra size-1 axes."""
    if plan is None:
        return {}
    return {k: int(v) for k, v in plan.axes.items() if int(v) > 1}


def plans_equivalent(a, b) -> bool:
    """True when a checkpoint written under ``a`` loads under ``b`` without
    resharding (same effective axis sizes)."""
    return effective_axes(a) == effective_axes(b)


# -- feasibility --------------------------------------------------------------

def _iter_spec_leaves(tree: Dict[str, Any], param_specs: Dict[str, Any]
                      ) -> Iterator[Tuple[str, Any, Tuple[int, ...]]]:
    """Yield (matched name, spec, shape) for every leaf the plan's spec
    table covers — matching full "/"-path, final key, then any path
    component (innermost wins), the same resolution order the restore
    target uses, so feasibility is checked for exactly the leaves that
    will be resharded (params AND their optimizer slots)."""
    import numpy as np
    from jax.tree_util import tree_flatten_with_path
    leaves, _ = tree_flatten_with_path(tree)
    for path, x in leaves:
        keys = [str(getattr(p, "key", p)) for p in path]
        full = "/".join(keys)
        name, spec = None, None
        if full in param_specs:
            name, spec = full, param_specs[full]
        elif keys and keys[-1] in param_specs:
            name, spec = keys[-1], param_specs[keys[-1]]
        else:
            for k in reversed(keys[:-1]):
                if k in param_specs:
                    name, spec = k, param_specs[k]
                    break
        if spec is None:
            continue
        shape = tuple(x.shape) if hasattr(x, "shape") else tuple(
            np.shape(x))
        yield name, spec, shape


def _axis_factor(entry, axes: Dict[str, int]) -> Tuple[int, List[str]]:
    names = list(entry) if isinstance(entry, (tuple, list)) else [entry]
    factor, used = 1, []
    for a in names:
        if a is None:
            continue
        factor *= int(axes.get(a, 1))
        used.append(str(a))
    return factor, used


def check_feasible(like_tree: Dict[str, Any], plan) -> None:
    """Raise ReshardError if any parameter dim the target plan shards is
    not divisible by the product of the mesh axes on that dim."""
    if plan is None:
        return
    axes = {k: int(v) for k, v in plan.axes.items()}
    for name, spec, shape in _iter_spec_leaves(like_tree, plan.param_specs):
        entries = tuple(spec)
        if len(entries) > len(shape):
            continue                      # restore replicates these anyway
        for d, entry in enumerate(entries):
            if entry is None:
                continue
            factor, used = _axis_factor(entry, axes)
            if factor > 1 and shape[d] % factor != 0:
                ax = "+".join(used)
                raise ReshardError(
                    f"target plan {plan.config_str!r} cannot shard "
                    f"'{name}': dim {d} of shape {tuple(shape)} has size "
                    f"{shape[d]}, not divisible by axis {ax}={factor} "
                    f"(remainder {shape[d] % factor}) — e.g. a tp shrink "
                    f"that does not divide the attention heads leaves "
                    f"uneven head remainders; pick an axis size that "
                    f"divides {shape[d]}")


# -- load ---------------------------------------------------------------------

def place_tree(tree: Dict[str, Any], plan, mesh) -> Dict[str, Any]:
    """Host-side assembly path: place an already-loaded (host or
    replicated) tree onto ``mesh`` per the plan's spec table via
    ``jax.device_put`` — unmatched leaves replicate."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from jax.tree_util import tree_map_with_path
    m = getattr(mesh, "mesh", mesh)
    specs = plan.param_specs if plan is not None else {}

    def one(path, x):
        keys = [str(getattr(p, "key", p)) for p in path]
        full = "/".join(keys)
        spec = specs.get(full)
        if spec is None and keys:
            spec = specs.get(keys[-1])
        if spec is None:
            for k in reversed(keys[:-1]):
                if k in specs:
                    spec = specs[k]
                    break
        if spec is None:
            spec = PartitionSpec()
        shape = tuple(getattr(x, "shape", ()) or ())
        if len(tuple(spec)) > len(shape):
            spec = PartitionSpec()
        return jax.device_put(x, NamedSharding(m, spec))

    return tree_map_with_path(one, tree)


def load_resharded(step_dir: str, like_tree: Dict[str, Any], target_plan,
                   *, mesh=None, devices=None,
                   source_plan=None) -> Dict[str, Any]:
    """Load the checkpoint at ``step_dir`` (written under ``source_plan``,
    read from its ``_PLAN.json`` when not given) placed per
    ``target_plan`` on ``mesh``. Feasibility is validated up front; the
    restore itself goes per-shard through orbax (bounded peak memory),
    with host-side assembly + device_put as the fallback path."""
    from .. import checkpoint as _ckpt
    t0 = time.perf_counter()
    if source_plan is None:
        source_plan = read_plan(step_dir)
    hm = mesh
    if hm is None:
        hm = target_plan.build_mesh(devices)
    m = getattr(hm, "mesh", hm)
    try:
        check_feasible(like_tree, target_plan)
        spec_tree = dict(target_plan.param_specs)
        try:
            tree = _ckpt.load_state_dict(step_dir, like_tree, mesh=m,
                                         spec_tree=spec_tree)
        except ReshardError:
            raise
        except Exception:
            # lazy per-shard path failed (e.g. incompatible on-disk
            # layout metadata): assemble host-side, then re-place
            raw = _ckpt.load_state_dict(step_dir, like_tree)
            tree = place_tree(raw, target_plan, m)
    except Exception as e:
        if _REG.enabled:
            _REG.counter("pt_elastic_reshard_failures_total",
                         "resharded restores that failed").inc(
                error=type(e).__name__)
        raise
    if _REG.enabled:
        src = source_plan.config_str if source_plan is not None else "none"
        _REG.counter("pt_elastic_reshards_total",
                     "cross-plan checkpoint restores").inc(
            source=src, target=target_plan.config_str)
        _REG.histogram("pt_elastic_reshard_seconds",
                       "resharded restore duration", "s").observe(
            time.perf_counter() - t0)
    return tree
