"""AnomalyGuard — NaN/Inf and loss-spike detection with bounded recovery.

A multi-day pretraining run hits two loss pathologies: *poison batches*
(one bad document → NaN loss → NaN grads → every parameter NaN within one
step) and *divergence* (loss blows up over tens of steps). The guard
watches the per-step loss against an EWMA band and maps each anomaly to a
policy:

* ``"skip"``     — undo this step's update and move past the batch
                   (transient poison batch);
* ``"rollback"`` — restore the last good checkpoint and replay
                   (state already corrupted, or skip unavailable);
* ``"abort"``    — raise :class:`DivergenceError` immediately.

Both recovery policies carry a **bounded budget** (``max_skips`` /
``max_rollbacks``): a persistent divergence exhausts it and the run fails
loudly instead of silently replaying the same collapse forever.

Detection is host-side and adds no device computation. How often it forces
a device→host sync of the loss is policy-dependent:

* ``policy="skip"`` fences EVERY step — undoing a poisoned update needs the
  pre-step references held from before the NEXT step runs, so the verdict
  must land before the next dispatch. That per-step fence is the price of
  checkpoint-free recovery, and the Trainer keeps it regardless of
  ``check_every``.
* ``policy="rollback"``/``"abort"`` can consume a batched **loss window**:
  set ``check_every=W`` and the Trainer stacks W device losses and performs
  ONE sync per window (and per superstep drain), keeping async dispatch
  overlap. Detection latency grows to ≤W steps, which rollback absorbs by
  construction — it restores the last good checkpoint either way.

Spike test: after ``warmup_steps`` accepted losses,
``loss > ewma + spike_factor * ewma_dev`` (EWMA of absolute deviation — a
cheap robust scale estimate) flags an anomaly; NaN/Inf flags
unconditionally, warmup included.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional

from ..observability import flight_recorder as _flight
from ..observability.metrics import REGISTRY as _REG

__all__ = ["AnomalyGuard", "DivergenceError",
           "OK", "SKIP", "ROLLBACK", "ABORT"]

OK = "ok"
SKIP = "skip"
ROLLBACK = "rollback"
ABORT = "abort"

_POLICIES = (SKIP, ROLLBACK, ABORT)


class DivergenceError(RuntimeError):
    """Loss anomaly with no recovery budget left (or policy='abort')."""


class AnomalyGuard:
    def __init__(self, policy: str = ROLLBACK, *, spike_factor: float = 6.0,
                 ewma_alpha: float = 0.05, warmup_steps: int = 20,
                 max_skips: int = 10, max_rollbacks: int = 3,
                 min_rel_dev: float = 1e-3, check_every: int = 1):
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}")
        self.policy = policy
        # loss-window size for batched verdicts (ignored — per-step — when
        # policy="skip"; see module docstring)
        self.check_every = max(1, int(check_every))
        self.min_rel_dev = float(min_rel_dev)
        self.spike_factor = float(spike_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.warmup_steps = int(warmup_steps)
        self.max_skips = int(max_skips)
        self.max_rollbacks = int(max_rollbacks)
        self.skips = 0
        self.rollbacks = 0
        self.anomalies = 0
        self.last_reason: Optional[str] = None
        self._ewma: Optional[float] = None
        self._dev = 0.0
        self._seen = 0
        # the final loss window a flight-recorder dump ships for the
        # post-mortem: every CHECKED loss, anomalous or not, in order
        self.recent_losses = deque(maxlen=64)
        # counter handle resolved once (check() can run per STEP; the
        # registry name-lookup must not ride the training loop)
        self._verdict_counter = _REG.counter(
            "pt_anomaly_verdicts_total", "AnomalyGuard verdicts by outcome")

    # -- detection ----------------------------------------------------------

    def is_anomalous(self, loss: float) -> Optional[str]:
        """Reason string when ``loss`` is anomalous, else None (no state
        change)."""
        if not math.isfinite(loss):
            return "non-finite loss"
        if self._ewma is not None and self._seen >= self.warmup_steps:
            # relative floor on the deviation: after a long flat plateau
            # _dev decays toward 0 and an ABSOLUTE floor would flag benign
            # fp jitter as a spike, draining the recovery budget
            floor = max(self.min_rel_dev * abs(self._ewma), 1e-12)
            band = self.spike_factor * max(self._dev, floor)
            if loss > self._ewma + band:
                return (f"loss spike {loss:.4g} > ewma {self._ewma:.4g} "
                        f"+ {self.spike_factor}*dev {self._dev:.4g}")
        return None

    def record(self, loss: float) -> None:
        """Fold an ACCEPTED loss into the EWMA band."""
        a = self.ewma_alpha
        if self._ewma is None:
            self._ewma = float(loss)
        else:
            self._dev = (1 - a) * self._dev + a * abs(loss - self._ewma)
            self._ewma = (1 - a) * self._ewma + a * float(loss)
        self._seen += 1

    # -- decision -----------------------------------------------------------

    def check(self, loss: float) -> str:
        """One per-step verdict: OK (loss recorded), or SKIP / ROLLBACK /
        ABORT per policy and remaining budget."""
        self.recent_losses.append(float(loss))
        reason = self.is_anomalous(float(loss))
        if reason is None:
            self.record(float(loss))
            self.last_reason = None
            return self._verdict(OK)
        self.anomalies += 1
        self.last_reason = reason
        if self.policy == ABORT:
            return self._verdict(ABORT)
        if self.policy == SKIP:
            self.skips += 1
            return self._verdict(
                SKIP if self.skips <= self.max_skips else ABORT)
        self.rollbacks += 1
        return self._verdict(
            ROLLBACK if self.rollbacks <= self.max_rollbacks else ABORT)

    def _verdict(self, verdict: str) -> str:
        if _REG.enabled:
            self._verdict_counter.inc(verdict=verdict)
        return verdict

    def raise_divergence(self, step: int, loss: float) -> None:
        # ship the post-mortem before dying: the flight dump carries the
        # final loss window + the last trainer/serving spans (no-op when
        # the recorder is not active)
        _flight.maybe_dump("anomaly_abort", extra={
            "step": int(step), "loss": float(loss),
            "reason": self.last_reason,
            "loss_window": list(self.recent_losses),
            "skips": self.skips, "rollbacks": self.rollbacks,
        })
        raise DivergenceError(
            f"loss anomaly at step {step} ({self.last_reason or loss}) with "
            f"recovery budget exhausted (skips={self.skips}/{self.max_skips},"
            f" rollbacks={self.rollbacks}/{self.max_rollbacks})")

    @property
    def ewma(self) -> Optional[float]:
        return self._ewma
