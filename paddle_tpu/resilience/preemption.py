"""PreemptionGuard — turn SIGTERM into an orderly checkpoint-and-exit.

TPU maintenance events arrive as SIGTERM with a short grace window
(the reference's elastic manager sees the same shape: etcd watcher +
process kill). The guard latches the signal; the training loop polls
``preempted`` at each step boundary, writes one final synchronous
checkpoint, and raises :class:`TrainingPreempted` — a ``SystemExit``
carrying :data:`RESUMABLE_EXIT_CODE` so the process exit status tells the
relauncher (distributed/launch, distributed/elastic) "resume me" rather
than "I failed".

This module is deliberately stdlib-only: within an already-imported
paddle_tpu process (elastic's lazy lookup of the exit-code contract) it
adds no import weight of its own.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Iterable, Optional

__all__ = ["PreemptionGuard", "TrainingPreempted", "RESUMABLE_EXIT_CODE"]

# os.EX_TEMPFAIL: "temporary failure, retry" — distinct from 0 (done),
# generic 1 (bug), and 124 (watchdog hard-exit on a hung step)
RESUMABLE_EXIT_CODE = 75


class TrainingPreempted(SystemExit):
    """Raised at a step boundary after the final checkpoint is durable.

    Subclasses SystemExit with RESUMABLE_EXIT_CODE: unhandled, the process
    exits with the resumable status; in-process relaunchers
    (ElasticManager.run) catch it and resume without burning the restart
    budget."""

    def __init__(self, step: Optional[int] = None):
        super().__init__(RESUMABLE_EXIT_CODE)
        self.step = step

    def __str__(self):
        return (f"training preempted at step {self.step}; state checkpointed,"
                f" exit {RESUMABLE_EXIT_CODE} (resumable)")


class PreemptionGuard:
    """Latching signal handler usable as a context manager::

        with PreemptionGuard() as guard:
            trainer.fit(..., preemption_guard=guard)

    A second SIGINT bypasses the orderly path (user really wants out, now).
    Installing from a non-main thread is a no-op (signal API limitation);
    :meth:`trigger` still works, so tests and external pollers can latch it
    manually.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,
                                                 signal.SIGINT)):
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self._prev = {}
        self._counts = {}
        self.installed = False

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> "PreemptionGuard":
        if self.installed:
            return self
        try:
            for sig in self.signals:
                self._prev[sig] = signal.signal(sig, self._handler)
            self.installed = True
        except ValueError:       # not the main thread
            self._prev.clear()
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self.installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    # -- state --------------------------------------------------------------

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    def trigger(self) -> None:
        """Latch without a signal (tests; external maintenance-event
        pollers that learn of preemption out-of-band)."""
        self._flag.set()

    def clear(self) -> None:
        """Reset the latch for a new run attempt. A guard REUSED across
        in-process relaunches (one guard outside ElasticManager.run) must
        be cleared per attempt, or the resumed fit re-preempts at its
        first step boundary; per-attempt guards don't need this."""
        self._flag.clear()

    def _handler(self, signum, frame):
        n = self._counts.get(signum, 0) + 1
        self._counts[signum] = n
        self._flag.set()
        if signum == signal.SIGINT and n >= 2:
            raise KeyboardInterrupt   # second ^C: skip the orderly path


def exit_resumable() -> None:
    """Hard process exit with the resumable status (for code paths that
    cannot raise through, e.g. daemon threads)."""
    os._exit(RESUMABLE_EXIT_CODE)
