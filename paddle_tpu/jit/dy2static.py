"""Minimal dy2static: AST conversion of Python control flow to lax ops.

Reference analogue: python/paddle/jit/dy2static/ — the AST transformer
stack (transformers/ifelse_transformer.py, loop_transformer.py) that
rewrites ``if``/``while``/``for`` over tensors into ``cond``/``while_loop``
ops, with convert-call runtime dispatch (convert_operators.py
convert_ifelse/convert_while_loop). The SOT bytecode path is out of scope
(documented in docs/DESIGN_DECISIONS.md); this is the AST fallback the
reference uses when SOT is disabled.

TPU design: the rewrite targets jax.lax.cond / lax.while_loop — traced
once, compiled control flow, no Python in the hot path. Dispatch is at
RUNTIME: a concrete (non-traced) condition runs plain Python, a traced
condition lowers to the lax op — the same dual behavior as the reference's
convert_ifelse checking for Variable.

Supported rewrites (everything else raises Dy2StaticError with the source
line — the "clear graph-break error" contract):
- ``if``/``elif``/``else`` — branch-assigned variables become the cond
  outputs; both branches must produce matching shapes/dtypes.
- ``while`` — loop-carried variables = names assigned in the body that
  are already defined before the loop.
- ``for i in range(...)`` — desugared to the while form.

- ``break``/``continue`` inside converted loops and ``return`` anywhere
  inside converted constructs — lowered to boolean guard flags carried
  through the loop/branch state, the reference's approach
  (transformers/break_continue_transformer.py, return_transformer.py):
  the jump statement becomes ``flag = True``, downstream statements are
  wrapped in ``if no_jump(flags): ...``, loop tests gain ``and not flag``
  (lazily — the original test is not evaluated once a flag is set on the
  Python path), and a range-``for``'s increment is break-guarded so the
  loop variable keeps Python's post-break value.

Not supported inside a converted construct (graph breaks):
attribute/subscript assignment, ``for`` over arbitrary iterables with a
traced condition. Python-level loops over concrete values still work
untransformed (they trace-unroll as before).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

_RUNTIME_NAME = "__pt_jst__"


class Dy2StaticError(Exception):
    """Unconvertible Python construct under to_static(full_graph=False)."""


# ---------------------------------------------------------------------------
# runtime dispatch (reference: dy2static/convert_operators.py)
# ---------------------------------------------------------------------------

def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def run_ifelse(pred, true_fn, false_fn, args: tuple):
    """convert_ifelse: Python if on concrete pred, lax.cond on traced."""
    if not _is_traced(pred):
        # concrete predicate: plain Python — user errors propagate raw
        return true_fn(*args) if pred else false_fn(*args)
    try:
        pred = jnp.asarray(pred)
        if pred.size == 1 and pred.shape != ():
            # reference semantics: a numel-1 tensor IS a valid condition
            # (their cond/bool conversion accepts [1]-shaped tensors)
            pred = pred.reshape(())
        if pred.shape != ():
            raise Dy2StaticError(
                "if-condition is a traced tensor with shape "
                f"{pred.shape}; reduce it to a scalar (e.g. .any()/.all()) "
                "for lax.cond")
        # UNDEF placeholders are not arrays — route them around the cond
        # as static closure; a branch that assigns them returns real values
        idx = [i for i, a in enumerate(args) if a is not UNDEF]
        ops = tuple(args[i] for i in idx)

        def wrap(branch):
            def h(ops_in):
                full = list(args)
                for j, i in enumerate(idx):
                    full[i] = ops_in[j]
                return branch(*full)
            return h

        return jax.lax.cond(pred, wrap(true_fn), wrap(false_fn), ops)
    except TypeError as e:
        raise Dy2StaticError(
            "if/else branches returned mismatched structures or dtypes "
            f"under tracing (lax.cond requires identical outputs): {e}"
        ) from e
    except NameError as e:
        raise Dy2StaticError(
            f"variable assigned in only one if/else branch and undefined "
            f"before it ({e}); define it before the if") from e


def run_while(test_fn, body_fn, carry: tuple):
    """convert_while_loop: Python while on concrete tests, lax.while_loop
    as soon as the test turns traced — including MID-LOOP (a break guard
    flag set under a traced condition makes iteration N's test traced
    even though iterations 0..N-1 ran concrete; the already-unrolled
    prefix stays Python, the remainder lowers from the current carry)."""
    while True:
        t = test_fn(*carry)
        if _is_traced(t):
            break
        if not t:
            return carry
        carry = body_fn(*carry)
    if any(c is UNDEF for c in carry):
        raise Dy2StaticError(
            "a loop-body temporary is undefined before a while/for loop "
            "with a TRACED condition (lax.while_loop needs concrete "
            "initial values for every carried variable) — initialize it "
            "before the loop")
    try:
        def cond(c):
            t = jnp.asarray(test_fn(*c))
            # numel-1 conditions are scalars in reference semantics
            return t.reshape(()) if t.size == 1 else t
        return jax.lax.while_loop(cond, lambda c: body_fn(*c), carry)
    except TypeError as e:
        raise Dy2StaticError(
            "while-loop carried variables changed structure/shape/dtype "
            f"across an iteration (lax.while_loop invariant): {e}") from e


class _Undef:
    """Placeholder for a name not yet bound before a converted if/while.
    Any USE of it raises a clear error; merely passing it through a branch
    that doesn't touch it is fine (Python-path semantics)."""

    def _die(self, *a, **k):
        raise Dy2StaticError(
            "use of a variable that was only assigned in the untaken "
            "branch of a converted if/else — define it before the if")

    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _die
    __truediv__ = __rtruediv__ = __matmul__ = __call__ = __getattr__ = _die
    __getitem__ = __iter__ = __bool__ = __float__ = __int__ = _die

    def __repr__(self):
        return "<undefined>"


UNDEF = _Undef()


def no_jump(*flags):
    """True while NO jump flag (break/continue/return guard) is set.
    Concrete flags stay Python bools; any traced flag lifts the whole
    expression to jnp logical ops (if/else over the result then routes
    through run_ifelse/lax.cond)."""
    if any(_is_traced(f) for f in flags):
        r = jnp.logical_not(jnp.asarray(flags[0]))
        for f in flags[1:]:
            r = jnp.logical_and(r, jnp.logical_not(f))
        return r
    return not any(bool(f) for f in flags)


def loop_test(test_thunk, *flags):
    """Loop condition ``(not any(flags)) and test`` with Python's lazy
    semantics on the concrete path (once a break/return flag is set the
    original test is NOT evaluated — it may no longer be well-defined)
    and jnp logical ops on the traced path."""
    if not any(_is_traced(f) for f in flags):
        if any(bool(f) for f in flags):
            return False
        return test_thunk()
    r = jnp.asarray(test_thunk())
    for f in flags:
        r = jnp.logical_and(r, jnp.logical_not(jnp.asarray(f)))
    return r


_RUNTIME = {"run_ifelse": staticmethod(run_ifelse),
            "run_while": staticmethod(run_while),
            "no_jump": staticmethod(no_jump),
            "loop_test": staticmethod(loop_test), "UNDEF": UNDEF}


# ---------------------------------------------------------------------------
# scope analysis
# ---------------------------------------------------------------------------

def _assigned_names(nodes: Sequence[ast.stmt]) -> List[str]:
    out: List[str] = []

    class V(ast.NodeVisitor):
        def visit_Name(self, n):
            if isinstance(n.ctx, ast.Store) and n.id not in out \
                    and not n.id.startswith("__pt_"):
                out.append(n.id)

        def visit_FunctionDef(self, n):  # don't descend into nested defs
            # generated __pt_* helpers are not data and never carried
            if n.name not in out and not n.name.startswith("__pt_"):
                out.append(n.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, n):
            pass

    for s in nodes:
        V().visit(s)
    return out


def _walk_same_scope(node):
    """ast.walk that does NOT descend into nested function defs/lambdas —
    a return inside a nested def (including our generated __pt_* helpers)
    belongs to that def, not to the construct being converted."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child,
                      (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            yield child          # the def node itself, not its body
            continue
        yield from _walk_same_scope(child)


def _forbid(nodes: Sequence[ast.stmt], where: str):
    # The _JumpRewriter pass lowers break/continue/return to guard flags
    # BEFORE this transformer runs, so reaching one here means the
    # rewriter could not handle its position (e.g. inside a try block
    # within a converted loop) — still a clear graph-break error, but a
    # narrower one than the pre-round-5 blanket rejection. Break/continue
    # are only jumps for THIS construct when not inside a nested loop
    # (where they bind to that loop and work natively).
    for s in nodes:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue             # nested defs keep their own returns
        for n in _walk_same_scope(s):
            if isinstance(n, ast.Return):
                raise Dy2StaticError(
                    f"graph break at line {getattr(n, 'lineno', '?')}: "
                    f"'return' in this position inside a converted "
                    f"{where} is not convertible (supported positions "
                    f"are lowered automatically) — restructure to assign "
                    f"a variable and return after the block")
    for s in nodes:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in _walk_loop_scope(s):
            if isinstance(n, (ast.Break, ast.Continue)):
                kind = type(n).__name__.lower()
                raise Dy2StaticError(
                    f"graph break at line {getattr(n, 'lineno', '?')}: "
                    f"'{kind}' in this position inside a converted "
                    f"{where} is not convertible (supported positions "
                    f"are lowered automatically) — use a loop condition")
    for s in nodes:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in _walk_same_scope(s):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, (ast.Attribute, ast.Subscript)) \
                                and isinstance(leaf.ctx, ast.Store):
                            raise Dy2StaticError(
                                f"graph break at line "
                                f"{getattr(n, 'lineno', '?')}: assignment "
                                f"to an attribute/subscript inside a "
                                f"converted {where} is not supported — "
                                f"use functional updates (x = x.at[i].set(v))")


def _names(ids: Sequence[str], ctx) -> List[ast.Name]:
    return [ast.Name(id=i, ctx=ctx) for i in ids]


def _tuple_of(ids: Sequence[str], ctx) -> ast.expr:
    return ast.Tuple(elts=_names(ids, ctx), ctx=ctx)


# ---------------------------------------------------------------------------
# jump lowering: break / continue / return -> guard flags
# (reference: transformers/break_continue_transformer.py + return_transformer)
# ---------------------------------------------------------------------------

def _rt_attr(name):
    return ast.Attribute(value=ast.Name(id=_RUNTIME_NAME, ctx=ast.Load()),
                         attr=name, ctx=ast.Load())


def _assign(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=value)


def _const_assign(name, value):
    return _assign(name, ast.Constant(value=value))


def _contains_jump(nodes, kinds) -> bool:
    """Any of ``kinds`` in these statements' own scope — NOT inside nested
    loops (break/continue bind to the nearest loop) or nested defs."""
    for s in nodes:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in ast.walk(s) if kinds == (ast.Return,) else _walk_loop_scope(s):
            if isinstance(n, kinds):
                return True
    return False


def _walk_loop_scope(node):
    """Walk without descending into nested loops or function defs."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.While, ast.For)):
            continue
        yield from _walk_loop_scope(child)


def _contains_return_same_fn(nodes) -> bool:
    for s in nodes:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in _walk_same_scope(s):
            if isinstance(n, ast.Return):
                return True
    return False


class _JumpRewriter:
    """Lowers break/continue/return to boolean guard flags BEFORE control
    -flow conversion, exactly the reference's scheme: the jump becomes
    ``flag = True`` (dead trailing statements dropped), statements after a
    may-jump construct are wrapped in ``if no_jump(flags): ...`` (which
    the later pass turns into lax.cond under tracing), loop tests become
    ``loop_test(lambda: orig_test, flags...)``, and a range-for's
    increment is break-guarded so the loop variable keeps Python's
    post-break value. Flags use the ``__jst_`` prefix: they must be
    REAL carried data (``__pt_`` names are invisible to the carry/out
    analysis by design)."""

    def __init__(self):
        self._n = 0

    def _fresh(self, kind):
        self._n += 1
        return f"__jst_{kind}_{self._n}"

    def rewrite(self, fdef):
        ret = None
        if _contains_return_same_fn([s for s in fdef.body
                                     if isinstance(s, (ast.If, ast.While,
                                                       ast.For, ast.Try,
                                                       ast.With))]):
            # returns live inside convertible constructs: lower ALL of
            # this function's returns to a (flag, value) pair
            ret = (self._fresh("ret"), self._fresh("retval"))
        body, _ = self._block(fdef.body, None, None, ret)
        if ret is not None:
            body = ([_const_assign(ret[0], False),
                     _const_assign(ret[1], None)] + body
                    + [ast.Return(value=ast.Name(id=ret[1], ctx=ast.Load()))])
        fdef.body = body
        return fdef

    # -- block transform ---------------------------------------------------
    # jump status of a statement sequence (what control does at its end):
    _NO, _MAY, _ALWAYS = 0, 1, 2

    @classmethod
    def _seq(cls, a, b):
        """Status of "a then b" (b runs only on a's non-jumped paths)."""
        if a == cls._ALWAYS:
            return a
        if b == cls._ALWAYS:
            # non-jumped paths all jump in b; jumped paths already did
            return cls._ALWAYS
        return max(a, b)

    def _no_jump_if(self, flags, body):
        return ast.If(
            test=ast.Call(func=_rt_attr("no_jump"),
                          args=_names(flags, ast.Load()), keywords=[]),
            body=body, orelse=[])

    def _block(self, stmts, brk, cont, ret):
        """Returns (new_stmts, status in {_NO, _MAY, _ALWAYS}).
        ``brk``/``cont`` are the nearest enclosing converted loop's flag
        names (or None), ``ret`` the function's (flag, value) pair.

        A branch that ALWAYS jumps lets the rest of the block chain into
        the sibling branch (so under tracing both lax.cond branches
        assign the same variables — no None-vs-array mismatch for early
        returns). A branch that only MAY jump keeps the rest under a
        runtime ``if no_jump(flags):`` guard instead — chaining there
        would wrongly skip the rest on the not-jumped path (round-5
        review: confirmed silent-wrong-result), and duplicating the rest
        into both branches would blow up nested code."""
        out = []
        flags = [f for f in (brk, cont, ret and ret[0]) if f]
        for idx, s in enumerate(stmts):
            if isinstance(s, ast.Break) and brk is not None:
                out.append(ast.copy_location(_const_assign(brk, True), s))
                return out, self._ALWAYS     # rest of the block is dead
            if isinstance(s, ast.Continue) and cont is not None:
                out.append(ast.copy_location(_const_assign(cont, True), s))
                return out, self._ALWAYS
            if isinstance(s, ast.Return) and ret is not None:
                val = s.value if s.value is not None \
                    else ast.Constant(value=None)
                out.append(ast.copy_location(_const_assign(ret[0], True), s))
                out.append(ast.copy_location(_assign(ret[1], val), s))
                return out, self._ALWAYS
            if isinstance(s, ast.If):
                tb, ts = self._block(s.body, brk, cont, ret)
                fb, fs = self._block(s.orelse, brk, cont, ret)
                if ts == fs == self._ALWAYS:
                    out.append(ast.copy_location(
                        ast.If(test=s.test, body=tb or [ast.Pass()],
                               orelse=fb), s))
                    return out, self._ALWAYS    # rest dead on every path
                if self._ALWAYS in (ts, fs):
                    rest, rs = self._block(list(stmts[idx + 1:]),
                                           brk, cont, ret)
                    other = fs if ts == self._ALWAYS else ts
                    if rest:
                        attach = (rest if other == self._NO
                                  else [self._no_jump_if(flags, rest)])
                        if ts == self._ALWAYS:
                            fb = fb + attach
                        else:
                            tb = tb + attach
                    out.append(ast.copy_location(
                        ast.If(test=s.test, body=tb or [ast.Pass()],
                               orelse=fb), s))
                    path = self._seq(other, rs)
                    return out, (self._ALWAYS if path == self._ALWAYS
                                 else self._MAY)
                out.append(ast.copy_location(
                    ast.If(test=s.test, body=tb or [ast.Pass()],
                           orelse=fb), s))
                if self._MAY in (ts, fs):
                    rest, rs = self._block(list(stmts[idx + 1:]),
                                           brk, cont, ret)
                    if rest:
                        out.append(self._no_jump_if(flags, rest))
                    return out, (self._ALWAYS if rs == self._ALWAYS
                                 else self._MAY)
                continue
            if isinstance(s, (ast.While, ast.For)):
                new, may_ret = self._loop(s, ret)
                out.extend(new)
                if may_ret:
                    # only the RETURN flag escapes a loop; guard the rest
                    rest, rs = self._block(list(stmts[idx + 1:]),
                                           brk, cont, ret)
                    if rest:
                        out.append(self._no_jump_if([ret[0]], rest))
                    return out, (self._ALWAYS if rs == self._ALWAYS
                                 else self._MAY)
                continue
            out.append(s)
        return out, self._NO

    # -- loops -------------------------------------------------------------
    def _loop(self, node, ret):
        """Lower one While/For's breaks+continues (and thread the return
        flag through). Returns (stmts, may_return)."""
        if isinstance(node, ast.For):
            is_range = (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and not node.orelse
                        and isinstance(node.target, ast.Name))
            if not is_range:
                # non-range for stays a Python loop: break/continue work
                # natively; a lowered-return function still needs returns
                # INSIDE it lowered (the final `return retval` must see
                # the flag) — but a native `return` also exits correctly,
                # so leave its body alone apart from nested loops
                body, _ = self._block(node.body, None, None, None)
                node.body = body
                return [node], False
        has_brk = _contains_jump(node.body, (ast.Break,))
        has_cont = _contains_jump(node.body, (ast.Continue,))
        has_ret = ret is not None and _contains_jump(node.body, (ast.Return,))
        brk = self._fresh("brk") if has_brk else None
        cont = self._fresh("cont") if has_cont else None
        body, _ = self._block(node.body, brk, cont, ret if has_ret else None)
        if cont:
            body = [_const_assign(cont, False)] + body   # reset each iter

        exit_flags = [f for f in (brk, ret[0] if has_ret else None) if f]
        init = [_const_assign(f, False) for f in (brk, cont) if f]

        if isinstance(node, ast.For):
            # desugar range-for here so the increment can be break-guarded
            # (Python leaves the loop var at its break-time value)
            a = node.iter.args

            def _const_int(n):
                # a negative literal parses as UnaryOp(USub, Constant)
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    return n.value
                if (isinstance(n, ast.UnaryOp)
                        and isinstance(n.op, ast.USub)
                        and isinstance(n.operand, ast.Constant)
                        and isinstance(n.operand.value, int)):
                    return -n.operand.value
                return None

            step = ast.Constant(value=1)
            step_val = 1
            if len(a) == 1:
                start, stop = ast.Constant(value=0), a[0]
            elif len(a) == 2:
                start, stop = a
            elif len(a) == 3 and _const_int(a[2]) not in (None, 0):
                # constant non-zero step: supported (reference loop
                # transformer handles arbitrary range forms; traced/zero
                # steps stay a clear graph break)
                start, stop, step = a
                step_val = _const_int(a[2])
            else:
                raise Dy2StaticError(
                    f"graph break at line {node.lineno}: range() with a "
                    "non-constant step is not supported under "
                    "to_static(full_graph=False); use a while loop")
            ivar = node.target.id
            incr = _assign(ivar, ast.BinOp(
                left=ast.Name(id=ivar, ctx=ast.Load()), op=ast.Add(),
                right=step))
            if exit_flags:
                incr = ast.If(
                    test=ast.Call(func=_rt_attr("no_jump"),
                                  args=_names(exit_flags, ast.Load()),
                                  keywords=[]),
                    body=[incr], orelse=[])
            test = ast.Compare(
                left=ast.Name(id=ivar, ctx=ast.Load()),
                ops=[ast.Lt() if step_val > 0 else ast.Gt()],
                comparators=[stop])
            init.append(_assign(ivar, start))
            body = body + [incr]
        else:
            test = node.test
            if node.orelse:
                raise Dy2StaticError(
                    f"graph break at line {node.lineno}: while/else is "
                    "not supported under to_static(full_graph=False)")

        if exit_flags:
            # loop_test(lambda: test, *flags): lazily skips the original
            # test once a flag is set (it may no longer be well-defined)
            test = ast.Call(
                func=_rt_attr("loop_test"),
                args=[ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=test)] + _names(exit_flags, ast.Load()),
                keywords=[])

        wh = ast.copy_location(ast.While(test=test, body=body, orelse=[]),
                               node)
        return init + [wh], has_ret


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites if/while/for into runtime-dispatch calls. Fresh helper
    names are namespaced per construct to avoid collisions."""

    def __init__(self):
        self._n = 0

    def _fresh(self, kind):
        self._n += 1
        return f"__pt_{kind}_{self._n}"

    # -- if/else ----------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        _forbid(node.body, "if")
        _forbid(node.orelse, "if")
        outs = sorted(set(_assigned_names(node.body))
                      | set(_assigned_names(node.orelse)))
        if not outs:
            # pure side-effect-free branch (e.g. raise): leave as-is; a
            # traced pred will fail loudly inside jax anyway
            return node
        tname, fname = self._fresh("true"), self._fresh("false")

        # branch-assigned names become helper PARAMETERS (shadowing the
        # enclosing scope) so `x = x + 1` patterns read the passed-in value
        # instead of tripping UnboundLocalError; purely-read names still
        # close over the enclosing scope
        def mk(name, body):
            ret = ast.Return(value=_tuple_of(outs, ast.Load()))
            return ast.FunctionDef(
                name=name, args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=o) for o in outs],
                    kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=(list(body) or [ast.Pass()]) + [ret],
                decorator_list=[])

        # pre-bind outs that don't exist yet to the UNDEF sentinel so the
        # call-site tuple can always be built; using an untaken-branch-only
        # variable later raises a clear error (see _Undef)
        guards = [self._undef_guard(o) for o in outs]
        call = ast.Assign(
            targets=[_tuple_of(outs, ast.Store())],
            value=ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_RUNTIME_NAME, ctx=ast.Load()),
                    attr="run_ifelse", ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()),
                      _tuple_of(outs, ast.Load())],
                keywords=[]))
        return guards + [mk(tname, node.body), mk(fname, node.orelse), call]

    def _undef_guard(self, name: str) -> ast.stmt:
        """try: name \nexcept (NameError, UnboundLocalError): name = UNDEF"""
        return ast.Try(
            body=[ast.Expr(value=ast.Name(id=name, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(
                    elts=[ast.Name(id="NameError", ctx=ast.Load()),
                          ast.Name(id="UnboundLocalError", ctx=ast.Load())],
                    ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=name, ctx=ast.Store())],
                    value=ast.Attribute(
                        value=ast.Name(id=_RUNTIME_NAME, ctx=ast.Load()),
                        attr="UNDEF", ctx=ast.Load()))])],
            orelse=[], finalbody=[])

    # -- while ------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            raise Dy2StaticError(
                f"graph break at line {node.lineno}: while/else is not "
                "supported under to_static(full_graph=False)")
        _forbid(node.body, "while")
        return self._lower_while(node)

    def _lower_while(self, node: ast.While):
        carried = sorted(set(_assigned_names(node.body)))
        if not carried:
            raise Dy2StaticError(
                f"graph break at line {getattr(node, 'lineno', '?')}: "
                "while body assigns no variables — nothing to carry")
        tname, bname = self._fresh("test"), self._fresh("body")
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=c) for c in carried],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        test_fn = ast.FunctionDef(
            name=tname, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=bname, args=args,
            body=list(node.body) + [
                ast.Return(value=_tuple_of(carried, ast.Load()))],
            decorator_list=[])
        call = ast.Assign(
            targets=[_tuple_of(carried, ast.Store())],
            value=ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_RUNTIME_NAME, ctx=ast.Load()),
                    attr="run_while", ctx=ast.Load()),
                args=[ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      _tuple_of(carried, ast.Load())],
                keywords=[]))
        # loop-body temporaries undefined before the loop enter the carry
        # as UNDEF (fine on the Python path; clear error on the traced one)
        guards = [self._undef_guard(c) for c in carried]
        return guards + [test_fn, body_fn, call]

    # -- for i in range(...) ----------------------------------------------
    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.orelse
                    and isinstance(node.target, ast.Name))
        if not is_range:
            # non-range for-loops stay Python (trace-unrolled over concrete
            # iterables — the common, supported case)
            return node
        _forbid(node.body, "for")
        a = node.iter.args
        if len(a) == 1:
            start, stop = ast.Constant(value=0), a[0]
        elif len(a) == 2:
            start, stop = a
        else:
            raise Dy2StaticError(
                f"graph break at line {node.lineno}: range() with a step "
                "is not supported under to_static(full_graph=False); use a "
                "while loop")
        ivar = node.target.id
        # desugar:  i = start; while i < stop: body; i = i + 1
        init = ast.Assign(targets=[ast.Name(id=ivar, ctx=ast.Store())],
                          value=start)
        incr = ast.Assign(
            targets=[ast.Name(id=ivar, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=ivar, ctx=ast.Load()),
                            op=ast.Add(), right=ast.Constant(value=1)))
        wh = ast.copy_location(ast.While(
            test=ast.Compare(left=ast.Name(id=ivar, ctx=ast.Load()),
                             ops=[ast.Lt()], comparators=[stop]),
            body=list(node.body) + [incr], orelse=[]), node)
        # body already visited + checked above — lower directly, no re-walk
        return [init] + self._lower_while(wh)


def convert(fn: Callable) -> Callable:
    """AST-convert ``fn``'s control flow; returns the rewritten function.

    The original closure/globals are preserved; free variables are bound by
    VALUE at conversion time (document: rebind by reconverting)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise Dy2StaticError(
            f"cannot read source of {fn!r} for AST conversion (lambdas, "
            f"REPL or C functions are not convertible): {e}") from e
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise Dy2StaticError(f"expected a function def, got {type(fdef)}")
    fdef.decorator_list = []   # decorators already applied to the original
    # pass 1: break/continue/return -> guard flags (must run before the
    # control-flow conversion turns if-branches into helper functions)
    _JumpRewriter().rewrite(fdef)
    # pass 2: if/while/for -> runtime-dispatch lax control flow
    new = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new)

    glb = dict(fn.__globals__)
    glb[_RUNTIME_NAME] = type("rt", (), _RUNTIME)
    cls_cell = None
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                if name == "__class__":
                    cls_cell = cell.cell_contents
                else:
                    glb[name] = cell.cell_contents
            except ValueError:
                pass
    if cls_cell is not None:
        # zero-arg super() needs a real __class__ CLOSURE CELL, not a
        # global: rebuild the def inside an outer fn providing it
        outer = ast.FunctionDef(
            name="__pt_outer__",
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg="__class__")],
                               kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[fdef,
                  ast.Return(value=ast.Name(id=fdef.name, ctx=ast.Load()))],
            decorator_list=[])
        new = ast.Module(body=[outer], type_ignores=[])
        ast.fix_missing_locations(new)
    code = compile(new, filename=f"<dy2static {fn.__name__}>", mode="exec")
    exec(code, glb)
    out = (glb["__pt_outer__"](cls_cell) if cls_cell is not None
           else glb[fdef.name])
    functools.update_wrapper(out, fn)
    out.__dy2static__ = True
    return out


__all__ = ["convert", "run_ifelse", "run_while", "Dy2StaticError"]
