"""paddle_tpu.jit — dygraph-to-static capture, AOT export, save/load.

Reference: python/paddle/jit/api.py (to_static:171, save/load via
translated_layer.py). The reference captures Python into a static Program by
AST transform or SOT bytecode tracing; here every op is already functionally
traceable, so ``to_static`` is JAX tracing + XLA compilation, and
``jit.save`` is true AOT deployment: the traced computation is serialized as
portable StableHLO (``jax.export``) together with the parameters, and
``jit.load`` returns a ``TranslatedLayer`` that executes WITHOUT the original
Python model code — the analogue of loading a saved inference program.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jexport

__all__ = ["to_static", "not_to_static", "InputSpec", "save", "load",
           "save_deploy_bundle", "TranslatedLayer", "enable_to_static",
           "ignore_module"]

_TO_STATIC_ENABLED = True


def enable_to_static(flag: bool) -> None:
    """Globally toggle to_static (reference: paddle.jit.enable_to_static)."""
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = bool(flag)


def ignore_module(modules) -> None:
    """No-op shim: JAX tracing needs no bytecode-level skip list."""


class InputSpec:
    """Shape/dtype spec for export tracing (reference:
    python/paddle/static/input.py InputSpec). ``None`` dims become symbolic
    dimensions in the exported artifact (dynamic batch)."""

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def to_shape_struct(self, scope=None):
        from paddle_tpu.core.dtype import convert_dtype
        dims = []
        sym_names = []
        for i, d in enumerate(self.shape):
            if d is None:
                sym_names.append(f"d{i}")
                dims.append(None)
            else:
                dims.append(d)
        if sym_names:
            scope = scope or jexport.SymbolicScope()
            syms = jexport.symbolic_shape(
                ",".join(sym_names), scope=scope)
            it = iter(syms)
            dims = [next(it) if d is None else d for d in dims]
        return jax.ShapeDtypeStruct(tuple(dims), convert_dtype(self.dtype))

    @classmethod
    def from_tensor(cls, tensor, name=None):
        """Spec from a live array (reference: static/input.py
        InputSpec.from_tensor:238)."""
        if not hasattr(tensor, "shape") or not hasattr(tensor, "dtype"):
            raise ValueError(
                f"Input `tensor` should be a Tensor, but received "
                f"{type(tensor).__name__}.")
        return cls(tuple(tensor.shape), str(tensor.dtype),
                   name or getattr(tensor, "name", None))

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(tuple(ndarray.shape), str(ndarray.dtype), name)

    def batch(self, batch_size):
        """Prepend a batch dim (reference contract)."""
        if isinstance(batch_size, (list, tuple)):
            batch_size = batch_size[0]
        self.shape = (int(batch_size),) + tuple(self.shape)
        return self

    def unbatch(self):
        if not self.shape:
            raise ValueError("unbatch on a 0-d InputSpec")
        self.shape = tuple(self.shape)[1:]
        return self

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _layer_pure(layer):
    """(pure_fn, params) view of a Layer; pure_fn(params, *args)."""
    pure = layer.functional()
    return pure, layer.raw_state()


def to_static(function=None, input_spec=None, full_graph: bool = True,
              backend=None, static_argnums=None):
    """Compile a function or Layer with jax.jit (reference: jit/api.py:171).

    On a Layer, returns a callable that closes over the layer's state and
    re-reads it each call (mutations to parameters are visible, matching the
    reference's dygraph-parameter semantics)."""

    def deco(fn):
        if not _TO_STATIC_ENABLED:
            return fn
        if hasattr(fn, "functional"):
            layer = fn
            if not full_graph:
                # AST-convert forward's Python control flow to lax ops
                # (reference: jit/dy2static AST transformer path). The
                # converted forward goes on a shallow COPY (shared
                # parameter storage) so the original layer's eager
                # behavior is untouched.
                import copy
                import types
                from . import dy2static as _d2s
                fwd = type(layer).forward
                if not getattr(fwd, "__dy2static__", False):
                    proxy = copy.copy(layer)
                    proxy.forward = types.MethodType(_d2s.convert(fwd),
                                                     proxy)
                    layer = proxy
            pure, _ = _layer_pure(layer)
            jitted = jax.jit(pure)

            def call(*args, **kwargs):
                return jitted(layer.raw_state(), *args, **kwargs)

            call.__wrapped_layer__ = layer
            call.__jitted__ = jitted
            call.__input_spec__ = input_spec
            return call
        if not full_graph and not getattr(fn, "__dy2static__", False):
            from . import dy2static as _d2s
            fn = _d2s.convert(fn)
        jitted = jax.jit(fn, static_argnums=static_argnums)
        jitted.__input_spec__ = input_spec
        return jitted

    if function is None:
        return deco
    return deco(function)


def not_to_static(fn: Callable) -> Callable:
    """Mark a function to stay eager (reference: paddle.jit.not_to_static)."""
    fn.__not_to_static__ = True
    return fn


# ---------------------------------------------------------------------------
# save / load: portable StableHLO artifacts
# ---------------------------------------------------------------------------

def _export_artifact(layer_or_fn, input_spec):
    """Shared export preamble for save/save_deploy_bundle: spec lookup,
    to_static unwrap, functional view, jax.export trace. Returns
    (exported, state, with_params, arg_structs)."""
    if input_spec is None:
        # a to_static-wrapped target carries its spec (reference behavior:
        # jit.save reuses the spec the user gave to_static)
        input_spec = getattr(layer_or_fn, "__input_spec__", None)
    if hasattr(layer_or_fn, "__wrapped_layer__"):
        # a to_static-wrapped Layer: export the underlying layer
        layer_or_fn = layer_or_fn.__wrapped_layer__
    if hasattr(layer_or_fn, "functional"):
        pure, params = _layer_pure(layer_or_fn)
        state = {"params": jax.tree.map(np.asarray, params)}
        fn = pure
        with_params = True
    else:
        fn = getattr(layer_or_fn, "__wrapped__", layer_or_fn)
        state = {}
        with_params = False

    if input_spec is None:
        raise ValueError("jit export requires input_spec (pass it here or "
                         "to jit.to_static) to trace the export")
    scope = jexport.SymbolicScope()
    arg_structs = [s.to_shape_struct(scope) if isinstance(s, InputSpec) else s
                   for s in input_spec]

    if with_params:
        param_structs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), state["params"])
        exported = jexport.export(jax.jit(fn))(param_structs, *arg_structs)
    else:
        exported = jexport.export(jax.jit(fn))(*arg_structs)
    return exported, state, with_params, arg_structs


def save(layer_or_fn, path: str, input_spec: Optional[Sequence] = None,
         **kwargs) -> None:
    """Serialize computation + params for code-free reload.

    Produces (reference shape: jit.save's .pdmodel/.pdiparams pair):
      path.pdexport  — serialized StableHLO (jax.export bytes)
      path.pdparams  — pickled numpy state dict
      path.pdmeta    — json manifest
    """
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    exported, state, with_params, input_spec = _export_artifact(
        layer_or_fn, input_spec)

    with open(path + ".pdexport", "wb") as f:
        f.write(exported.serialize())
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    with open(path + ".pdmeta", "w") as f:
        json.dump({"with_params": with_params,
                   "n_inputs": len(input_spec),
                   "format": "paddle_tpu.jit.v1"}, f)


def save_deploy_bundle(layer_or_fn, path: str,
                       input_spec: Optional[Sequence] = None) -> str:
    """Export a PYTHON-FREE deploy bundle for csrc/pt_deploy_runner.cc.

    Reference analogue: the save_inference_model artifact consumed by the
    C++ AnalysisPredictor (paddle/fluid/inference/api/
    analysis_predictor.cc) — a model a C++ binary can run without
    Python. Here the bundle is portable StableHLO + raw parameter
    binaries + the serialized CompileOptions the PJRT C API wants:

        <path>/manifest.txt        line-based tensor manifest
        <path>/module.stablehlo    portable StableHLO bytecode
        <path>/compile_options.pb  serialized CompileOptionsProto
        <path>/p<N>.bin            parameter leaves, call order

    The runner feeds params (from the bundle) then runtime inputs in
    manifest order — exactly the exported main's calling convention
    (flattened (params, *args) pytree)."""
    # the C++ runner feeds raw binaries against STATIC manifest shapes —
    # symbolic (None) dims would serialize as dimension NAMES the runner
    # cannot parse or feed; reject at export time, not deploy time
    for s in (input_spec or getattr(layer_or_fn, "__input_spec__", None)
              or []):
        shape = getattr(s, "shape", s.shape if hasattr(s, "shape") else ())
        if any(not isinstance(d, int) for d in shape):
            raise ValueError(
                f"save_deploy_bundle requires fully static input shapes "
                f"(got {tuple(shape)}); the C++ runner feeds raw binaries "
                f"against the manifest's concrete dims — export one "
                f"bundle per batch size instead")
    exported, state, with_params, arg_structs = _export_artifact(
        layer_or_fn, input_spec)
    if not with_params:
        raise ValueError("save_deploy_bundle exports Layers (params are "
                         "baked into the bundle); for pure functions use "
                         "jit.save")
    params = state["params"]

    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "module.stablehlo"), "wb") as f:
        f.write(exported.mlir_module_serialized)
    from jax._src.lib import xla_client as _xc
    with open(os.path.join(path, "compile_options.pb"), "wb") as f:
        f.write(_xc.CompileOptions().SerializeAsString())

    def dt(a):
        name = np.dtype(a.dtype).name
        return {"float32": "f32", "float16": "f16", "bfloat16": "bf16",
                "float64": "f64", "int32": "i32", "int64": "i64",
                "uint8": "u8", "int8": "i8", "bool": "pred"}[name]

    lines = ["module module.stablehlo", "options compile_options.pb"]
    leaves = jax.tree.leaves(params)
    for i, leaf in enumerate(leaves):
        fn = f"p{i}.bin"
        with open(os.path.join(path, fn), "wb") as pf:
            pf.write(np.ascontiguousarray(leaf).tobytes())
        lines.append(f"param {fn} {dt(leaf)} "
                     + " ".join(str(d) for d in leaf.shape))
    for s in arg_structs:
        lines.append(f"input {dt(s)} "
                     + " ".join(str(d) for d in s.shape))
    for o in exported.out_avals:
        lines.append(f"output {dt(o)} "
                     + " ".join(str(d) for d in o.shape))
    with open(os.path.join(path, "manifest.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


class TranslatedLayer:
    """A loaded, code-free executable (reference:
    python/paddle/jit/translated_layer.py TranslatedLayer): wraps a
    deserialized StableHLO module + its parameters."""

    def __init__(self, exported, params, with_params: bool):
        self._exported = exported
        self._params = params
        self._with_params = with_params

    def __call__(self, *args):
        args = tuple(jnp.asarray(a) for a in args)
        if self._with_params:
            return self._exported.call(self._params, *args)
        return self._exported.call(*args)

    forward = __call__

    def state_dict(self):
        return self._params

    @property
    def input_specs(self):
        return self._exported.in_avals

    def as_text(self) -> str:
        return self._exported.mlir_module()


def load(path: str) -> TranslatedLayer:
    """Load a jit.save artifact; executes without the original model code."""
    with open(path + ".pdmeta") as f:
        meta = json.load(f)
    with open(path + ".pdexport", "rb") as f:
        exported = jexport.deserialize(f.read())
    with open(path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    params = jax.tree.map(jnp.asarray, state.get("params", {}))
    tl = TranslatedLayer(exported, params, meta["with_params"])
    # surface the artifact's input arity (static.load_inference_model
    # sizes its feed list from this)
    tl.n_inputs = int(meta.get("n_inputs", 1))
    return tl


_SOT_CODE_LEVEL = 0
_SOT_VERBOSITY = 0


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """reference: jit/sot set_code_level — controls translated-code dump.
    Tracing here is jax; the knob maps to jax's jaxpr dump verbosity."""
    global _SOT_CODE_LEVEL
    _SOT_CODE_LEVEL = level


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    global _SOT_VERBOSITY
    _SOT_VERBOSITY = level
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level > 0 else logging.WARNING)


# reference path jit/api.py (doctests use paddle.jit.api.to_static)
from ..utils import register_submodule_aliases as _rsa
import sys as _sys
_rsa(__name__, {"api": _sys.modules[__name__]})


class TracedLayer:
    """Legacy dygraph tracer (reference: jit/api.py TracedLayer — wraps a
    traced program + exposes save_inference_model). TPU: the trace IS a
    jitted function; save_inference_model delegates to jit.save."""

    def __init__(self, layer, jitted, example_inputs):
        self._layer = layer
        self._jitted = jitted
        self._inputs = example_inputs

    @staticmethod
    def trace(layer, inputs):
        inputs = list(inputs)
        pure, params = _layer_pure(layer)
        jitted = jax.jit(lambda *a: pure(layer.raw_state(), *a))
        out = jitted(*inputs)
        return out, TracedLayer(layer, jitted, inputs)

    def __call__(self, *args):
        # reference convention: static_layer([in_var]) — one LIST of
        # inputs (jit/api.py TracedLayer.__call__); bare arrays also taken
        if len(args) == 1 and isinstance(args[0], (list, tuple)):
            args = tuple(args[0])
        return self._jitted(*args)

    def set_strategy(self, build_strategy=None, exec_strategy=None):
        """Reference: TracedLayer.set_strategy(BuildStrategy,
        ExecutionStrategy) tunes the legacy executor. XLA owns both
        concerns here; accepted and recorded for API parity."""
        self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy

    def save_inference_model(self, path, feed=None, fetch=None, **kw):
        specs = [InputSpec(tuple(x.shape), str(x.dtype)) for x in self._inputs]
        save(self._layer, path if isinstance(path, str) else path[0],
             input_spec=specs)


if "TracedLayer" not in __all__:
    __all__.append("TracedLayer")
