"""paddle_tpu.distributed.auto_parallel — the semi-auto + search layer.

Reference: python/paddle/distributed/auto_parallel/ — two halves:

* the **semi-auto DistTensor API** (reference api.py:118 shard_tensor
  etc.): implemented in ``paddle_tpu.parallel`` (GSPMD mesh/placement
  API) and re-exported here so auto-parallel recipes import from the
  reference path;
* the **search half** (reference ``tuner``/``cost_model``: pick the
  hybrid-parallel placement for the user): :mod:`planner` — enumerate
  legal 5D ``(dp, fsdp, tp, pp, sep)`` configs over a declared mesh
  (``fsdp`` is ZeRO-3 as pure PartitionSpecs, ISSUE 18), prune
  with the per-chip HBM model (:mod:`memory_model`), price survivors by
  compiling and attributing their real graphs (PR 8 collective census ×
  PR 9 ``attribute_costs``/``price_census``/``OpCostDB``), and emit the
  winner as concrete GSPMD annotations (:mod:`emit.ShardingPlan`) the
  trainer consumes directly. ``tools/plan.py`` is the CLI face.
"""

from ...parallel.mesh import HybridMesh, current_mesh
from ...parallel.api import (shard_tensor, reshard, shard_layer,
                             shard_optimizer_state, param_spec_tree,
                             Shard, Replicate, Partial)

# the planner surface (ISSUE 11)
from .planner import (ParallelConfig, PricedConfig, PlanReport,
                      StaleCostModelError, InfeasibleMeshError,
                      enumerate_configs, ep_imbalance, price_compiled,
                      price_config, plan, rank_agreement, check_drift,
                      validate_rank_order)
from .memory_model import MemoryEstimate, estimate_hbm, hbm_capacity
from .emit import ShardingPlan, emit_plan, plan_for_config


def dtensor_from_fn(fn, mesh=None, placements=(), *args, **kwargs):
    """Build a sharded tensor from a creation fn (reference: api.py:248
    dtensor_from_fn) — create then place."""
    return shard_tensor(fn(*args, **kwargs), mesh=mesh,
                        placements=placements)

from ..compat import ProcessMesh
from ..strategy import DistributedStrategy as Strategy

__all__ = ["ProcessMesh", "shard_tensor", "reshard", "shard_layer",
           "shard_optimizer_state", "dtensor_from_fn", "Shard",
           "Replicate", "Partial", "Strategy", "HybridMesh",
           "current_mesh", "param_spec_tree",
           # planner API
           "ParallelConfig", "PricedConfig", "PlanReport",
           "StaleCostModelError", "InfeasibleMeshError",
           "enumerate_configs", "ep_imbalance", "price_compiled",
           "price_config", "plan", "rank_agreement", "check_drift",
           "validate_rank_order", "MemoryEstimate", "estimate_hbm",
           "hbm_capacity", "ShardingPlan", "emit_plan", "plan_for_config"]
