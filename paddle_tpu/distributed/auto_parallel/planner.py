"""Sharding planner: enumerate, price, and emit the fastest 5D config.

The reference stack's ``auto_parallel`` layer picks hybrid-parallel
placements for the user; this module is its TPU-native reproduction on
top of the pricing stack PRs 8–9 built:

1. **Enumerate** (:func:`enumerate_configs`) — every legal
   ``(dp, fsdp, tp, pp, sep)`` factorization of the declared device
   mesh, legality meaning model divisibility (heads/layers/sequence/
   batch/hidden per axis) rather than taste. ``fsdp`` is ZeRO-3 as
   GSPMD specs (ISSUE 18): params + AdamW slots + grads sharded over
   the axis, XLA inserting all-gather-on-use / reduce-scatter — no
   reducer machinery.
2. **Prune** — the closed-form per-chip HBM model
   (:mod:`memory_model`): params + optimizer slots + grads + activations
   under remat must fit BEFORE a config earns a compile.
3. **Price** (:func:`price_config`) — each survivor's candidate graph is
   actually compiled (the real ``Trainer`` step over the real sharded
   model on the real mesh) and attributed: per-op compute/HBM roofline
   from :func:`attribute_costs`, per-mesh-axis comm from the PR 8
   collective census priced by :func:`price_census`, measured dot
   latencies and the per-dispatch host floor from the :class:`OpCostDB`
   where calibration exists. There is deliberately no second "model of
   the model": the planner prices the HLO XLA will run.
4. **Emit** (:mod:`emit`) — the winner becomes a concrete GSPMD plan
   (``Mesh`` axis sizes + per-parameter ``PartitionSpec`` + batch spec)
   the trainer consumes directly; the full ranked table persists as a
   plan artifact (``PlanReport.save``).

The cost model watches itself: before trusting its tables, :func:`plan`
consults the ``pt_step_time_predicted_over_measured`` drift gauge
(PR 10) and the OpCostDB calibration age — ``drift="warn"`` annotates
the report, ``drift="refuse"`` raises :class:`StaleCostModelError`.

Prediction convention: serialized upper bound, like the analyzer —
``compute⊕hbm roofline + priced comm + per-collective launch floor +
dispatch floor``. Absolute seconds are only as good as the device
tables; the acceptance bar is therefore RANK ORDER against measured
step times (:func:`validate_rank_order` over the MULTICHIP dryrun
scenarios / ``tools/plan.py --validate``), not absolute error.
"""

from __future__ import annotations

import json
import math
import time
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ParallelConfig", "PricedGraph", "PricedConfig", "PlanReport",
    "StaleCostModelError", "InfeasibleMeshError", "enumerate_configs",
    "price_compiled", "price_config", "plan", "rank_agreement",
    "check_drift", "measure_compiled", "validate_rank_order",
    "ep_imbalance",
]

# per-collective launch floor (seconds): tiny-payload collectives are
# latency-bound, not bandwidth-bound, so bytes ÷ bw alone would call a
# 60-collective graph free. Kept SMALL by design — on the CPU tier the
# virtual-device emulation makes per-collective cost pure noise while the
# per-op compute/byte attribution tracks measured ordering (verified on
# the dp8/dp4tp2/pp2 candidate sweep), so the floor must stay below the
# compute signal; on TPU the ICI launch overhead is ~µs.
COLLECTIVE_FLOOR_S = {"cpu": 2e-6, "default": 1e-6}

#: OpCostDB graph records older than this are stale for drift purposes
CALIBRATION_MAX_AGE_S = 14 * 24 * 3600.0

#: acceptable band for the pt_step_time_predicted_over_measured gauge —
#: wide because the serialized roofline legitimately over/under-shoots
#: on overlap-heavy (TPU) or dispatch-heavy (CPU tier) programs; outside
#: it the cost tables themselves are suspect
DRIFT_BAND = (0.2, 5.0)


class StaleCostModelError(RuntimeError):
    """The drift gauge says the cost tables disagree with reality beyond
    the band — a plan ranked with them would be noise."""


class InfeasibleMeshError(RuntimeError):
    """No legal config fits the declared mesh (wrong device count, or
    every factorization failed the HBM model)."""


@dataclass(frozen=True)
class ParallelConfig:
    """One point in the 5D search space (axis vocabulary of
    ``parallel/mesh.py AXES_ORDER``; ``fsdp`` is ZeRO-3 expressed as
    GSPMD specs — params/slots/grads sharded over the axis, batch over
    ``dp×fsdp`` — ``ep`` (ISSUE 20) shards experts over a subgroup of
    the data ranks: it divides ``dp`` rather than multiplying the device
    count, so ``size`` is ep-invariant)."""
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sep: int = 1
    fsdp: int = 1
    ep: int = 1

    @property
    def size(self) -> int:
        # ep carves a subgroup out of dp — it never adds devices
        return self.dp * self.fsdp * self.tp * self.pp * self.sep

    def axes(self) -> Dict[str, int]:
        return {"dp": self.dp, "fsdp": self.fsdp, "tp": self.tp,
                "pp": self.pp, "sep": self.sep, "ep": self.ep}

    def __str__(self) -> str:
        # the fsdp/ep segments appear only when the axis is real — plan
        # artifacts, graph-budget pins and elastic sidecars from before
        # the axes existed keep parsing AND printing byte-identically
        fs = f"fsdp{self.fsdp}_" if self.fsdp > 1 else ""
        e = f"ep{self.ep}_" if self.ep > 1 else ""
        return f"dp{self.dp}_{fs}{e}tp{self.tp}_pp{self.pp}_sep{self.sep}"

    @staticmethod
    def parse(s: str) -> "ParallelConfig":
        """Inverse of ``str()`` (also accepts ``dp2xtp2`` / ``dp=2,tp=2``
        forms so the CLI stays forgiving)."""
        import re
        out = {"dp": 1, "tp": 1, "pp": 1, "sep": 1, "fsdp": 1, "ep": 1}
        # the lookbehind keeps the 'dp' inside 'fsdp4' (and the 'ep'
        # inside 'sep2') from matching as a degree of the shorter name
        for m in re.finditer(
                r"(?<![a-z])(fsdp|dp|tp|pp|sep|ep)\s*=?\s*(\d+)",
                s.lower()):
            out[m.group(1)] = int(m.group(2))
        return ParallelConfig(**out)


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_configs(n_devices: int, model_cfg=None, *,
                      global_batch: int = 8, seq_len: int = 32,
                      max_pp: Optional[int] = None,
                      include_sep: bool = True,
                      include_pp: bool = True,
                      include_fsdp: bool = True,
                      include_ep: bool = True) -> List[ParallelConfig]:
    """Every legal ``(dp, fsdp, tp, pp, sep[, ep])`` with
    ``dp*fsdp*tp*pp*sep == n_devices``. Legality against ``model_cfg``
    (a LlamaConfig shape):

    * ``tp`` divides attention heads, KV heads, intermediate and vocab
      (column/row-parallel projections + vocab-parallel CE);
    * ``fsdp`` divides the hidden size (every projection/embedding is
      annotated with the axis on its H dimension) and, jointly with
      ``dp``, the global batch (batch spec is ``("dp","fsdp")``);
    * ``pp`` divides the layer count (stage stacking), and the
      per-data-rank batch must hold ≥2 microbatches;
    * ``sep`` divides the sequence (ring/GSPMD seq sharding) and the
      KV-head count (the ring exchanges head-sharded KV blocks);
    * ``dp`` divides the global batch;
    * ``ep`` (enumerated only for MoE models — ``model_cfg`` exposes
      ``num_experts``) divides ``dp`` (the expert subgroup is carved out
      of the data ranks, never extra devices) and the expert count, and
      composes with neither ``pp`` nor ``sep`` yet (stated exclusions,
      like pp×sep).

    Without a ``model_cfg`` only the factorization + batch constraints
    apply (the CLI's ``--no-model`` exploration mode); ep stays 1 there
    because its legality is inherently a model property.
    """
    out: List[ParallelConfig] = []
    for dp in _divisors(n_devices):
        if global_batch % dp:
            continue
        rest0 = n_devices // dp
        for fsdp in _divisors(rest0):
            if fsdp > 1 and not include_fsdp:
                continue
            if global_batch % (dp * fsdp):
                continue
            rest1 = rest0 // fsdp
            for tp in _divisors(rest1):
                rest2 = rest1 // tp
                for pp in _divisors(rest2):
                    if not include_pp and pp > 1:
                        continue
                    if max_pp is not None and pp > max_pp:
                        continue
                    sep = rest2 // pp
                    if sep > 1 and not include_sep:
                        continue
                    cfg = ParallelConfig(dp=dp, fsdp=fsdp, tp=tp, pp=pp,
                                         sep=sep)
                    if model_cfg is not None and not _legal(
                            cfg, model_cfg, global_batch, seq_len):
                        continue
                    out.append(cfg)
                    # ep variants: only meaningful for MoE models, and
                    # only dp-divisor degrees — size is ep-invariant so
                    # these share the same device factorization
                    if (include_ep and model_cfg is not None
                            and getattr(model_cfg, "num_experts", 0)):
                        import dataclasses as _dc
                        for ep in _divisors(dp):
                            if ep == 1:
                                continue
                            cfg_ep = _dc.replace(cfg, ep=ep)
                            if _legal(cfg_ep, model_cfg, global_batch,
                                      seq_len):
                                out.append(cfg_ep)
    # stable, human-sensible order: least exotic first
    out.sort(key=lambda c: (c.pp, c.sep, c.fsdp, c.tp, c.dp, c.ep))
    return out


def _legal(cfg: ParallelConfig, m, global_batch: int,
           seq_len: int) -> bool:
    if cfg.tp > 1:
        if (m.num_attention_heads % cfg.tp
                or m.num_key_value_heads % cfg.tp
                or m.intermediate_size % cfg.tp
                or m.vocab_size % cfg.tp):
            return False
    if cfg.fsdp > 1:
        # every fsdp annotation in models/llama.py lands on the hidden
        # dimension (qkv/gate_up dim0, o/down/embed dim1, lm_head dim0),
        # so H-divisibility is the whole sharding constraint; the batch
        # constraint comes from the ("dp","fsdp") batch spec
        if (m.hidden_size % cfg.fsdp
                or global_batch % (cfg.dp * cfg.fsdp)):
            return False
    if cfg.pp > 1:
        if m.num_hidden_layers % cfg.pp:
            return False
        # the pipe candidate compiles with num_microbatches=2, so the
        # per-data-rank (dp×fsdp) batch must split into 2 microbatches
        # exactly — a bare ">= 2" check admits configs whose build then
        # fails and reads as a misleading "compile failed" prune
        per_dp = global_batch // (cfg.dp * cfg.fsdp)
        if per_dp < 2 or per_dp % 2:
            return False
    if cfg.sep > 1:
        if seq_len % cfg.sep or m.num_key_value_heads % cfg.sep:
            return False
    if cfg.pp > 1 and cfg.sep > 1:
        # pipe stage stacking and the seq-parallel ring are separately
        # tested but their composition is not a supported scenario yet
        # (ROADMAP item 4) — don't emit plans we can't compile
        return False
    if cfg.tp > 1 and getattr(m, "num_experts", 0):
        # expert FFN weights carry the tp annotation on their
        # moe_intermediate dimension
        if getattr(m, "moe_intermediate_size", 0) % cfg.tp:
            return False
    if cfg.ep > 1:
        n_exp = int(getattr(m, "num_experts", 0) or 0)
        # the expert subgroup is carved out of the data ranks and must
        # split the expert set evenly across its members
        if not n_exp or n_exp % cfg.ep or cfg.dp % cfg.ep:
            return False
        # explicit composition exclusions, stated like pp×sep: neither
        # the pipe stage stacker nor the sep ring carries the expert
        # all-to-all yet
        if cfg.pp > 1 or cfg.sep > 1:
            return False
    return True


# ---------------------------------------------------------------------------
# pricing
# ---------------------------------------------------------------------------

def ep_imbalance(histogram, ep: int) -> float:
    """Bottleneck factor for the expert all-to-all from a MEASURED
    per-expert token histogram (ISSUE 20's routing-entropy term).

    With tokens uniformly spread over source ranks, the *fraction* of
    tokens crossing shards is 1−1/ep regardless of expert popularity —
    skew shows up instead on the bottleneck link: a2a completion time is
    set by the busiest destination shard. Group the histogram into
    ``ep`` contiguous expert shards (the ep-axis layout of the expert
    dimension); the factor is ``ep × max shard share`` — 1.0 when
    routing is balanced, → ep when one shard absorbs everything.
    Dividing the ep-axis bandwidth by this factor makes
    :func:`price_census` charge the busiest link's bytes."""
    import numpy as np
    h = np.asarray(histogram, dtype=float).ravel()
    if ep <= 1 or h.size == 0 or h.size % ep or h.sum() <= 0:
        return 1.0
    shard_share = h.reshape(ep, h.size // ep).sum(axis=1) / h.sum()
    return float(max(ep * shard_share.max(), 1.0))


@dataclass
class PricedGraph:
    """One compiled graph, priced: the component terms and their sum."""
    compute_s: float              # per-op max(flops/peak, bytes/hbm_bw)
    comm_s: float                 # priced census bytes ÷ per-axis bw
    collective_floor_s: float     # n_collectives × per-tier launch floor
    dispatch_s: float             # measured per-dispatch host floor
    dot_adjust_s: float           # measured-dot correction (OpCostDB)
    predicted_step_s: float
    census_counts: Dict[str, int]
    census_bytes: int
    priced_census: Dict
    total_flops: float
    total_bytes: float
    notes: List[str] = field(default_factory=list)

    def components(self) -> Dict[str, float]:
        return {"compute_s": self.compute_s, "comm_s": self.comm_s,
                "collective_floor_s": self.collective_floor_s,
                "dispatch_s": self.dispatch_s,
                "dot_adjust_s": self.dot_adjust_s,
                "predicted_step_s": self.predicted_step_s}


def _collective_floor(kind: str) -> float:
    return COLLECTIVE_FLOOR_S["cpu" if "cpu" in kind.lower() \
        else "default"]


def _db_dispatch_floor(db, kind: str) -> Tuple[float, List[str]]:
    """Measured per-dispatch host floor: the train-step graph's
    null-executable floor from the calibration probe, when this device
    kind has been calibrated."""
    notes: List[str] = []
    if db is None:
        return 0.0, notes
    from ...ops.pallas.autotune import OpCostDB
    rec = db.lookup(OpCostDB.graph_key("train_step_k1", kind))
    if not rec:
        notes.append(f"OpCostDB has no graph calibration for "
                     f"'{kind}' — dispatch floor 0, analytical only "
                     f"(run tools/op_cost_probe.py --calibrate)")
        return 0.0, notes
    return float(rec.get("dispatch_floor_s", 0.0)), notes


def price_compiled(compiled_or_text, mesh=None, *, spec=None,
                   bandwidths: Optional[Dict[str, float]] = None,
                   db=None, dispatch_floor_s: Optional[float] = None,
                   collective_floor_s: Optional[float] = None
                   ) -> PricedGraph:
    """Price ONE compiled graph (anything with ``as_text()``, or raw
    optimized-HLO text): the shared core under :func:`price_config`,
    the dryrun's rank-order validation, and the graph_lint planner
    budget.

    ``bandwidths`` maps mesh-axis name → bytes/s for the census pricing
    (axes it doesn't name fall back to ``spec.link_bw``); a synthetic
    table therefore yields EXACT arithmetic — the pricing-exactness
    tests pin that property.
    """
    from ...analysis.hlo import parse_hlo
    from ...analysis.collectives import collective_census
    from ...observability.costs import (attribute_costs, device_spec,
                                        price_census)
    spec = spec or device_spec()
    text = (compiled_or_text if isinstance(compiled_or_text, str)
            else compiled_or_text.as_text())
    mod = parse_hlo(text)
    report = attribute_costs(mod, spec=spec)
    census = collective_census(mod, mesh=mesh)
    priced = price_census(census, bandwidths=bandwidths, spec=spec)

    # compute/HBM roofline WITHOUT the comm term — comm is priced per
    # axis by the census (the analyzer's single link_bw verdict would
    # double-count it)
    compute_s = 0.0
    for o in report.ops:
        compute_s += max(o.flops / spec.peak_flops,
                         o.bytes / spec.hbm_bw)

    notes: List[str] = list(report.notes)
    # measured-dot correction: replace the analytical time of every dot
    # shape the calibration probe has measured on this device kind
    dot_adjust = 0.0
    if db is not None:
        from ...ops.pallas.autotune import OpCostDB
        for m_dim, k, n, dtype, count in report.dots:
            rec = db.lookup(OpCostDB.dot_key(m_dim, k, n, dtype,
                                             spec.kind))
            if rec and rec.get("t_s"):
                analytical = 2.0 * m_dim * k * n / spec.peak_flops
                dot_adjust += (float(rec["t_s"]) - analytical) * count
    if dispatch_floor_s is None:
        dispatch_floor_s, db_notes = _db_dispatch_floor(db, spec.kind)
        notes += db_notes
    if collective_floor_s is None:
        collective_floor_s = _collective_floor(spec.kind)
    n_coll = census["total_collectives"]
    floor_s = n_coll * collective_floor_s
    predicted = (max(compute_s + dot_adjust, 0.0)
                 + priced["total_comm_s"] + floor_s + dispatch_floor_s)
    return PricedGraph(
        compute_s=compute_s, comm_s=priced["total_comm_s"],
        collective_floor_s=floor_s, dispatch_s=dispatch_floor_s,
        dot_adjust_s=dot_adjust, predicted_step_s=predicted,
        census_counts=dict(census["counts"]),
        census_bytes=int(census["total_collective_bytes"]),
        priced_census=priced, total_flops=report.total_flops,
        total_bytes=report.total_bytes, notes=notes)


@dataclass
class CandidateBuild:
    """The concrete artifacts one priced config was compiled from —
    kept (``keep_builds=True``) so validation can EXECUTE the same
    program it priced."""
    model: object
    mesh: object
    trainer: object
    batch: Dict
    compiled: object


@dataclass
class PricedConfig:
    config: ParallelConfig
    feasible: bool
    memory: Optional[object] = None          # MemoryEstimate
    graph: Optional[PricedGraph] = None
    predicted_step_s: float = math.inf
    predicted_mfu: float = 0.0
    hbm_high_water_bytes: float = 0.0
    plan: Optional[object] = None            # emit.ShardingPlan
    measured_step_s: Optional[float] = None
    reason: str = ""
    build: Optional[CandidateBuild] = None

    def as_dict(self) -> Dict:
        out = {"config": str(self.config), "axes": self.config.axes(),
               "feasible": self.feasible,
               "predicted_step_s": self.predicted_step_s,
               "predicted_mfu": self.predicted_mfu,
               "hbm_high_water_bytes": self.hbm_high_water_bytes,
               "reason": self.reason}
        if self.memory is not None:
            out["memory"] = self.memory.as_dict()
        if self.graph is not None:
            out["components"] = self.graph.components()
            out["census_counts"] = self.graph.census_counts
            out["census_bytes"] = self.graph.census_bytes
        if self.measured_step_s is not None:
            out["measured_step_s"] = self.measured_step_s
        if self.plan is not None:
            out["plan"] = self.plan.as_dict()
        return out


def _build_candidate(model_cfg, cfg: ParallelConfig, devices,
                     global_batch: int, seq_len: int) -> CandidateBuild:
    """Compile the REAL trainer step for one config: sharded model on
    the real mesh — the same construction path as the MULTICHIP dryrun
    scenarios, so what the planner prices is what the trainer runs."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as pt
    from ...models import (LlamaForCausalLM, LlamaForCausalLMPipe,
                           MoEForCausalLM)
    from ...optimizer import AdamW
    from ...parallel import (HybridMesh, shard_layer,
                             shard_optimizer_state, shard_tensor,
                             param_spec_tree)
    from ...trainer import Trainer

    import dataclasses
    is_moe = bool(getattr(model_cfg, "num_experts", 0))
    if is_moe:
        mcfg = model_cfg
    else:
        mcfg = dataclasses.replace(model_cfg,
                                   sequence_parallel=cfg.sep > 1)
    pt.seed(0)
    if cfg.pp > 1:
        model = LlamaForCausalLMPipe(mcfg, num_stages=cfg.pp,
                                     num_microbatches=2)
    elif is_moe:
        model = MoEForCausalLM(mcfg)
    else:
        model = LlamaForCausalLM(mcfg)
    hm = HybridMesh.build(dp=cfg.dp, fsdp=cfg.fsdp, tp=cfg.tp,
                          pp=cfg.pp, sep=cfg.sep, ep=cfg.ep,
                          devices=list(devices)[:cfg.size])
    # on an ep mesh the batch shards over the full data submesh
    # dp×ep×fsdp (dp axis size is dp/ep there); ep==1 meshes have no
    # "ep" axis, so the spec must not name it
    data_axes = (("dp", "ep", "fsdp") if cfg.ep > 1
                 else ("dp", "fsdp"))
    with hm:
        shard_layer(model)
        tr = Trainer(model, AdamW(learning_rate=1e-3, parameters=model),
                     donate=False)
        tr.opt_state = shard_optimizer_state(tr.opt_state,
                                             param_spec_tree(model))
        rs = np.random.RandomState(0)
        ids = rs.randint(0, mcfg.vocab_size, (global_batch, seq_len + 1))
        batch = {"input_ids": shard_tensor(jnp.asarray(ids[:, :-1]),
                                           spec=P(data_axes, None)),
                 "labels": shard_tensor(jnp.asarray(ids[:, 1:]),
                                        spec=P(data_axes, None))}
        tr._ensure_built()
        args = (tr.params, tr.opt_state, batch, tr._lr_scalar(),
                tr._key_data())
        compiled = tr._step_jit.lower(*args).compile()
    return CandidateBuild(model=model, mesh=hm, trainer=tr, batch=batch,
                          compiled=compiled)


def price_config(config: ParallelConfig, model_cfg, *, devices=None,
                 global_batch: int = 8, seq_len: int = 32,
                 bandwidths: Optional[Dict[str, float]] = None,
                 spec=None, db=None,
                 dispatch_floor_s: Optional[float] = None,
                 collective_floor_s: Optional[float] = None,
                 hbm_budget_bytes: Optional[float] = None,
                 keep_build: bool = False,
                 check_memory: bool = True,
                 moe_histogram=None) -> PricedConfig:
    """Memory-gate, compile, attribute and price ONE config; emit its
    GSPMD plan. Infeasible configs return without paying a compile.

    ``moe_histogram`` — measured per-expert token counts. For ep>1
    configs the expert all-to-all is priced from it: the ep-axis
    bandwidth fed to ``price_census`` is divided by
    :func:`ep_imbalance`, so skewed routing raises the predicted price
    (the compile-only census cannot see data-dependent skew)."""
    import jax
    from ...observability.costs import device_spec
    from .memory_model import estimate_hbm
    from .emit import emit_plan

    spec = spec or device_spec()
    imb = 1.0
    if moe_histogram is not None and config.ep > 1:
        imb = ep_imbalance(moe_histogram, config.ep)
        bandwidths = dict(bandwidths or {})
        bandwidths["ep"] = bandwidths.get("ep", spec.link_bw) / imb
    mem = None
    if check_memory:
        mem = estimate_hbm(model_cfg, config, global_batch=global_batch,
                           seq_len=seq_len, budget_bytes=hbm_budget_bytes,
                           device_kind=spec.kind)
        if not mem.feasible:
            return PricedConfig(
                config=config, feasible=False, memory=mem,
                hbm_high_water_bytes=mem.total_bytes,
                reason=(f"HBM infeasible: needs "
                        f"{mem.total_bytes / 2**30:.2f} GiB/chip, budget "
                        f"{mem.budget_bytes / 2**30:.2f} GiB"))

    devices = list(devices) if devices is not None else list(jax.devices())
    if config.size > len(devices):
        return PricedConfig(
            config=config, feasible=False, memory=mem,
            reason=f"needs {config.size} devices, {len(devices)} "
                   f"available")

    build = _build_candidate(model_cfg, config, devices, global_batch,
                             seq_len)
    graph = price_compiled(build.compiled, mesh=build.mesh, spec=spec,
                           bandwidths=bandwidths, db=db,
                           dispatch_floor_s=dispatch_floor_s,
                           collective_floor_s=collective_floor_s)
    if imb > 1.0:
        graph.notes.append(
            f"ep all-to-all priced from measured routing histogram: "
            f"bottleneck imbalance ×{imb:.3f} on the ep axis")
    # MFU from the one model-flop definition (PaLM closed form is the
    # cross-paper headline; the planner's denominator is per-chip peak
    # over the WHOLE mesh for the global batch)
    tokens = global_batch * seq_len
    model_flops = build.model.flops_per_token(seq_len) * tokens
    mfu = model_flops / (config.size * spec.peak_flops
                         * graph.predicted_step_s) \
        if graph.predicted_step_s > 0 else 0.0
    sharding_plan = emit_plan(build.model, build.mesh, config)
    pc = PricedConfig(
        config=config, feasible=True, memory=mem, graph=graph,
        predicted_step_s=graph.predicted_step_s, predicted_mfu=mfu,
        hbm_high_water_bytes=(mem.total_bytes if mem is not None
                              else 0.0),
        plan=sharding_plan)
    if keep_build:
        pc.build = build
    return pc


# ---------------------------------------------------------------------------
# drift: the planner consults the cost model's own health signal
# ---------------------------------------------------------------------------

def check_drift(band: Tuple[float, float] = DRIFT_BAND,
                db=None, now: Optional[float] = None) -> Dict:
    """Is the cost model currently trustworthy?

    Two signals, both advisory by design (``plan(drift=...)`` decides
    what to do with them):

    * the live ``pt_step_time_predicted_over_measured`` gauge (PR 10) —
      any published component outside ``band`` means the roofline is
      actively disagreeing with the wall clock;
    * OpCostDB calibration age — graph records older than
      ``CALIBRATION_MAX_AGE_S`` (or absent for this device kind) can't
      anchor measured floors.

    Returns ``{"status": "ok"|"stale"|"uncalibrated", "ratios": {...},
    "notes": [...]}`` — "stale" is the refusal-grade verdict, absence of
    evidence ("uncalibrated") only warns.
    """
    from ...observability.metrics import REGISTRY
    ratios: Dict[str, float] = {}
    notes: List[str] = []
    status = "ok"
    try:
        for row in REGISTRY.collect():
            if row.get("name") != "pt_step_time_predicted_over_measured":
                continue
            comp = row.get("labels", {}).get("component", "?")
            v = float(row.get("value", 0.0))
            ratios[comp] = v
            if v and not (band[0] <= v <= band[1]):
                status = "stale"
                notes.append(
                    f"drift gauge component={comp}: predicted/measured "
                    f"= {v:.3g} outside [{band[0]}, {band[1]}] — "
                    f"recalibrate (tools/op_cost_probe.py --calibrate) "
                    f"before trusting this plan")
    except Exception:
        pass
    if status == "ok" and db is not None:
        from ...observability.costs import device_spec
        from ...ops.pallas.autotune import OpCostDB
        rec = db.lookup(OpCostDB.graph_key("train_step_k1",
                                           device_spec().kind))
        if rec is None:
            status = "uncalibrated"
            notes.append("no OpCostDB calibration for this device kind; "
                         "pricing is analytical-only")
        else:
            try:
                cap = time.mktime(time.strptime(rec["captured_at"],
                                                "%Y-%m-%dT%H:%M:%S"))
                age = (now if now is not None else time.time()) - cap
                if age > CALIBRATION_MAX_AGE_S:
                    status = "uncalibrated"
                    notes.append(f"OpCostDB calibration is "
                                 f"{age / 86400:.0f} days old")
            except (KeyError, ValueError):
                pass
    return {"status": status, "ratios": ratios, "notes": notes}


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------

@dataclass
class PlanReport:
    """The full planning result: ranked table + chosen plan + the drift
    verdict the ranking was produced under."""
    n_devices: int
    mesh_shape: str
    device: Dict
    model: str
    global_batch: int
    seq_len: int
    ranked: List[PricedConfig] = field(default_factory=list)
    pruned: List[PricedConfig] = field(default_factory=list)
    drift: Dict = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)
    validation: Optional[Dict] = None

    @property
    def chosen(self) -> Optional[PricedConfig]:
        return self.ranked[0] if self.ranked else None

    def table(self, top: Optional[int] = None) -> str:
        rows = self.ranked[:top] if top else self.ranked
        lines = [f"{'config':<24} {'pred step':>12} {'pred MFU':>9} "
                 f"{'HBM GiB':>8} {'comm':>10} {'collectives':>11}"]
        for pc in rows:
            g = pc.graph
            lines.append(
                f"{str(pc.config):<24} "
                f"{pc.predicted_step_s * 1e3:>10.3f}ms "
                f"{pc.predicted_mfu:>9.4f} "
                f"{pc.hbm_high_water_bytes / 2**30:>8.3f} "
                f"{(g.comm_s * 1e6 if g else 0):>8.1f}us "
                f"{(sum(g.census_counts.values()) if g else 0):>11}")
        for pc in self.pruned:
            lines.append(f"{str(pc.config):<24} PRUNED: {pc.reason}")
        return "\n".join(lines)

    def as_dict(self) -> Dict:
        return {
            "schema": "pt-shard-plan-v1",
            "n_devices": self.n_devices, "mesh_shape": self.mesh_shape,
            "device": self.device, "model": self.model,
            "global_batch": self.global_batch, "seq_len": self.seq_len,
            "drift": self.drift, "notes": self.notes,
            "ranked": [pc.as_dict() for pc in self.ranked],
            "pruned": [pc.as_dict() for pc in self.pruned],
            "chosen": (str(self.chosen.config) if self.chosen else None),
            **({"validation": self.validation} if self.validation
               else {}),
        }

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.as_dict(), f, indent=1, sort_keys=True,
                      default=float)
            f.write("\n")
        return path


def plan(model_cfg, *, n_devices: Optional[int] = None, devices=None,
         mesh_shape: str = "", global_batch: int = 8, seq_len: int = 32,
         configs: Optional[Sequence[ParallelConfig]] = None,
         bandwidths: Optional[Dict[str, float]] = None, spec=None,
         db=None, drift: str = "warn",
         hbm_budget_bytes: Optional[float] = None,
         dispatch_floor_s: Optional[float] = None,
         collective_floor_s: Optional[float] = None,
         keep_builds: bool = False,
         model_name: str = "llama",
         moe_histogram=None) -> PlanReport:
    """Enumerate → prune → price → rank → emit.

    ``drift`` — "warn" (annotate + warnings.warn), "refuse" (raise
    :class:`StaleCostModelError` when the drift gauge is out of band),
    or "ignore". Raises :class:`InfeasibleMeshError` when the mesh
    can't host any legal config (the CLI's nonzero-exit contract).
    """
    import jax
    from ...observability.costs import device_spec, get_op_cost_db

    if drift not in ("warn", "refuse", "ignore"):
        raise ValueError(f"drift must be warn|refuse|ignore, got "
                         f"{drift!r}")
    spec = spec or device_spec()
    if db is None:
        db = get_op_cost_db()
    devices = list(devices) if devices is not None else list(jax.devices())
    n = int(n_devices) if n_devices else len(devices)
    if n > len(devices):
        raise InfeasibleMeshError(
            f"mesh declares {n} devices but only {len(devices)} exist")

    drift_verdict = {"status": "ignored", "ratios": {}, "notes": []}
    if drift != "ignore":
        drift_verdict = check_drift(db=db)
        if drift_verdict["status"] == "stale":
            msg = "; ".join(drift_verdict["notes"])
            if drift == "refuse":
                raise StaleCostModelError(msg)
            warnings.warn(f"sharding planner: {msg}", RuntimeWarning,
                          stacklevel=2)

    cand = list(configs) if configs is not None else enumerate_configs(
        n, model_cfg, global_batch=global_batch, seq_len=seq_len)
    if not cand:
        raise InfeasibleMeshError(
            f"no legal (dp,fsdp,tp,pp,sep) factorization of {n} devices "
            f"for this model/batch (global_batch={global_batch}, "
            f"seq_len={seq_len})")

    report = PlanReport(
        n_devices=n, mesh_shape=mesh_shape or str(n),
        device=spec.as_dict(), model=model_name,
        global_batch=global_batch, seq_len=seq_len,
        drift=drift_verdict, notes=list(drift_verdict["notes"]))

    for cfg in cand:
        if cfg.size != n:
            report.pruned.append(PricedConfig(
                config=cfg, feasible=False,
                reason=f"size {cfg.size} != mesh {n}"))
            continue
        try:
            pc = price_config(
                cfg, model_cfg, devices=devices,
                global_batch=global_batch, seq_len=seq_len,
                bandwidths=bandwidths, spec=spec, db=db,
                dispatch_floor_s=dispatch_floor_s,
                collective_floor_s=collective_floor_s,
                hbm_budget_bytes=hbm_budget_bytes,
                keep_build=keep_builds,
                moe_histogram=moe_histogram)
        except Exception as e:       # a config that can't compile is
            pc = PricedConfig(       # pruned evidence, not a crash
                config=cfg, feasible=False,
                reason=f"compile failed: {type(e).__name__}: "
                       f"{str(e)[:200]}")
        (report.ranked if pc.feasible else report.pruned).append(pc)

    report.ranked.sort(key=lambda pc: pc.predicted_step_s)
    if not report.ranked:
        raise InfeasibleMeshError(
            "every candidate config was pruned:\n"
            + "\n".join(f"  {pc.config}: {pc.reason}"
                        for pc in report.pruned))
    return report


# ---------------------------------------------------------------------------
# rank-order validation (the acceptance bar)
# ---------------------------------------------------------------------------

def rank_agreement(predicted: Sequence[float],
                   measured: Sequence[float],
                   rel_eps: float = 0.05) -> float:
    """Pairwise (Kendall tau-b-style) concordance between two
    orderings: fraction of index pairs ordered the same way. 1.0 =
    identical order, 0.5 = uncorrelated, 0.0 = reversed.

    Pairs within ``rel_eps`` relative distance in EITHER list are
    statistical ties and drop out of the denominator (tau-b's tie
    handling): min-of-rounds ordering between two configs 1% apart is
    noise, and a cost model should be judged on the orderings it
    actually asserts."""
    assert len(predicted) == len(measured)
    n = len(predicted)
    if n < 2:
        return 1.0

    def _sign(a: float, b: float) -> int:
        if abs(a - b) <= rel_eps * max(abs(a), abs(b)):
            return 0
        return 1 if a > b else -1

    agree = total = 0
    for i in range(n):
        for j in range(i + 1, n):
            sp = _sign(predicted[i], predicted[j])
            sm = _sign(measured[i], measured[j])
            if sp == 0 or sm == 0:
                continue
            total += 1
            agree += (sp == sm)
    return agree / total if total else 1.0


def measure_compiled(compiled, args, *, rounds: int = 3, iters: int = 2,
                     warmup: int = 1) -> float:
    """Min-of-rounds per-call seconds for an undonated compiled program
    (the bench-variance policy: mins over interle-able rounds beat
    means on a noisy host)."""
    import jax

    def _block(out):
        leaves = [l for l in jax.tree_util.tree_leaves(out)
                  if hasattr(l, "block_until_ready")]
        if leaves:
            leaves[-1].block_until_ready()

    for _ in range(max(0, warmup)):
        _block(compiled(*args))
    best = float("inf")
    for _ in range(max(1, rounds)):
        out = None
        t0 = time.perf_counter()
        for _ in range(max(1, iters)):
            out = compiled(*args)
        _block(out)
        best = min(best, (time.perf_counter() - t0) / max(1, iters))
    return best


def validate_rank_order(report: PlanReport, *, rounds: int = 4,
                        iters: int = 2) -> Dict:
    """Execute every ranked config's OWN priced program and compare the
    predicted ordering with the measured one. Requires
    ``plan(keep_builds=True)``. Returns the verdict dict the bench row
    and the dryrun print: pairwise agreement, whether the predicted
    winner lands in the measured top 2, and the per-config table.

    Rounds INTERLEAVE across configs (the op_cost_probe discipline): a
    host-contention spike then taxes every config's round equally
    instead of wholly landing on whichever config was being timed —
    sequential timing measurably scrambles the ordering on a shared
    host."""
    import gc
    import jax

    def _block(out):
        leaves = [l for l in jax.tree_util.tree_leaves(out)
                  if hasattr(l, "block_until_ready")]
        if leaves:
            leaves[-1].block_until_ready()

    rows, argsets = [], []
    for pc in report.ranked:
        if pc.build is None:
            continue
        tr, batch = pc.build.trainer, pc.build.batch
        args = (tr.params, tr.opt_state, batch, tr._lr_scalar(),
                tr._key_data())
        _block(pc.build.compiled(*args))              # warmup, off-clock
        rows.append(pc)
        argsets.append(args)
    best = [float("inf")] * len(rows)
    for _ in range(max(1, rounds)):
        for i, pc in enumerate(rows):
            gc.collect()
            out = None
            t0 = time.perf_counter()
            for _ in range(max(1, iters)):
                out = pc.build.compiled(*argsets[i])
            _block(out)
            best[i] = min(best[i],
                          (time.perf_counter() - t0) / max(1, iters))
    for pc, t in zip(rows, best):
        pc.measured_step_s = t
    if len(rows) < 2:
        return {"n_configs": len(rows), "agreement": 1.0,
                "top1_is_measured_top2": 1.0,
                "note": "fewer than 2 measurable configs"}
    pred = [pc.predicted_step_s for pc in rows]
    meas = [pc.measured_step_s for pc in rows]
    agreement = rank_agreement(pred, meas)
    pred_best = min(range(len(rows)), key=lambda i: pred[i])
    meas_rank = sorted(range(len(rows)), key=lambda i: meas[i])
    # "within the measured top 2", with a 10% near-tie tolerance at the
    # boundary: min-of-rounds ordering between statistical ties is
    # arbitrary, and a binary acceptance row must not flap on it
    top2_cut = meas[meas_rank[min(1, len(rows) - 1)]] * 1.10
    top1_ok = (pred_best in meas_rank[:2]
               or meas[pred_best] <= top2_cut)
    verdict = {
        "n_configs": len(rows),
        "agreement": round(agreement, 4),
        "top1_is_measured_top2": 1.0 if top1_ok else 0.0,
        "predicted_best": str(rows[pred_best].config),
        "measured_best": str(rows[meas_rank[0]].config),
        "table": [{"config": str(pc.config),
                   "predicted_s": pc.predicted_step_s,
                   "measured_s": pc.measured_step_s} for pc in rows],
    }
    report.validation = verdict
    return verdict
