"""Plan emission: the winning config as concrete GSPMD annotations.

The planner's output is not advice — it is the ``Mesh`` + per-parameter
``NamedSharding``/``PartitionSpec`` table the trainer consumes directly
(the pjit/mesh annotation surface of SNIPPETS.md [1][3]). A
:class:`ShardingPlan` is deliberately a dumb, serializable artifact:
axis sizes, a name→spec table, and the batch spec — so the ranked-table
JSON a planning run persists can be loaded later and applied to a fresh
model on a fresh mesh without re-running the search (the elastic-resume
flow of ROADMAP item 6 re-plans only when the device count changed).

``apply`` places parameters from the PLAN's table, not from live
``Parameter.sharding`` annotations — that indirection is the point:
an emission/pricing divergence (plan says replicate, annotation says
shard) becomes visible as a census mismatch, which the graph_lint
``planner`` budget pins in CI.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["ShardingPlan", "emit_plan", "plan_for_config"]


def _spec_to_json(spec) -> List:
    out = []
    for e in tuple(spec):
        if isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(e)
    return out


def _spec_from_json(entries) -> "PartitionSpec":
    from jax.sharding import PartitionSpec
    fixed = [tuple(e) if isinstance(e, list) else e for e in entries]
    return PartitionSpec(*fixed)


@dataclass
class ShardingPlan:
    """One emitted plan: mesh axis sizes + parameter/batch specs."""
    config_str: str
    axes: Dict[str, int]                     # dp/fsdp/tp/pp/sep sizes
    batch_spec: object                       # PartitionSpec
    param_specs: Dict[str, object]           # name -> PartitionSpec
    sequence_parallel: bool = False
    notes: str = ""

    # -- construction --------------------------------------------------------

    def build_mesh(self, devices=None):
        """The HybridMesh this plan shards over."""
        from ...parallel.mesh import HybridMesh
        import jax
        n = 1
        for v in self.axes.values():
            n *= v
        devices = (list(devices) if devices is not None
                   else list(jax.devices()))[:n]
        # axes records MESH sizes: on an ep mesh the "dp" entry is the
        # already-carved dp/ep, so the build degree is their product
        ep = self.axes.get("ep", 1)
        return HybridMesh.build(
            dp=self.axes.get("dp", 1) * ep,
            fsdp=self.axes.get("fsdp", 1),
            tp=self.axes.get("tp", 1), pp=self.axes.get("pp", 1),
            sep=self.axes.get("sep", 1), ep=ep, devices=devices)

    # -- application ---------------------------------------------------------

    def apply(self, model, mesh=None, devices=None):
        """Place every parameter of ``model`` per the plan table
        (unlisted params replicate), buffers replicated — returns the
        mesh so callers enter it for training. The GSPMD annotation
        surface: ``NamedSharding(mesh, spec)`` per array.

        The plan is keyed by parameter NAME: applying a plan emitted
        for a different model class (a pp winner's pipe-stacked names
        onto a plain model, or vice versa) would match nothing and
        silently replicate everything — that mis-apply raises instead,
        naming both sides."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        hm = mesh if mesh is not None else self.build_mesh(devices)
        m = getattr(hm, "mesh", hm)
        model_names = [name for name, _ in model.named_parameters()]
        if self.param_specs and model_names:
            matched = set(self.param_specs) & set(model_names)
            # emit_plan records EVERY trainable param (empty specs
            # included), so a same-model apply matches ~all names; a
            # minority match means the plan was emitted for a different
            # architecture (a pipe winner onto a plain model matches
            # only the odd shared name like 'lm_head')
            if len(matched) * 2 < len(model_names):
                missing = sorted(set(model_names)
                                 - set(self.param_specs))[:3]
                raise ValueError(
                    f"ShardingPlan({self.config_str}): only "
                    f"{len(matched)}/{len(model_names)} parameters of "
                    f"{type(model).__name__} appear in the plan's name "
                    f"table (e.g. missing {missing}) — the plan was "
                    f"emitted for a different model class/architecture "
                    f"and would silently replicate everything; re-plan "
                    f"for this model instead of applying a mismatched "
                    f"artifact")
        for name, p in model.named_parameters():
            spec = self.param_specs.get(name, PartitionSpec())
            p.value = jax.device_put(p.value, NamedSharding(m, spec))
        for _, b in model.named_buffers():
            b.value = jax.device_put(b.value,
                                     NamedSharding(m, PartitionSpec()))
        return hm

    def shard_batch(self, batch: Dict, mesh=None):
        """Place a training batch per the plan's batch spec."""
        import jax
        from jax.sharding import NamedSharding
        hm = mesh if mesh is not None else self.build_mesh()
        m = getattr(hm, "mesh", hm)
        sh = NamedSharding(m, self.batch_spec)
        return {k: jax.device_put(v, sh) for k, v in batch.items()}

    # -- serialization -------------------------------------------------------

    def as_dict(self) -> Dict:
        return {"config": self.config_str, "axes": dict(self.axes),
                "batch_spec": _spec_to_json(self.batch_spec),
                "sequence_parallel": self.sequence_parallel,
                "param_specs": {k: _spec_to_json(v)
                                for k, v in self.param_specs.items()},
                "notes": self.notes}

    @staticmethod
    def from_dict(d: Dict) -> "ShardingPlan":
        return ShardingPlan(
            config_str=d["config"], axes=dict(d["axes"]),
            batch_spec=_spec_from_json(d["batch_spec"]),
            param_specs={k: _spec_from_json(v)
                         for k, v in d["param_specs"].items()},
            sequence_parallel=bool(d.get("sequence_parallel", False)),
            notes=d.get("notes", ""))


def emit_plan(model, mesh, config) -> ShardingPlan:
    """Freeze ``model``'s per-parameter placements on ``mesh`` into a
    plan artifact. Uses the same ``param_spec_tree``/``_clean_spec``
    definition the runtime sharding path uses — emission and execution
    cannot disagree about what a spec means."""
    from jax.sharding import PartitionSpec
    from ...parallel.api import param_spec_tree, _clean_spec
    m = getattr(mesh, "mesh", mesh)
    axes = {name: int(m.shape[name]) for name in m.axis_names}
    # the batch spans the full data submesh; _clean_spec drops "ep" on
    # ep==1 meshes so pre-EP plan artifacts stay byte-identical
    batch_spec = _clean_spec([("dp", "ep", "fsdp"), None], m)
    return ShardingPlan(
        config_str=str(config),
        axes=axes,
        batch_spec=batch_spec,
        param_specs=param_spec_tree(model, mesh=m),
        sequence_parallel=bool(getattr(config, "sep", 1) > 1),
        notes=f"emitted for {config}")


def plan_for_config(model_cfg, config, devices=None) -> ShardingPlan:
    """Emit the plan for ``config`` WITHOUT pricing: build the model's
    annotation surface (no placement, no compile) and freeze its specs on
    the config's mesh. Used where the winner is already known — the
    elastic resume path re-applying a chosen config, the reshard CLI —
    and only the spec table is needed."""
    import dataclasses
    import jax
    from ...models import (LlamaForCausalLM, LlamaForCausalLMPipe,
                           MoEForCausalLM)
    from ...parallel.mesh import HybridMesh
    import paddle_tpu as pt
    sep = int(getattr(config, "sep", 1))
    is_moe = bool(getattr(model_cfg, "num_experts", 0))
    if is_moe:
        mcfg = model_cfg
    else:
        mcfg = dataclasses.replace(model_cfg, sequence_parallel=sep > 1)
    pt.seed(0)
    if int(getattr(config, "pp", 1)) > 1:
        model = LlamaForCausalLMPipe(mcfg, num_stages=int(config.pp),
                                     num_microbatches=2)
    elif is_moe:
        model = MoEForCausalLM(mcfg)
    else:
        model = LlamaForCausalLM(mcfg)
    devices = (list(devices) if devices is not None
               else list(jax.devices()))[:config.size]
    hm = HybridMesh.build(dp=int(config.dp),
                          fsdp=int(getattr(config, "fsdp", 1)),
                          tp=int(config.tp),
                          pp=int(getattr(config, "pp", 1)), sep=sep,
                          ep=int(getattr(config, "ep", 1)),
                          devices=devices)
    return emit_plan(model, hm, config)
