"""Per-config HBM model: the planner's feasibility gate.

Before a candidate ``(dp, fsdp, tp, pp, sep)`` config is worth a
compile, it must FIT — params + optimizer state + gradients +
activations under the model's remat policy, per chip. This module
prices that closed-form from the ``LlamaConfig`` alone (no
instantiation: pruning runs BEFORE the per-config compile the planner
pays for survivors only).

Conventions, and why each term looks the way it does:

* **params** — analytical count from the config (embedding + L decoder
  layers + final norm + untied lm_head), divided by ``fsdp * tp * pp``:
  tensor parallelism shards every projection along exactly one axis and
  fsdp (ZeRO-3, ISSUE 18) shards the hidden dimension of the same
  matrices (models/llama.py ``sharding=("fsdp","tp")`` annotations);
  the pipe model stacks layers over ``pp``. Norm vectors are replicated
  over tp/fsdp but are O(H) — lost in the noise, deliberately not
  special-cased.
* **optimizer state** — slot count × fp32 per sharded param (AdamW: m+u,
  ``optimizer.py _init_slots``), sharded like the params
  (``shard_optimizer_state`` places slots with the param's spec) — this
  is the ZeRO lever: fsdp divides the 4-byte slots that dominate
  large-model footprints.
* **gradients** — one param-dtype copy sharded like the params (XLA
  reduce-scatters into the fsdp-sharded layout); donation keeps only
  one live generation, which is what the train-step budget pins.
* **fsdp gather working set** — with ``fsdp > 1`` the compute of one
  layer needs that layer's params all-gathered over the axis (still
  tp/pp-sharded): one per-layer param block at full fsdp width is
  transiently live. Without it the model would claim a 1-chip fsdp=64
  config stores 1/64th of everything and never pays for the gathered
  operand XLA actually materializes.
* **activations** — boundary activations per layer are
  ``B/(dp·fsdp) × S/sep × H`` (batch sharded over the ``("dp","fsdp")``
  spec, sequence over sep — the ``_seq_shard`` constraint); with remat
  "full" only boundaries survive the forward plus one layer's recompute
  working set, without remat every layer keeps its internal
  intermediates (qkv + attn out + the two MLP halves ≈ ``4H + 2M`` per
  token). The fused CE head (PR 5) means NO ``B×S×V`` logits term — the
  planner would otherwise veto every config on vocab-heavy models for a
  buffer the runtime never materializes.

The capacity table lives here (device_db carries bandwidths, not sizes)
with the same public-spec sourcing discipline and a CPU tier so the
planner is testable on smoke hosts. ``utilization`` headroom (default
90%) covers XLA's workspace + fragmentation — same convention as the
reference's memory estimater (auto_parallel cost model).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

__all__ = ["HBM_CAPACITY", "MemoryEstimate", "estimate_hbm",
           "hbm_capacity"]

# bytes per chip (cloud.google.com/tpu/docs per-generation spec sheets;
# same sources as observability/costs/device_db.py bandwidth tables)
HBM_CAPACITY = {
    "tpu v4": 32e9,          # 32 GiB
    "tpu v5 lite": 16e9,     # v5e: 16 GiB
    "tpu v5e": 16e9,
    "tpu v5": 95e9,          # v5p: 95 GiB
    "tpu v5p": 95e9,
    "tpu v6 lite": 32e9,     # v6e (trillium): 32 GiB
    "cpu": 8e9,              # nominal smoke-host tier
}

_DTYPE_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4, "float64": 8}


def hbm_capacity(kind: Optional[str] = None) -> float:
    """Capacity for ``kind`` (defaults to the current device), longest-
    substring matched like every device_db lookup."""
    if kind is None:
        from ...observability.costs import current_device_kind
        kind = current_device_kind()
    kind = kind.lower()
    best, best_len = HBM_CAPACITY["cpu"], -1
    for k, v in HBM_CAPACITY.items():
        if k in kind and len(k) > best_len:
            best, best_len = v, len(k)
    return best


@dataclass
class MemoryEstimate:
    """Per-chip high-water estimate for one parallel config."""
    params_bytes: float
    opt_bytes: float
    grads_bytes: float
    acts_bytes: float
    budget_bytes: float
    feasible: bool
    detail: Dict[str, float] = field(default_factory=dict)

    @property
    def total_bytes(self) -> float:
        return (self.params_bytes + self.opt_bytes + self.grads_bytes
                + self.acts_bytes)

    def as_dict(self) -> Dict[str, float]:
        return {"params_bytes": self.params_bytes,
                "opt_bytes": self.opt_bytes,
                "grads_bytes": self.grads_bytes,
                "acts_bytes": self.acts_bytes,
                "total_bytes": self.total_bytes,
                "budget_bytes": self.budget_bytes,
                "feasible": self.feasible}


def _param_count(cfg) -> float:
    """Analytical parameter count of a LlamaConfig- or MoEConfig-shaped
    model (matches ``LlamaForCausalLM.num_params`` to the norm vectors;
    for MoE configs the routed/shared expert FFNs replace the dense MLP
    on the non-dense layers)."""
    H, M, L, V = (cfg.hidden_size, cfg.intermediate_size,
                  cfg.num_hidden_layers, cfg.vocab_size)
    hd = H // cfg.num_attention_heads
    qkv = H * (cfg.num_attention_heads + 2 * cfg.num_key_value_heads) * hd
    attn_norms = qkv + H * H + 2 * H                  # attn + norms
    n_exp = int(getattr(cfg, "num_experts", 0) or 0)
    if n_exp:
        k_dense = int(getattr(cfg, "first_k_dense_replace", 0))
        shared_w = (int(getattr(cfg, "num_shared_experts", 0))
                    * cfg.moe_intermediate_size)
        per_moe = (n_exp * 3 * H * cfg.moe_intermediate_size
                   + 3 * H * shared_w + H * n_exp)    # experts+shared+gate
        n = (V * H + H * V + H                        # embed + head + norm
             + k_dense * (attn_norms + 3 * H * M)
             + (L - k_dense) * (attn_norms + per_moe))
        return float(n)
    per_layer = attn_norms + 3 * H * M                # attn + mlp + norms
    n = V * H + L * per_layer + H                     # embed + layers + norm
    if not getattr(cfg, "tie_word_embeddings", True):
        n += H * V
    return float(n)


def _expert_param_count(cfg) -> float:
    """ROUTED expert FFN params only — the slice the ep axis divides
    (shared experts and the router gate replicate over ep)."""
    n_exp = int(getattr(cfg, "num_experts", 0) or 0)
    if not n_exp:
        return 0.0
    L = cfg.num_hidden_layers
    k_dense = int(getattr(cfg, "first_k_dense_replace", 0))
    per_expert = 3 * cfg.hidden_size * cfg.moe_intermediate_size
    return float((L - k_dense) * n_exp * per_expert)


def estimate_hbm(model_cfg, config, *, global_batch: int, seq_len: int,
                 opt_slots: int = 2, budget_bytes: Optional[float] = None,
                 device_kind: Optional[str] = None,
                 utilization: float = 0.9) -> MemoryEstimate:
    """Price one config's per-chip HBM high-water.

    ``config`` carries ``dp/fsdp/tp/pp/sep`` degrees (a planner
    ``ParallelConfig`` or anything duck-shaped like one; ``fsdp``
    defaults to 1 for pre-ISSUE-18 duck shapes). ``opt_slots`` is the
    optimizer's fp32 slot count per param (AdamW m+u = 2).
    ``budget_bytes`` overrides the device capacity lookup — the
    HBM-infeasibility tests pin tiny budgets through it.
    """
    dp, tp, pp, sep = config.dp, config.tp, config.pp, config.sep
    fsdp = int(getattr(config, "fsdp", 1))
    ep = int(getattr(config, "ep", 1))
    dt = _DTYPE_BYTES.get(getattr(model_cfg, "dtype", "float32"), 4)
    H, M, L = (model_cfg.hidden_size, model_cfg.intermediate_size,
               model_cfg.num_hidden_layers)

    shard = float(fsdp * tp * pp)
    # expert FFN params/slots/grads additionally divide by ep (each ep
    # rank stores only its expert slice); everything else replicates
    # over the ep subgroup exactly like plain dp
    total_p = _param_count(model_cfg)
    expert_p = _expert_param_count(model_cfg) if ep > 1 else 0.0
    dense_p = total_p - expert_p
    params_b = (dense_p + expert_p / ep) * dt / shard
    opt_b = (dense_p + expert_p / ep) * 4.0 * opt_slots / shard
    grads_b = params_b

    tokens_local = (global_batch / (dp * fsdp)) * (seq_len / sep)
    boundary = tokens_local * H * dt                  # one layer boundary
    remat = getattr(model_cfg, "recompute", "none") in ("full", "selective")
    layers_local = L / pp
    if remat:
        # boundaries survive the forward; one layer re-runs at a time
        acts_b = layers_local * boundary + (4 * H + 2 * M) / H * boundary
    else:
        # every layer keeps qkv/attn-out/gate/up intermediates
        acts_b = layers_local * (boundary + (4 * H + 2 * M) / H * boundary)

    # fsdp gather working set: one decoder layer's params all-gathered
    # over the axis for compute (still tp-sharded; counted inside
    # acts_bytes because it is transient, not storage)
    gather_b = 0.0
    if fsdp > 1:
        hd = H // model_cfg.num_attention_heads
        qkv = H * (model_cfg.num_attention_heads
                   + 2 * model_cfg.num_key_value_heads) * hd
        per_layer = qkv + H * H + 3 * H * M
        gather_b = per_layer * dt / float(tp)
        acts_b += gather_b

    # expert a2a staging: dispatch + combine each materialize the
    # routed slot buffer (tokens_local × top_k × H) once per moe layer's
    # in-flight window — one layer at a time, so a single ×2 copy
    a2a_b = 0.0
    if ep > 1:
        top_k = int(getattr(model_cfg, "num_experts_per_tok", 1))
        a2a_b = 2.0 * tokens_local * top_k * H * dt
        acts_b += a2a_b

    budget = budget_bytes if budget_bytes is not None else \
        hbm_capacity(device_kind) * utilization
    total = params_b + opt_b + grads_b + acts_b
    return MemoryEstimate(
        params_bytes=params_b, opt_bytes=opt_b, grads_bytes=grads_b,
        acts_bytes=acts_b, budget_bytes=float(budget),
        feasible=total <= budget,
        detail={"tokens_local": tokens_local,
                "layers_local": layers_local, "dtype_bytes": dt,
                "fsdp_gather_bytes": gather_b,
                "expert_params_bytes": expert_p * dt / (shard * ep),
                "moe_a2a_staging_bytes": a2a_b})
