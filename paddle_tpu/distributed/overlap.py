"""Comm/compute overlap controls.

Reference analogues:
- ``mp_async_allreduce`` (fleet/layers/mpu/mp_layers.py:458-477): overlap
  the TP backward input-grad allreduce with the weight-grad matmul.
- ``allreduce_matmul_grad_overlapping``
  (distributed/passes/allreduce_matmul_grad_overlapping.py): split matmul_grad
  so the dx allreduce overlaps the dW matmul.
- sharding comm overlap (dygraph_sharding_optimizer.py:470): overlap grad
  reduce-scatter with backward compute.

TPU redesign: the reference needs these passes because torch/paddle eager
autograd executes ops in strict sequence on one stream. Under XLA the
dataflow graph ALREADY contains the independence (dx's all-reduce and the
dW dot share no edge — verify with :func:`backward_overlap_independent`),
and the TPU compiler's latency-hiding scheduler turns that independence
into actual overlap when async collectives are enabled. So the knobs here
map to (a) XLA scheduler flags, applied process-wide before backend init,
and (b) analysis helpers that PROVE the overlap precondition on compiled
HLO — the moral equivalent of the reference's pass unit tests.

GSPMD also already emits the overlap-friendly grad-sync structure for
gradient accumulation: the dp/fsdp all-reduce sits INSIDE the microbatch
loop body (one per microbatch, overlappable with the next microbatch's
compute) rather than one deferred sync — check with
:func:`collectives_in_loop`.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Optional, Tuple

import jax

# The TPU async-collective + latency-hiding-scheduler set. These are the
# production XLA knobs that let the scheduler hide collective latency
# behind independent compute (the effect the reference's overlap passes
# hand-implement). Safe to set on CPU (unknown flags are rejected loudly at
# backend init, so we only add them when the target is TPU).
OVERLAP_XLA_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true "
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true "
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true "
    "--xla_tpu_overlap_compute_collective_tc=true "
    "--xla_enable_async_all_gather=true "
    "--xla_enable_async_collective_permute=true "
)

# process-lifetime memo of vet verdicts (ISSUE 14 satellite): a Trainer
# is constructed per experiment but the flag set an XLA build accepts
# cannot change within one process — never probe the same set twice
_VET_MEMO: Dict[str, List[str]] = {}
_WARNED: set = set()


def _warn_once(key: str, msg: str) -> None:
    """stderr warning emitted at most once per process per key — the
    overlap policy is consulted per Trainer construction and per
    compile, and a repeated warning is noise, not information."""
    if key not in _WARNED:
        _WARNED.add(key)
        sys.stderr.write(msg)


def validate_xla_flags(candidates: List[str], *, cwd: Optional[str] = None,
                       timeout: Optional[float] = None) -> List[str]:
    """Return the subset of ``candidates`` this XLA build accepts.

    XLA FATALLY ABORTS the whole process on any unrecognized flag in
    XLA_FLAGS (parse_flags_from_env.cc) — observed live on the axon/libtpu
    build, which rejects the whole async-collective set. So candidates are
    vetted in a killable probe subprocess first: the child's abort message
    names the offending flags, those are dropped, and the remainder is
    re-vetted (the build may reject several in sequence). A hang or any
    non-flag failure vets conservatively to [] — no flag is worth wedging
    the bench — but such transient outcomes are NOT cached; only a
    definitive verdict (probe succeeded, or the unknown-flag refinement
    converged) is persisted per jax/plugin-build under build/ so repeat
    runs skip the extra backend inits."""
    import json as _json

    if not candidates:
        return []
    if timeout is None:
        timeout = float(os.environ.get("PT_FLAG_VET_TIMEOUT", "240"))
    fp = _xla_build_fingerprint()
    cacheable = "plugin-meta-unavailable" not in fp
    key = fp + "|" + " ".join(sorted(candidates))
    if key in _VET_MEMO:
        return [c for c in candidates if c in _VET_MEMO[key]]
    # repo root, shared by the cache file and the probe child's PYTHONPATH
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cache_path = os.path.join(pkg_root, "build", "xla_flag_cache.json")
    cache = {}
    if cacheable:
        try:
            with open(cache_path) as f:
                cache = _json.load(f)
            if key in cache:
                _VET_MEMO[key] = list(cache[key])
                return [c for c in candidates if c in cache[key]]
        except Exception:
            cache = {}

    from paddle_tpu.utils.hw_probe import _one_probe
    base = os.environ.get("XLA_FLAGS", "")
    # pkg_root also goes on the probe child's PYTHONPATH: the child must
    # find paddle_tpu regardless of the caller's cwd (library users run
    # from anywhere; only bench.py happens to sit next to the package)
    live = list(candidates)
    definitive = True
    for _ in range(len(candidates)):
        if not live:
            break
        env = dict(os.environ)
        env["XLA_FLAGS"] = (base + " " + " ".join(live)).strip()
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        ok, msg = _one_probe(timeout, cwd or pkg_root, env=env)
        if ok:
            break
        if msg.startswith("UNKNOWN_XLA_FLAGS"):
            bad = set(msg.split()[1:])
            nxt = [c for c in live if c.split("=")[0] not in bad]
            if len(nxt) == len(live):
                # abort names only flags outside our set — the user's own
                # XLA_FLAGS are bad; nothing we drop can fix that
                sys.stderr.write(
                    f"paddle_tpu.overlap: XLA rejects flags not from the "
                    f"overlap set ({sorted(bad)}) — fix XLA_FLAGS; applying "
                    f"no overlap flags\n")
                live = []
                definitive = False
                break
            live = nxt
            continue
        sys.stderr.write(f"paddle_tpu.overlap: flag vetting probe failed "
                         f"({msg[:200]}); applying no overlap flags\n")
        live = []
        definitive = False  # hang/TPU-busy/import error: retry next run
        break
    # definitive verdicts memoize for the process lifetime (the build's
    # accepted flag set cannot change underneath a running process) and,
    # when the build is identifiable, persist to disk; transient
    # failures (hang, TPU busy) stay uncached so a later call/run retries
    if definitive:
        _VET_MEMO[key] = list(live)
        if cacheable:
            try:
                os.makedirs(os.path.dirname(cache_path), exist_ok=True)
                cache[key] = live
                with open(cache_path, "w") as f:
                    _json.dump(cache, f, indent=1)
            except Exception:
                pass
    return live


def _xla_build_fingerprint() -> str:
    """Cache key for flag-support vetting: the flag parser lives in the
    PJRT plugin (libtpu/axon), not in jax — include every installed
    dist that looks like a TPU/PJRT plugin so a plugin upgrade without a
    jax version bump invalidates the cache."""
    import jax as _jax
    parts = [f"jax{_jax.__version__}",
             os.environ.get("JAX_PLATFORMS", "")]
    try:
        import importlib.metadata as _md
        plug = []
        for d in _md.distributions():
            try:
                # d.metadata can be None for orphaned/partial dist-info
                # dirs (interrupted pip uninstall) — skip those, don't
                # abandon the whole fingerprint
                name = d.metadata["Name"] if d.metadata else None
            except Exception:
                continue
            if name and any(t in name.lower()
                            for t in ("libtpu", "axon", "pjrt", "jaxlib")):
                plug.append(f"{name}{d.version}")
        parts.extend(sorted(plug))
    except Exception:
        # plugin versions unknowable -> the key cannot prove build
        # identity, so mark it uncacheable rather than risk serving a
        # stale "accepted" verdict to a different plugin build (which
        # would reintroduce the fatal abort this machinery prevents)
        parts.append("plugin-meta-unavailable")
    return "|".join(parts)


def apply_overlap_flags(enable: bool = True, *, target: str = "tpu",
                        validate: bool = False, cwd: Optional[str] = None,
                        validate_timeout: Optional[float] = None) -> str:
    """Install the overlap scheduler flags into XLA_FLAGS (idempotent).

    Must run BEFORE jax backend initialization — flags set after the
    backend is live are ignored, in which case this warns and returns the
    current value unchanged. ``PT_NO_OVERLAP=1`` forces them off (the A/B
    lever for measuring the overlap win on hardware). ``validate=True``
    vets each flag against the installed XLA in a subprocess first
    (required on real hardware: unknown flags are a process-fatal error,
    see :func:`validate_xla_flags`)."""
    if os.environ.get("PT_NO_OVERLAP"):
        enable = False
    cur = os.environ.get("XLA_FLAGS", "")
    if not enable or target != "tpu":
        return cur
    # match by EXACT flag name so an explicit user "=false" is respected
    # and a longer flag name doesn't shadow a shorter one's install
    cur_names = {tok.split("=")[0] for tok in cur.split()}
    missing = [f for f in OVERLAP_XLA_FLAGS.split()
               if f.split("=")[0] not in cur_names]
    if not missing:
        return cur
    try:
        initialized = jax._src.xla_bridge._backends  # noqa: SLF001
    except AttributeError:
        initialized = {}
    if initialized:
        # checked BEFORE validate: vetting spawns multi-minute backend-init
        # subprocesses, pointless when flags can no longer be applied
        _warn_once(
            "backend-initialized",
            "paddle_tpu.overlap: backend already initialized; XLA overlap "
            "flags NOT applied (set strategy before first jax use)\n")
        return cur
    if validate:
        missing = validate_xla_flags(missing, cwd=cwd,
                                     timeout=validate_timeout)
        if not missing:
            return cur
    new = (cur + " " + " ".join(missing)).strip()
    os.environ["XLA_FLAGS"] = new
    return new


def overlap_fingerprint() -> str:
    """The overlap-relevant environment state as a stable string: which
    OVERLAP_XLA_FLAGS names are present in XLA_FLAGS (with their values,
    so an explicit ``=false`` differs from installed) plus the
    PT_NO_OVERLAP A/B lever. ``Trainer._fp_parts`` folds this into the
    compile-cache fingerprint so a flag flip between runs can never hit
    a stale AOT executable compiled under the other schedule."""
    ours = {f.split("=")[0] for f in OVERLAP_XLA_FLAGS.split()}
    toks = sorted(t for t in os.environ.get("XLA_FLAGS", "").split()
                  if t.split("=")[0] in ours)
    no = "PT_NO_OVERLAP;" if os.environ.get("PT_NO_OVERLAP") else ""
    return no + " ".join(toks)


def enable_overlap(enable: bool = True, *, target: Optional[str] = None,
                   validate: Optional[bool] = None,
                   cwd: Optional[str] = None,
                   timeout: Optional[float] = None) -> Dict[str, object]:
    """THE applied overlap policy (ISSUE 14): validate and install the
    async-collective / latency-hiding flag set before backend init.

    * strict no-op when off — ``enable=False`` or ``PT_NO_OVERLAP=1``
      touches nothing and says so in the returned ``reason``;
    * TPU-only — ``target`` defaults to :func:`_detect_target`; on a CPU
      target the flags would make backend init fatal, so nothing is
      installed;
    * vetted — on TPU targets ``validate`` defaults to True (unknown
      flags abort the process; see :func:`validate_xla_flags`, whose
      verdict is memoized for the process lifetime);
    * warn-once — an unsupported libtpu build (vet drops flags) warns a
      single time per process, not per Trainer construction.

    Returns ``{"enabled", "applied", "reason", "xla_flags",
    "fingerprint"}``; ``fingerprint`` is :func:`overlap_fingerprint`
    AFTER the install, the value trainers fold into the compile cache.
    """
    cur = os.environ.get("XLA_FLAGS", "")
    if target is None:
        target = _detect_target()
    if os.environ.get("PT_NO_OVERLAP"):
        reason = "PT_NO_OVERLAP"
    elif not enable:
        reason = "disabled"
    elif target != "tpu":
        reason = f"target={target}"
    else:
        reason = ""
    if reason:
        return {"enabled": False, "applied": [], "reason": reason,
                "xla_flags": cur, "fingerprint": overlap_fingerprint()}
    if validate is None:
        validate = True
    try:
        initialized = bool(jax._src.xla_bridge._backends)  # noqa: SLF001
    except AttributeError:
        initialized = False
    new = apply_overlap_flags(True, target=target, validate=validate,
                              cwd=cwd, validate_timeout=timeout)
    after = {t.split("=")[0] for t in new.split()}
    wanted = [f.split("=")[0] for f in OVERLAP_XLA_FLAGS.split()]
    applied = [n for n in wanted if n in after]
    missing = [n for n in wanted if n not in after]
    if initialized and missing:
        reason = "backend-initialized"  # apply_overlap_flags warned once
    elif missing:
        reason = "partial" if applied else "no-flags-accepted"
        _warn_once(
            "unsupported:" + ",".join(missing),
            f"paddle_tpu.overlap: this XLA/libtpu build rejects "
            f"{len(missing)}/{len(wanted)} overlap flag(s) "
            f"({', '.join(missing)}); continuing without them\n")
    else:
        reason = "applied"
    return {"enabled": bool(applied), "applied": applied, "reason": reason,
            "xla_flags": new, "fingerprint": overlap_fingerprint()}


# ---------------------------------------------------------------------------
# HLO analysis: prove the overlap preconditions on the compiled program
# ---------------------------------------------------------------------------

_INSTR_LHS = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*")
# opcode = first word directly followed by '(' after the (possibly tuple)
# result type — tuple types like "(s32[], f32[4])" never match word-paren
_OPCODE = re.compile(r"\s([a-z][\w\-]*)\(")
_OPND = re.compile(r"%([\w.\-]+)")
# computation header: "%name (params...) -> type {" or "ENTRY %name (...) {"
_COMP_HDR = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s+\(.*\{")
_COMP_REF_ONE = re.compile(
    r"(?:calls|body|condition|to_apply)=%?([\w.\-]+)")
_COMP_REF_LIST = re.compile(
    r"(?:calls|branch_computations)=\{([^}]*)\}")
_COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                   "collective-permute", "all-to-all")
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|reduce-scatter|all-gather|collective-permute|"
    r"all-to-all)(-start|-done)?\(")


def _parse_hlo(txt: str):
    """Returns (graph, comp_of, comp_members): instruction dataflow plus
    computation membership. Instructions that reference a computation
    (while body, fusion calls, conditional branches) get dependency edges
    to EVERY instruction of that computation — a conservative
    over-approximation that keeps independence claims sound."""
    graph: Dict[str, Tuple[str, List[str]]] = {}
    comp_of: Dict[str, str] = {}
    comp_members: Dict[str, List[str]] = {}
    cur = None
    for line in txt.splitlines():
        h = _COMP_HDR.match(line.strip())
        if h and "=" not in line.split("(")[0]:
            cur = h.group(1)
            comp_members.setdefault(cur, [])
        m = _INSTR_LHS.match(line)
        if not m:
            continue
        name = m.group(1)
        rhs = line.split("=", 1)[1]
        mo = _OPCODE.search(" " + rhs)
        if not mo:
            continue
        op = mo.group(1)
        opnds = [o for o in _OPND.findall(rhs) if o != name]
        # computation references become dependencies on the whole callee
        refs = list(_COMP_REF_ONE.findall(rhs))
        for r in _COMP_REF_LIST.findall(rhs):
            refs.extend(p.strip().lstrip("%") for p in r.split(","))
        graph[name] = (op, opnds + [f"comp:{r}" for r in refs if r])
        if cur is not None:
            comp_of[name] = cur
            comp_members[cur].append(name)
    return graph, comp_of, comp_members


def _ancestors(graph, comp_members, name):
    seen = set()
    todo = list(graph.get(name, ("", []))[1])
    while todo:
        n = todo.pop()
        if n.startswith("comp:"):
            for member in comp_members.get(n[5:], ()):
                if member not in seen:
                    seen.add(member)
                    todo.extend(graph.get(member, ("", []))[1])
            continue
        if n in seen or n not in graph:
            continue
        seen.add(n)
        todo.extend(graph[n][1])
    return seen


def backward_overlap_independent(compiled_text: str) -> bool:
    """True if some collective and some dot are mutually independent in the
    HLO — the precondition for the latency-hiding scheduler to overlap the
    TP backward allreduce with the weight-grad matmul
    (reference mp_async_allreduce's effect)."""
    g, _, members = _parse_hlo(compiled_text)
    colls = [n for n, (op, _) in g.items()
             if op.replace("-start", "").replace("-done", "")
             in _COLLECTIVE_OPS]
    dots = [n for n, (op, _) in g.items()
            if op in ("dot", "convolution") or "dot" in op]
    for c in colls:
        anc_c = _ancestors(g, members, c)
        for d in dots:
            if d in anc_c:
                continue
            if c in _ancestors(g, members, d):
                continue
            return True
    return False


def collectives_in_loop(compiled_text: str) -> Tuple[int, int]:
    """(total collectives, collectives inside while bodies), counting the
    async -start forms too. A collective inside the microbatch loop body
    syncs per microbatch — the structure that overlaps grad comm with the
    next microbatch's compute."""
    total = 0
    in_body = 0
    body_names = set(re.findall(r"body=%?([\w.\-]+)", compiled_text))
    cur = None
    for line in compiled_text.splitlines():
        h = _COMP_HDR.match(line.strip())
        if h and "=" not in line.split("(")[0]:
            cur = h.group(1)
        if _COLLECTIVE_RE.search(line) and "=" in line:
            if "-done(" in line:
                continue          # count start/done pairs once
            total += 1
            if cur in body_names:
                in_body += 1
    return total, in_body


def strategy_overlap_summary(strategy) -> Dict[str, bool]:
    """Which reference overlap knobs the strategy requests. Unknown knobs
    land in strategy.extras; the three reference names are honored."""
    tp_cfg = getattr(strategy, "tensor_parallel", None)
    sh_cfg = getattr(strategy, "sharding", None)
    extras = getattr(strategy, "extras", {}) or {}
    return {
        "mp_async_allreduce": bool(
            getattr(tp_cfg, "mp_async_allreduce", False)
            or extras.get("mp_async_allreduce")),
        "allreduce_matmul_grad_overlapping": bool(
            extras.get("allreduce_matmul_grad_overlapping")),
        "sharding_comm_overlap": bool(
            getattr(sh_cfg, "comm_overlap", False)
            or extras.get("comm_overlap")),
    }


def apply_strategy_overlap(strategy, *, target: Optional[str] = None) -> str:
    """Map the reference overlap knobs to the XLA scheduler flags. Any one
    of them on → async collectives + latency hiding on (they are one
    mechanism under XLA)."""
    summary = strategy_overlap_summary(strategy)
    if target is None:
        target = _detect_target()
    if any(summary.values()):
        # vet on real hardware: unknown flags abort the process at init.
        # Short default timeout on this path — fleet.init must not stall
        # minutes behind a wedged tunnel; a vet timeout just means no
        # overlap flags this run (bench.py keeps the long default)
        return apply_overlap_flags(
            True, target=target, validate=(target == "tpu"),
            validate_timeout=float(
                os.environ.get("PT_FLAG_VET_TIMEOUT", "60")))
    return os.environ.get("XLA_FLAGS", "")


def _config_platforms() -> str:
    try:
        return jax.config.jax_platforms or ""
    except AttributeError:
        return ""


def _detect_target() -> str:
    """'tpu' only when the process is actually headed for a TPU backend —
    the flags are TPU-compiler-only and make a CPU backend init fatal."""
    if os.environ.get("PT_BENCH_FORCE_CPU"):
        return "cpu"
    jp = _config_platforms() or os.environ.get("JAX_PLATFORMS", "")
    # unknown/auto platform -> 'cpu': installing TPU-only flags on a
    # non-TPU backend is fatal at init, so only opt in on clear evidence
    return "tpu" if ("tpu" in jp or "axon" in jp) else "cpu"


__all__ = ["OVERLAP_XLA_FLAGS", "enable_overlap", "overlap_fingerprint",
           "apply_overlap_flags", "validate_xla_flags",
           "backward_overlap_independent", "collectives_in_loop",
           "strategy_overlap_summary", "apply_strategy_overlap"]
