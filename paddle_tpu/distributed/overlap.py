"""Comm/compute overlap controls.

Reference analogues:
- ``mp_async_allreduce`` (fleet/layers/mpu/mp_layers.py:458-477): overlap
  the TP backward input-grad allreduce with the weight-grad matmul.
- ``allreduce_matmul_grad_overlapping``
  (distributed/passes/allreduce_matmul_grad_overlapping.py): split matmul_grad
  so the dx allreduce overlaps the dW matmul.
- sharding comm overlap (dygraph_sharding_optimizer.py:470): overlap grad
  reduce-scatter with backward compute.

TPU redesign: the reference needs these passes because torch/paddle eager
autograd executes ops in strict sequence on one stream. Under XLA the
dataflow graph ALREADY contains the independence (dx's all-reduce and the
dW dot share no edge — verify with :func:`backward_overlap_independent`),
and the TPU compiler's latency-hiding scheduler turns that independence
into actual overlap when async collectives are enabled. So the knobs here
map to (a) XLA scheduler flags, applied process-wide before backend init,
and (b) analysis helpers that PROVE the overlap precondition on compiled
HLO — the moral equivalent of the reference's pass unit tests.

GSPMD also already emits the overlap-friendly grad-sync structure for
gradient accumulation: the dp/fsdp all-reduce sits INSIDE the microbatch
loop body (one per microbatch, overlappable with the next microbatch's
compute) rather than one deferred sync — check with
:func:`collectives_in_loop`.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Optional, Tuple

import jax

# The TPU async-collective + latency-hiding-scheduler set. These are the
# production XLA knobs that let the scheduler hide collective latency
# behind independent compute (the effect the reference's overlap passes
# hand-implement). Safe to set on CPU (unknown flags are rejected loudly at
# backend init, so we only add them when the target is TPU).
OVERLAP_XLA_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true "
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true "
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true "
    "--xla_tpu_overlap_compute_collective_tc=true "
    "--xla_enable_async_all_gather=true "
    "--xla_enable_async_collective_permute=true "
)


def validate_xla_flags(candidates: List[str], *, cwd: Optional[str] = None,
                       timeout: Optional[float] = None) -> List[str]:
    """Return the subset of ``candidates`` this XLA build accepts.

    XLA FATALLY ABORTS the whole process on any unrecognized flag in
    XLA_FLAGS (parse_flags_from_env.cc) — observed live on the axon/libtpu
    build, which rejects the whole async-collective set. So candidates are
    vetted in a killable probe subprocess first: the child's abort message
    names the offending flags, those are dropped, and the remainder is
    re-vetted (the build may reject several in sequence). A hang or any
    non-flag failure vets conservatively to [] — no flag is worth wedging
    the bench — but such transient outcomes are NOT cached; only a
    definitive verdict (probe succeeded, or the unknown-flag refinement
    converged) is persisted per jax/plugin-build under build/ so repeat
    runs skip the extra backend inits."""
    import json as _json

    if not candidates:
        return []
    if timeout is None:
        timeout = float(os.environ.get("PT_FLAG_VET_TIMEOUT", "240"))
    fp = _xla_build_fingerprint()
    cacheable = "plugin-meta-unavailable" not in fp
    key = fp + "|" + " ".join(sorted(candidates))
    # repo root, shared by the cache file and the probe child's PYTHONPATH
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cache_path = os.path.join(pkg_root, "build", "xla_flag_cache.json")
    cache = {}
    if cacheable:
        try:
            with open(cache_path) as f:
                cache = _json.load(f)
            if key in cache:
                return [c for c in candidates if c in cache[key]]
        except Exception:
            cache = {}

    from paddle_tpu.utils.hw_probe import _one_probe
    base = os.environ.get("XLA_FLAGS", "")
    # pkg_root also goes on the probe child's PYTHONPATH: the child must
    # find paddle_tpu regardless of the caller's cwd (library users run
    # from anywhere; only bench.py happens to sit next to the package)
    live = list(candidates)
    definitive = True
    for _ in range(len(candidates)):
        if not live:
            break
        env = dict(os.environ)
        env["XLA_FLAGS"] = (base + " " + " ".join(live)).strip()
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        ok, msg = _one_probe(timeout, cwd or pkg_root, env=env)
        if ok:
            break
        if msg.startswith("UNKNOWN_XLA_FLAGS"):
            bad = set(msg.split()[1:])
            nxt = [c for c in live if c.split("=")[0] not in bad]
            if len(nxt) == len(live):
                # abort names only flags outside our set — the user's own
                # XLA_FLAGS are bad; nothing we drop can fix that
                sys.stderr.write(
                    f"paddle_tpu.overlap: XLA rejects flags not from the "
                    f"overlap set ({sorted(bad)}) — fix XLA_FLAGS; applying "
                    f"no overlap flags\n")
                live = []
                definitive = False
                break
            live = nxt
            continue
        sys.stderr.write(f"paddle_tpu.overlap: flag vetting probe failed "
                         f"({msg[:200]}); applying no overlap flags\n")
        live = []
        definitive = False  # hang/TPU-busy/import error: retry next run
        break
    if definitive and cacheable:
        try:
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            cache[key] = live
            with open(cache_path, "w") as f:
                _json.dump(cache, f, indent=1)
        except Exception:
            pass
    return live


def _xla_build_fingerprint() -> str:
    """Cache key for flag-support vetting: the flag parser lives in the
    PJRT plugin (libtpu/axon), not in jax — include every installed
    dist that looks like a TPU/PJRT plugin so a plugin upgrade without a
    jax version bump invalidates the cache."""
    import jax as _jax
    parts = [f"jax{_jax.__version__}",
             os.environ.get("JAX_PLATFORMS", "")]
    try:
        import importlib.metadata as _md
        plug = []
        for d in _md.distributions():
            try:
                # d.metadata can be None for orphaned/partial dist-info
                # dirs (interrupted pip uninstall) — skip those, don't
                # abandon the whole fingerprint
                name = d.metadata["Name"] if d.metadata else None
            except Exception:
                continue
            if name and any(t in name.lower()
                            for t in ("libtpu", "axon", "pjrt", "jaxlib")):
                plug.append(f"{name}{d.version}")
        parts.extend(sorted(plug))
    except Exception:
        # plugin versions unknowable -> the key cannot prove build
        # identity, so mark it uncacheable rather than risk serving a
        # stale "accepted" verdict to a different plugin build (which
        # would reintroduce the fatal abort this machinery prevents)
        parts.append("plugin-meta-unavailable")
    return "|".join(parts)


def apply_overlap_flags(enable: bool = True, *, target: str = "tpu",
                        validate: bool = False, cwd: Optional[str] = None,
                        validate_timeout: Optional[float] = None) -> str:
    """Install the overlap scheduler flags into XLA_FLAGS (idempotent).

    Must run BEFORE jax backend initialization — flags set after the
    backend is live are ignored, in which case this warns and returns the
    current value unchanged. ``PT_NO_OVERLAP=1`` forces them off (the A/B
    lever for measuring the overlap win on hardware). ``validate=True``
    vets each flag against the installed XLA in a subprocess first
    (required on real hardware: unknown flags are a process-fatal error,
    see :func:`validate_xla_flags`)."""
    if os.environ.get("PT_NO_OVERLAP"):
        enable = False
    cur = os.environ.get("XLA_FLAGS", "")
    if not enable or target != "tpu":
        return cur
    # match by EXACT flag name so an explicit user "=false" is respected
    # and a longer flag name doesn't shadow a shorter one's install
    cur_names = {tok.split("=")[0] for tok in cur.split()}
    missing = [f for f in OVERLAP_XLA_FLAGS.split()
               if f.split("=")[0] not in cur_names]
    if not missing:
        return cur
    try:
        initialized = jax._src.xla_bridge._backends  # noqa: SLF001
    except AttributeError:
        initialized = {}
    if initialized:
        # checked BEFORE validate: vetting spawns multi-minute backend-init
        # subprocesses, pointless when flags can no longer be applied
        sys.stderr.write(
            "paddle_tpu.overlap: backend already initialized; XLA overlap "
            "flags NOT applied (set strategy before first jax use)\n")
        return cur
    if validate:
        missing = validate_xla_flags(missing, cwd=cwd,
                                     timeout=validate_timeout)
        if not missing:
            return cur
    new = (cur + " " + " ".join(missing)).strip()
    os.environ["XLA_FLAGS"] = new
    return new


# ---------------------------------------------------------------------------
# HLO analysis: prove the overlap preconditions on the compiled program
# ---------------------------------------------------------------------------

_INSTR_LHS = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*")
# opcode = first word directly followed by '(' after the (possibly tuple)
# result type — tuple types like "(s32[], f32[4])" never match word-paren
_OPCODE = re.compile(r"\s([a-z][\w\-]*)\(")
_OPND = re.compile(r"%([\w.\-]+)")
# computation header: "%name (params...) -> type {" or "ENTRY %name (...) {"
_COMP_HDR = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s+\(.*\{")
_COMP_REF_ONE = re.compile(
    r"(?:calls|body|condition|to_apply)=%?([\w.\-]+)")
_COMP_REF_LIST = re.compile(
    r"(?:calls|branch_computations)=\{([^}]*)\}")
_COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                   "collective-permute", "all-to-all")
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|reduce-scatter|all-gather|collective-permute|"
    r"all-to-all)(-start|-done)?\(")


def _parse_hlo(txt: str):
    """Returns (graph, comp_of, comp_members): instruction dataflow plus
    computation membership. Instructions that reference a computation
    (while body, fusion calls, conditional branches) get dependency edges
    to EVERY instruction of that computation — a conservative
    over-approximation that keeps independence claims sound."""
    graph: Dict[str, Tuple[str, List[str]]] = {}
    comp_of: Dict[str, str] = {}
    comp_members: Dict[str, List[str]] = {}
    cur = None
    for line in txt.splitlines():
        h = _COMP_HDR.match(line.strip())
        if h and "=" not in line.split("(")[0]:
            cur = h.group(1)
            comp_members.setdefault(cur, [])
        m = _INSTR_LHS.match(line)
        if not m:
            continue
        name = m.group(1)
        rhs = line.split("=", 1)[1]
        mo = _OPCODE.search(" " + rhs)
        if not mo:
            continue
        op = mo.group(1)
        opnds = [o for o in _OPND.findall(rhs) if o != name]
        # computation references become dependencies on the whole callee
        refs = list(_COMP_REF_ONE.findall(rhs))
        for r in _COMP_REF_LIST.findall(rhs):
            refs.extend(p.strip().lstrip("%") for p in r.split(","))
        graph[name] = (op, opnds + [f"comp:{r}" for r in refs if r])
        if cur is not None:
            comp_of[name] = cur
            comp_members[cur].append(name)
    return graph, comp_of, comp_members


def _ancestors(graph, comp_members, name):
    seen = set()
    todo = list(graph.get(name, ("", []))[1])
    while todo:
        n = todo.pop()
        if n.startswith("comp:"):
            for member in comp_members.get(n[5:], ()):
                if member not in seen:
                    seen.add(member)
                    todo.extend(graph.get(member, ("", []))[1])
            continue
        if n in seen or n not in graph:
            continue
        seen.add(n)
        todo.extend(graph[n][1])
    return seen


def backward_overlap_independent(compiled_text: str) -> bool:
    """True if some collective and some dot are mutually independent in the
    HLO — the precondition for the latency-hiding scheduler to overlap the
    TP backward allreduce with the weight-grad matmul
    (reference mp_async_allreduce's effect)."""
    g, _, members = _parse_hlo(compiled_text)
    colls = [n for n, (op, _) in g.items()
             if op.replace("-start", "").replace("-done", "")
             in _COLLECTIVE_OPS]
    dots = [n for n, (op, _) in g.items()
            if op in ("dot", "convolution") or "dot" in op]
    for c in colls:
        anc_c = _ancestors(g, members, c)
        for d in dots:
            if d in anc_c:
                continue
            if c in _ancestors(g, members, d):
                continue
            return True
    return False


def collectives_in_loop(compiled_text: str) -> Tuple[int, int]:
    """(total collectives, collectives inside while bodies), counting the
    async -start forms too. A collective inside the microbatch loop body
    syncs per microbatch — the structure that overlaps grad comm with the
    next microbatch's compute."""
    total = 0
    in_body = 0
    body_names = set(re.findall(r"body=%?([\w.\-]+)", compiled_text))
    cur = None
    for line in compiled_text.splitlines():
        h = _COMP_HDR.match(line.strip())
        if h and "=" not in line.split("(")[0]:
            cur = h.group(1)
        if _COLLECTIVE_RE.search(line) and "=" in line:
            if "-done(" in line:
                continue          # count start/done pairs once
            total += 1
            if cur in body_names:
                in_body += 1
    return total, in_body


def strategy_overlap_summary(strategy) -> Dict[str, bool]:
    """Which reference overlap knobs the strategy requests. Unknown knobs
    land in strategy.extras; the three reference names are honored."""
    tp_cfg = getattr(strategy, "tensor_parallel", None)
    sh_cfg = getattr(strategy, "sharding", None)
    extras = getattr(strategy, "extras", {}) or {}
    return {
        "mp_async_allreduce": bool(
            getattr(tp_cfg, "mp_async_allreduce", False)
            or extras.get("mp_async_allreduce")),
        "allreduce_matmul_grad_overlapping": bool(
            extras.get("allreduce_matmul_grad_overlapping")),
        "sharding_comm_overlap": bool(
            getattr(sh_cfg, "comm_overlap", False)
            or extras.get("comm_overlap")),
    }


def apply_strategy_overlap(strategy, *, target: Optional[str] = None) -> str:
    """Map the reference overlap knobs to the XLA scheduler flags. Any one
    of them on → async collectives + latency hiding on (they are one
    mechanism under XLA)."""
    summary = strategy_overlap_summary(strategy)
    if target is None:
        target = _detect_target()
    if any(summary.values()):
        # vet on real hardware: unknown flags abort the process at init.
        # Short default timeout on this path — fleet.init must not stall
        # minutes behind a wedged tunnel; a vet timeout just means no
        # overlap flags this run (bench.py keeps the long default)
        return apply_overlap_flags(
            True, target=target, validate=(target == "tpu"),
            validate_timeout=float(
                os.environ.get("PT_FLAG_VET_TIMEOUT", "60")))
    return os.environ.get("XLA_FLAGS", "")


def _config_platforms() -> str:
    try:
        return jax.config.jax_platforms or ""
    except AttributeError:
        return ""


def _detect_target() -> str:
    """'tpu' only when the process is actually headed for a TPU backend —
    the flags are TPU-compiler-only and make a CPU backend init fatal."""
    if os.environ.get("PT_BENCH_FORCE_CPU"):
        return "cpu"
    jp = _config_platforms() or os.environ.get("JAX_PLATFORMS", "")
    # unknown/auto platform -> 'cpu': installing TPU-only flags on a
    # non-TPU backend is fatal at init, so only opt in on clear evidence
    return "tpu" if ("tpu" in jp or "axon" in jp) else "cpu"


__all__ = ["OVERLAP_XLA_FLAGS", "apply_overlap_flags", "validate_xla_flags",
           "backward_overlap_independent", "collectives_in_loop",
           "strategy_overlap_summary", "apply_strategy_overlap"]
