"""Launcher core (reference: launch/main.py + controllers/collective.py).

Job model mirrors the reference: a **Pod** is this host's set of worker
**Containers**; the controller spawns them with per-rank env, streams logs
to files, watches exit codes, and tears the pod down on first failure
(or relaunches under elastic policy — distributed/elastic.py).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

# eager on purpose: importing THIS module already initializes the
# paddle_tpu parent package (python imports parents first, `-m` included),
# so a lazy import here would not make the launcher any lighter
from paddle_tpu.resilience.preemption import RESUMABLE_EXIT_CODE  # 75


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class LaunchConfig:
    """CLI surface (reference launch args subset that matters off-GPU)."""
    nproc_per_node: int = 1
    nnodes: int = 1
    node_rank: int = 0
    master: Optional[str] = None          # "host:port" of rank-0 TCPStore
    log_dir: str = "log"
    job_id: str = "default"
    devices: Optional[str] = None          # visible-device list per rank
    max_restarts: int = 0                  # >0 enables elastic relaunch
    max_preempt_relaunches: int = 100      # resumable exits don't burn budget
    run_mode: str = "collective"


@dataclasses.dataclass
class Container:
    """One worker process (reference: launch/job/container.py)."""
    rank: int
    local_rank: int
    env: Dict[str, str]
    cmd: List[str]
    log_path: str
    proc: Optional[subprocess.Popen] = None

    def start(self):
        os.makedirs(os.path.dirname(self.log_path) or ".", exist_ok=True)
        logf = open(self.log_path, "ab")
        env = dict(os.environ)
        env.update(self.env)
        self.proc = subprocess.Popen(self.cmd, env=env, stdout=logf,
                                     stderr=subprocess.STDOUT)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def exit_code(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()

    def terminate(self, grace: float = 10.0):
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.send_signal(signal.SIGTERM)
        deadline = time.time() + grace
        while time.time() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.1)
        self.proc.kill()


def _pod_exit_code(bad: List["Container"]) -> int:
    """Exit code for a failed pod. Resumable (75) ONLY when EVERY failed
    container exited 75: one real crash inside a preempted pod must burn
    the failure budget, not ride the preemption path."""
    codes = [c.exit_code or 1 for c in bad]
    if all(c == RESUMABLE_EXIT_CODE for c in codes):
        return RESUMABLE_EXIT_CODE
    return next(c for c in codes if c != RESUMABLE_EXIT_CODE)


@dataclasses.dataclass
class Pod:
    """This node's containers (reference: launch/job/pod.py)."""
    containers: List[Container] = dataclasses.field(default_factory=list)

    def start(self):
        for c in self.containers:
            c.start()

    def alive(self) -> bool:
        return any(c.alive() for c in self.containers)

    def failed(self) -> List[Container]:
        return [c for c in self.containers
                if c.exit_code not in (None, 0)]

    def join(self, poll: float = 1.0) -> int:
        """Watch until all exit or one fails (reference watcher behavior):
        first non-zero exit tears down the pod. Returns pod exit code."""
        while True:
            bad = self.failed()
            if bad:
                for c in self.containers:
                    c.terminate()
                return _pod_exit_code(bad)
            if not self.alive():
                return 0
            time.sleep(poll)

    def terminate(self):
        for c in self.containers:
            c.terminate()


def _make_pod(cfg: LaunchConfig, training_script: str,
              script_args: Sequence[str], node_rank: int,
              endpoints: List[str], coord: str) -> Pod:
    """The ONE per-rank container builder (shared by the single-node and
    multi-node tiers — only endpoint/coordinator derivation differs)."""
    world = cfg.nnodes * cfg.nproc_per_node
    coord_host, coord_port = coord.rsplit(":", 1)
    pod = Pod()
    for local_rank in range(cfg.nproc_per_node):
        rank = node_rank * cfg.nproc_per_node + local_rank
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "MASTER_ADDR": coord_host,
            "MASTER_PORT": coord_port,
            "PADDLE_JOB_ID": cfg.job_id,
            # jax.distributed.initialize() reads these
            "JAX_COORDINATOR_ADDRESS": coord,
            "JAX_NUM_PROCESSES": str(world),
            "JAX_PROCESS_ID": str(rank),
        }
        if cfg.devices is not None:
            devs = cfg.devices.split(",")
            env["CUDA_VISIBLE_DEVICES"] = devs[local_rank % len(devs)]
        pod.containers.append(Container(
            rank=rank, local_rank=local_rank, env=env,
            cmd=[sys.executable, "-u", training_script, *script_args],
            log_path=os.path.join(cfg.log_dir,
                                  f"workerlog.{rank}")))
    return pod


def build_pod(cfg: LaunchConfig, training_script: str,
              script_args: Sequence[str]) -> Pod:
    """Construct per-rank containers with the collective env
    (reference controllers/collective.py:build_pod)."""
    if cfg.master is None:
        master_host, master_port = "127.0.0.1", _free_port()
    else:
        master_host, master_port = cfg.master.rsplit(":", 1)
        master_port = int(master_port)

    # endpoints across the whole job, node-major (reference fakes the same
    # layout for single-host multi-proc tests)
    base_port = _free_port()
    endpoints = []
    for node in range(cfg.nnodes):
        host = master_host if cfg.nnodes > 1 else "127.0.0.1"
        for lr in range(cfg.nproc_per_node):
            endpoints.append(f"{host}:{base_port + lr}")
    return _make_pod(cfg, training_script, script_args, cfg.node_rank,
                     endpoints, f"{master_host}:{master_port}")


def _build_pod_multinode(cfg: LaunchConfig, training_script: str,
                         script_args: Sequence[str], node_rank: int,
                         peers: List[str]) -> Pod:
    """Per-rank containers from the SYNCED peer list (each record is
    "host:base_port:coord_port"); the jax coordinator is node 0's
    host:coord_port."""
    parsed = [p.rsplit(":", 2) for p in peers]
    endpoints = [f"{h}:{int(base) + lr}"
                 for h, base, _ in parsed
                 for lr in range(cfg.nproc_per_node)]
    return _make_pod(cfg, training_script, script_args, node_rank,
                     endpoints, f"{parsed[0][0]}:{parsed[0][2]}")


def launch(cfg: LaunchConfig, training_script: str,
           script_args: Sequence[str] = ()) -> int:
    """Run the job to completion; under cfg.max_restarts > 0 failed pods are
    relaunched (elastic fault-tolerance level, reference
    fleet/elastic/manager.py:43 ElasticLevel.FAULT_TOLERANCE).

    ``nnodes > 1`` with ``master`` set takes the MULTI-NODE tier: pods
    rendezvous through the master membership service (launch/master.py),
    node ranks are auto-assigned by registration order, heartbeats and
    restart epochs coordinate elastic recovery across hosts."""
    if cfg.nnodes > 1 and cfg.master:
        return _launch_multinode(cfg, training_script, script_args)
    attempt = preempts = 0
    while True:
        pod = build_pod(cfg, training_script, script_args)
        pod.start()
        code = pod.join()
        if code == 0:
            return 0
        if code == RESUMABLE_EXIT_CODE:
            # orderly preemption: the worker checkpointed and asked to be
            # resumed — relaunch without consuming the failure budget
            if preempts >= cfg.max_preempt_relaunches:
                return code
            preempts += 1
            print(f"[launch] pod preempted (resumable); relaunch "
                  f"{preempts}/{cfg.max_preempt_relaunches}", file=sys.stderr)
            continue
        if attempt >= cfg.max_restarts:
            return code
        attempt += 1
        print(f"[launch] pod failed (exit {code}); restart "
              f"{attempt}/{cfg.max_restarts}", file=sys.stderr)


def _local_host(master_host: str) -> str:
    """This machine's address AS SEEN on the route to the master — the
    address peers can reach us at. gethostbyname(hostname) is wrong on
    stock Debian/Ubuntu (resolves to 127.0.1.1 via /etc/hosts); the
    UDP connect trick reads the outbound interface without sending."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((master_host, 9))       # no packet is sent
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def _host_is_local(host: str) -> bool:
    """Does ``host`` name this machine? Decided by a BIND PROBE — only
    the owning host can bind its own IP (getaddrinfo(hostname) commonly
    omits NIC addresses on Debian-style images, which would leave a job
    with no store server at all). Server election must only be attempted
    on the master host: TCPStore's server start binds a LOCAL port
    wherever it runs, so 'bind succeeded' elsewhere would just strand a
    stray server."""
    if host in ("localhost", "0.0.0.0"):
        return True
    try:
        target = socket.gethostbyname(host)
    except OSError:
        return False
    if target.startswith("127."):
        return True
    try:
        with socket.socket() as s:
            s.bind((target, 0))
        return True
    except OSError:
        return False


def _launch_multinode(cfg: LaunchConfig, training_script: str,
                      script_args: Sequence[str]) -> int:
    """Multi-node controller (reference: controllers/master.py +
    controllers/collective.py watcher). One controller per host; the one
    whose bind of the master port succeeds hosts the membership store
    (reference HTTPMaster: rank-0 hosts, peers connect). Elastic loop:
    local failure bumps the restart epoch; every controller watching the
    epoch tears down its pod and re-registers; heartbeat TTL catches
    hosts that die without reporting."""
    from .master import Master

    host, port = cfg.master.rsplit(":", 1)
    master = None
    if _host_is_local(host):
        # the master host's controller hosts the store; two controllers
        # on one machine (tests) race the bind — loser falls to client
        try:
            master = Master(host, int(port), cfg.job_id, is_server=True)
        except RuntimeError:
            master = None
    if master is None:
        master = Master(host, int(port), cfg.job_id, is_server=False)

    attempt = preempts = 0
    code = 0
    # preempt counter FIRST, epoch second — the mirror of bump_epoch's
    # write order, so a concurrent preempt bump can only surface as
    # "failure" (budget-burning, fail-safe), never the reverse
    seen_pre = master.preempt_epochs()
    epoch = master.restart_epoch()
    while True:
        base_port, coord_port = _free_port(), _free_port()
        rec = f"{_local_host(host)}:{base_port}:{coord_port}"
        try:
            peers, node_rank = master.sync_peers(rec, cfg.nnodes, epoch,
                                                 timeout=60.0)
        except TimeoutError:
            # peers moved to a newer epoch between our read and sync —
            # re-read and re-register (does not consume the budget)
            seen_pre = master.preempt_epochs()   # counter-then-epoch order
            new_epoch = master.restart_epoch()
            if new_epoch == epoch:
                raise        # genuinely missing peers: fail loudly
            epoch = new_epoch
            continue
        others = [f"e{epoch}-n{i}" for i in range(cfg.nnodes)
                  if i != node_rank]
        pod = _build_pod_multinode(cfg, training_script, script_args,
                                   node_rank, peers)
        master.start_heartbeat(f"e{epoch}-n{node_rank}")
        pod.start()
        print(f"[launch] epoch {epoch}: node {node_rank}/{cfg.nnodes} "
              f"up ({cfg.nproc_per_node} workers)", file=sys.stderr)

        failed = False
        while True:
            bad = pod.failed()
            if bad:
                code = _pod_exit_code(bad)
                print(f"[launch] epoch {epoch}: local worker failed "
                      f"(exit {code}); signaling restart", file=sys.stderr)
                # tell the peers WHY: a resumable (preemption) exit must not
                # burn their failure budget either
                master.bump_epoch("preempt" if code == RESUMABLE_EXIT_CODE
                                  else "failure")
                pod.terminate()
                failed = True
                break
            if master.restart_epoch() != epoch:
                print(f"[launch] epoch {epoch}: peer signaled restart",
                      file=sys.stderr)
                pod.terminate()
                code = 0
                failed = True
                break
            dead = master.dead_pods(others, ttl=15.0)
            if dead:
                print(f"[launch] epoch {epoch}: peer heartbeat lost "
                      f"({dead}); signaling restart", file=sys.stderr)
                master.bump_epoch()
                pod.terminate()
                code = 1
                failed = True
                break
            if not pod.alive():
                break                        # all local workers exited 0
            time.sleep(0.5)

        if not failed:
            # completion barrier (Master.done_barrier) — heartbeats KEEP
            # RUNNING through it: a pod whose workers finish early must
            # not look dead to peers still training (their dead_pods
            # watch would tear down a healthy job)
            if master.done_barrier(cfg.nnodes, epoch):
                master.stop_heartbeat()
                return 0
            failed = True       # a peer failed during our barrier wait
            code = 0
        master.stop_heartbeat()

        new_pre = master.preempt_epochs()   # counter-then-epoch order
        new_epoch = master.restart_epoch()
        # every bump in the window was preemption-reasoned → resumable;
        # any failure in the mix burns the budget (fail-safe)
        resumable = (code == RESUMABLE_EXIT_CODE
                     or (code == 0 and new_epoch > epoch
                         and new_pre - seen_pre >= new_epoch - epoch))
        seen_pre = new_pre
        if resumable:
            # orderly preemption (local exit 75, or a PEER's — the epoch
            # reason says so): same contract as the single-node loop —
            # relaunch into a resume without consuming the failure budget,
            # bounded separately
            preempts += 1
            if preempts > cfg.max_preempt_relaunches:
                print(f"[launch] preemption budget exhausted "
                      f"({cfg.max_preempt_relaunches})", file=sys.stderr)
                return code or RESUMABLE_EXIT_CODE
            print(f"[launch] node preempted (resumable); relaunch "
                  f"{preempts}/{cfg.max_preempt_relaunches}", file=sys.stderr)
        else:
            attempt += 1
            if attempt > cfg.max_restarts:
                print(f"[launch] restart budget exhausted "
                      f"({cfg.max_restarts})", file=sys.stderr)
                return code or 1
        epoch = new_epoch


def _parse_args(argv: Sequence[str]):
    import argparse
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="multi-process launcher (reference: "
                    "python -m paddle.distributed.launch)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", type=str, default=None)
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--devices", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0)
    p.add_argument("training_script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ns = _parse_args(argv if argv is not None else sys.argv[1:])
    cfg = LaunchConfig(
        nproc_per_node=ns.nproc_per_node, nnodes=ns.nnodes,
        node_rank=ns.node_rank, master=ns.master, log_dir=ns.log_dir,
        job_id=ns.job_id, devices=ns.devices, max_restarts=ns.max_restarts)
    return launch(cfg, ns.training_script, ns.script_args)
