"""Job master / membership service for multi-node launches.

Reference analogue: launch/controllers/master.py — the HTTPMaster (rank-0
hosts a KV store; peers sync_peers through it) and the ETCDMaster tier
(registration + heartbeat + watch for elastic membership changes). TPU
redesign: one service over the C++ TCPStore (csrc/pt_native.cc) covers
both tiers — the same store that backs collective rendezvous does pod
membership, so there is no second service to deploy:

- ``sync_peers``: epoch-scoped registration — each pod atomically takes a
  slot (store.add) and publishes its endpoint record; everyone blocks
  until all ``nnodes`` records exist. Registration order IS the node
  rank (reference HTTPMaster.sync_peers semantics, incl. rank -1
  auto-assignment).
- heartbeats: pods stamp ``hb/<pod>`` every ``interval``; anyone can ask
  for pods whose stamp is older than a TTL (the ETCD lease analogue).
- restart epochs: a pod that observes failure bumps ``epoch``; every
  watcher sees the bump, tears down its local pod and re-registers under
  the new epoch — the watch-triggered elastic restart of the reference's
  ETCDMaster watcher, minus etcd.

The server side lives wherever ``Master(..., is_server=True)`` runs
(normally the node whose address is --master); clients retry-connect
until it is up, so controller start order does not matter.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple


class Master:
    """Membership service over one TCPStore endpoint."""

    def __init__(self, host: str, port: int, job_id: str = "default",
                 is_server: bool = False, timeout: float = 120.0,
                 connect_retry_s: float = 60.0):
        from ...native import TCPStore
        self.job = job_id
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        # (stamp, first-seen LOCAL time) per observed pod — staleness is
        # judged by how long a stamp stays UNCHANGED on OUR clock, never
        # by comparing the producer's wall clock to ours
        self._hb_seen: Dict[str, Tuple[str, float]] = {}
        if is_server:
            self.store = TCPStore(host, port, is_master=True,
                                  timeout=timeout)
            return
        deadline = time.time() + connect_retry_s
        last: Optional[Exception] = None
        while True:
            try:
                self.store = TCPStore(host, port, timeout=timeout)
                return
            except RuntimeError as e:       # server not up yet
                last = e
                if time.time() >= deadline:
                    raise RuntimeError(
                        f"Master: no server at {host}:{port} after "
                        f"{connect_retry_s:.0f}s: {last}") from last
                time.sleep(0.5)

    def _k(self, *parts) -> str:
        return "/".join(("ptmaster", self.job) + tuple(str(p) for p in parts))

    @property
    def is_server(self) -> bool:
        """True when this Master hosts the store in-process — it must be
        the LAST controller standing on success (its exit kills the
        store)."""
        return getattr(self.store, "_server", None) is not None

    # -- peer sync ----------------------------------------------------------

    def sync_peers(self, value: str, nnodes: int, epoch: int = 0,
                   timeout: float = 120.0) -> Tuple[List[str], int]:
        """Register this pod's record and wait for the full set.

        Returns (records ordered by node rank, this pod's node rank).
        Epoch-scoped: a new epoch is a fresh registration round (elastic
        restarts re-sync without stale members)."""
        rank = self.store.add(self._k("e", epoch, "count"), 1) - 1
        if rank >= nnodes:
            raise RuntimeError(
                f"sync_peers: {rank + 1} pods registered for a {nnodes}-"
                f"node job (duplicate launch or stale epoch?)")
        self.store.set(self._k("e", epoch, "peer", rank), value)
        deadline = time.time() + timeout
        peers: List[str] = []
        for i in range(nnodes):
            left = max(deadline - time.time(), 0.1)
            peers.append(self.store.get(
                self._k("e", epoch, "peer", i), timeout=left).decode())
        return peers, rank

    def done_barrier(self, nnodes: int, epoch: int) -> bool:
        """Two-phase all-pods completion barrier for one epoch.

        Returns True when every pod registered done; False if the
        restart epoch moved first (a peer failed — caller should
        restart). Phase 2 (ack) keeps the SERVER-hosting Master alive
        until every peer has observed completion: exiting earlier kills
        the in-process store under peers still polling."""
        self.store.add(self._k("e", epoch, "done"), 1)
        while True:
            n = self.store.add(self._k("e", epoch, "done"), 0)
            if n >= nnodes:
                self.store.add(self._k("e", epoch, "ack"), 1)
                if self.is_server:
                    deadline = time.time() + 60
                    while (self.store.add(self._k("e", epoch, "ack"), 0)
                           < nnodes and time.time() < deadline):
                        time.sleep(0.2)
                return True
            if self.restart_epoch() != epoch:
                return False
            time.sleep(0.3)

    # -- heartbeats ---------------------------------------------------------

    def heartbeat(self, pod_name: str) -> None:
        self.store.set(self._k("hb", pod_name), repr(time.time()))

    def start_heartbeat(self, pod_name: str, interval: float = 2.0) -> None:
        """Background stamping thread (reference: ETCDMaster lease
        keepalive). Re-armable: each start gets a fresh stop event, so
        the elastic loop can stop/start across restart epochs."""
        self._hb_stop = threading.Event()
        self.heartbeat(pod_name)

        def run():
            while not self._hb_stop.wait(interval):
                try:
                    self.heartbeat(pod_name)
                except Exception:
                    return                   # store gone: job is over
        self._hb_thread = threading.Thread(target=run, daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)

    def heartbeats(self, pod_names: List[str]) -> Dict[str, float]:
        out = {}
        for p in pod_names:
            v = self.store.try_get(self._k("hb", p))
            if v is not None:
                out[p] = float(v.decode())
        return out

    def dead_pods(self, pod_names: List[str], ttl: float) -> List[str]:
        """Pods whose heartbeat stamp has not CHANGED for ``ttl`` seconds
        of THIS observer's clock (never-seen pods are NOT dead — they may
        not have started stamping yet). Staleness-of-stamp, not
        stamp-vs-now: the producer's wall clock may be skewed by more
        than the TTL (NTP not yet converged after a VM resume — exactly
        the elastic-recovery scenario)."""
        now = time.time()
        dead = []
        for p in pod_names:
            v = self.store.try_get(self._k("hb", p))
            if v is None:
                continue
            stamp = v.decode()
            prev = self._hb_seen.get(p)
            if prev is None or prev[0] != stamp:
                self._hb_seen[p] = (stamp, now)
                continue
            if now - prev[1] > ttl:
                dead.append(p)
        return dead

    # -- restart epochs -----------------------------------------------------

    def restart_epoch(self) -> int:
        return self.store.add(self._k("epoch"), 0)

    def bump_epoch(self, reason: str = "failure") -> int:
        """Signal every pod to tear down and re-register (the watch event
        of the reference's elastic manager). ``reason`` ("failure" or
        "preempt") tells watchers whether the restart should consume their
        failure budget — an orderly preemption anywhere in the job must
        not.

        The reason rides a parallel atomic COUNTER, not a per-epoch key:
        concurrent bumpers would race a key write and mislabel each other's
        epoch. The preempt counter is advanced FIRST, so any observer of
        the epoch move sees it; observers compare deltas, and a mixed
        failure+preempt window counts as failure (the budget-burning,
        fail-safe reading). Residual window: with no multi-key transaction
        in the store, a preempt bumper stalled BETWEEN its two adds while a
        failure bumper completes can make that one failure window read as
        resumable — one relaunch that skips the failure budget, still
        bounded by max_preempt_relaunches."""
        if reason == "preempt":
            self.store.add(self._k("preempt_epochs"), 1)
        return self.store.add(self._k("epoch"), 1)

    def preempt_epochs(self) -> int:
        """Total preemption-reason bumps so far (see bump_epoch)."""
        return self.store.add(self._k("preempt_epochs"), 0)


__all__ = ["Master"]
