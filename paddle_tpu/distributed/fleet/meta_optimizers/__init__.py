"""fleet.meta_optimizers package path (reference:
fleet/meta_optimizers/ — the dygraph wrappers recipes import)."""
from .dygraph_optimizer import (DygraphShardingOptimizer,
                                HybridParallelOptimizer)

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer"]
