"""Dygraph hybrid/sharding optimizer wrappers.

Reference: fleet/meta_optimizers/dygraph_optimizer/
{hybrid_parallel_optimizer.py HybridParallelOptimizer:254,
dygraph_sharding_optimizer.py DygraphShardingOptimizer:48}.

TPU redesign: the reference wrappers implement what GSPMD already does —
HybridParallelOptimizer fuses grad allreduces across mp/sharding groups
and rescopes gradient clipping to the hybrid topology;
DygraphShardingOptimizer partitions optimizer state across the sharding
group (ZeRO-1) with per-rank param ownership and broadcast-after-step.
Here the collectives come out of the compiler, so the wrappers:

- delegate the whole imperative surface to the inner optimizer (the
  recipes' ``opt.step()``/``minimize`` keep working);
- HybridParallelOptimizer: the global-norm clip on the inner optimizer is
  ALREADY topology-aware (optimizer/clip.py computes the norm over the
  global arrays; with sharded grads XLA inserts the cross-device
  reduction), so the wrapper validates the clip type and otherwise stays
  out of the way;
- DygraphShardingOptimizer: places optimizer state sharded like its
  parameters over the active mesh (the fsdp axis = the sharding group)
  via parallel.api.shard_optimizer_state after it materializes —
  the ZeRO-1 memory profile without rank bookkeeping.
"""

from __future__ import annotations

from typing import Optional


class _DelegatingOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    @property
    def inner_opt(self):
        return self._inner_opt

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_inner_opt"), name)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def minimize(self, loss=None, startup_program=None, parameters=None,
                 no_grad_set=None, grads=None):
        if grads is None:
            raise ValueError(
                "TPU optimizers take explicit grads: wrapper.minimize("
                "grads=...) or wrapper.step(grads)")
        self.step(grads)   # through the subclass hooks (ZeRO-1 etc.)
        return None, None

    def step(self, grads=None):
        return self._inner_opt.step(grads)


class HybridParallelOptimizer(_DelegatingOptimizer):
    """Reference hybrid_parallel_optimizer.py:254. step()/minimize()
    delegate; the dist-aware global-norm clip is validated here."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        super().__init__(optimizer, hcg, strategy)
        clip = getattr(optimizer, "grad_clip", None)
        if clip is not None and not hasattr(clip, "__call__"):
            raise TypeError(
                f"optimizer.grad_clip must be callable, got {type(clip)}")

    def step(self, grads=None):
        return self._inner_opt.step(grads)


class DygraphShardingOptimizer(_DelegatingOptimizer):
    """Reference dygraph_sharding_optimizer.py:48 (ZeRO-1). Opt state is
    sharded like the parameters over the active mesh after it first
    materializes; ``reduce_gradients`` is a validated no-op (GSPMD emits
    the grad reduce-scatter)."""

    def step(self, grads=None):
        out = self._inner_opt.step(grads)
        self._shard_state()
        self._restore_param_placement()
        return out

    def _restore_param_placement(self):
        """The broadcast-after-step equivalent: the sharded-state update
        arithmetic leaves new param VALUES fsdp-sharded; re-place them per
        their own annotations (replicated when unannotated) so forwards
        keep the ZeRO-1 profile — sharded state, gathered params
        (reference: dygraph_sharding_optimizer's post-step broadcast)."""
        from paddle_tpu.parallel.mesh import current_mesh
        hm = current_mesh()
        if hm is None or hm.mesh.shape.get("fsdp", 1) == 1:
            return   # no ZeRO axis -> placement cannot drift; skip the loop
        import jax
        from jax.sharding import NamedSharding
        from paddle_tpu.parallel.api import _clean_spec
        for k, p in self._inner_opt._bound_params.items():
            spec = _clean_spec(p.sharding, hm.mesh)
            p.value = jax.device_put(p.value,
                                     NamedSharding(hm.mesh, spec))

    def _shard_state(self):
        opt = self._inner_opt
        state = getattr(opt, "_state", None)
        if state is None:
            return
        from paddle_tpu.parallel.mesh import current_mesh
        hm = current_mesh()
        if hm is None:
            return
        from paddle_tpu.parallel.api import (_clean_spec,
                                             shard_optimizer_state)
        from jax.sharding import PartitionSpec as P
        fsdp = hm.mesh.shape.get("fsdp", 1)
        specs = {}
        for k, p in opt._bound_params.items():
            spec = _clean_spec(p.sharding, hm.mesh)
            if fsdp > 1 and all(e is None for e in spec):
                # ZeRO-1 proper: even a REPLICATED param's optimizer state
                # is partitioned across the sharding group — split the
                # first fsdp-divisible dim (reference shards by rank
                # ownership; this is the mesh-native equivalent)
                shape = tuple(p.value.shape)
                for dim, size in enumerate(shape):
                    if size % fsdp == 0 and size >= fsdp:
                        entries = [None] * len(shape)
                        entries[dim] = "fsdp"
                        spec = P(*entries)
                        break
            specs[k] = spec
        opt._state = shard_optimizer_state(state, specs)

    def reduce_gradients(self, parameter_list=None, hcg=None):
        """No-op by design: gradient reduction is emitted by GSPMD at the
        sharding boundary (reference does a manual group reduce here)."""
        return None


__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer"]
