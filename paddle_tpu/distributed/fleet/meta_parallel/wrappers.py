"""Meta-parallel model wrappers + train/eval batch drivers.

Reference: fleet/meta_parallel/{meta_parallel_base.py MetaParallelBase,
tensor_parallel.py TensorParallel, sharding_parallel.py ShardingParallel,
pipeline_parallel.py PipelineParallel:150 (train_batch:657 /
eval_batch:668)}.

TPU redesign: the reference wrappers install gradient hooks and drive
per-rank P2P runtimes; under GSPMD the wrapper's real work is (a) placing
the wrapped layer's parameters onto the active mesh per their sharding
annotations and (b) offering the recipe-facing ``train_batch`` /
``eval_batch`` loop — ONE jitted value_and_grad + optimizer step, with
the 1F1B fused path used automatically when the wrapped model provides
``loss_and_grads`` (models/llama.py) and GPipe-through-grad otherwise.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ....nn.layer import Layer
from ....parallel.api import shard_layer
from ....parallel.mesh import current_mesh
from ....parallel.pipeline import PipelineLayer


class MetaParallelBase(Layer):
    """Common wrapper: holds the layers, places params on the mesh, and
    forwards attribute access so recipes keep touching the inner model."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        if current_mesh() is not None:
            shard_layer(layers)
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        # Layer.__getattr__ resolves registered params/sublayers first;
        # anything else falls through to the wrapped model (recipe attrs)
        try:
            return super().__getattr__(name)
        except AttributeError:
            inner = self.__dict__["_sub_layers"].get("_layers")
            if inner is None:     # explicit None check: an EMPTY container
                raise             # is falsy but still the wrapped model
            return getattr(inner, name)


class TensorParallel(MetaParallelBase):
    """Reference: tensor_parallel.py TensorParallel — broadcast of
    non-distributed params across mp ranks is GSPMD replication here."""


class ShardingParallel(MetaParallelBase):
    """Reference: sharding_parallel.py ShardingParallel — ZeRO parameter
    placement comes from the fsdp axis annotations."""


class PipelineParallel(MetaParallelBase):
    """Recipe-facing pipeline driver (reference pipeline_parallel.py:150).

    ``train_batch([inputs, labels], optimizer)`` runs ONE compiled
    forward+backward+step; the fused 1F1B path is used when the wrapped
    model provides ``loss_and_grads``."""

    def __init__(self, layers, hcg=None, strategy=None):
        if not (isinstance(layers, PipelineLayer)
                or hasattr(layers, "loss_and_grads")):
            raise TypeError(
                "PipelineParallel expects a PipelineLayer-derived model "
                "(or one providing loss_and_grads), got "
                f"{type(layers).__name__}")
        super().__init__(layers, hcg, strategy)
        self._grad_fn = None

    def _build_grad_fn(self):
        model = self._layers

        if hasattr(model, "loss_and_grads"):
            # fused 1F1B forward+backward (models/llama.py)
            def loss_grads(params, inputs, labels):
                return model.loss_and_grads(params, inputs, labels)
        else:
            loss_fn = getattr(model, "loss_fn", None)

            def loss_grads(params, inputs, labels):
                def f(p):
                    out = model.functional_call(p, inputs)
                    if loss_fn is not None:
                        return loss_fn(out, labels)
                    # model returns loss directly when labels are bound
                    return out if out.ndim == 0 else jnp.mean(out)
                return jax.value_and_grad(f)(params)

        self._grad_fn = jax.jit(loss_grads)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One compiled forward+backward then the optimizer's canonical
        imperative step. ``scaler`` is accepted for recipe parity — bf16
        training needs no loss scaling (amp/ shim documents this)."""
        inputs, labels = data
        if self._grad_fn is None:
            self._build_grad_fn()
        params = dict(self._layers.raw_parameters())
        loss, grads = self._grad_fn(params, jnp.asarray(inputs),
                                    jnp.asarray(labels))
        optimizer.step(dict(grads))
        if lr_scheduler is not None and hasattr(lr_scheduler, "step"):
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss: bool = False):
        inputs = data[0] if isinstance(data, (tuple, list)) else data
        was_training = self._layers.training
        self._layers.eval()
        try:
            if compute_loss and isinstance(data, (tuple, list)) \
                    and len(data) > 1:
                out = self._layers(jnp.asarray(inputs),
                                   jnp.asarray(data[1]))
                return out[0] if isinstance(out, tuple) else out
            return self._layers(jnp.asarray(inputs))
        finally:
            if was_training:
                self._layers.train()


__all__ = ["MetaParallelBase", "TensorParallel", "ShardingParallel",
           "PipelineParallel"]
