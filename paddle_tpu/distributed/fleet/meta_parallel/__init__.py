"""paddle.distributed.fleet.meta_parallel subpackage path (reference:
fleet/meta_parallel/{parallel_layers/mp_layers.py, pp_layers.py,
pipeline_parallel.py}); implementations in paddle_tpu.parallel."""
from ....parallel.mp_layers import (ColumnParallelLinear,
                                    ColumnSequenceParallelLinear,
                                    ParallelCrossEntropy,
                                    RowParallelLinear,
                                    RowSequenceParallelLinear,
                                    VocabParallelEmbedding)
from ....parallel.pipeline import (LayerDesc, PipelineLayer, SegmentLayers,
                                   SharedLayerDesc)
from .wrappers import (MetaParallelBase, PipelineParallel, ShardingParallel,
                       TensorParallel)

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "PipelineLayer", "LayerDesc", "SharedLayerDesc", "SegmentLayers",
           "MetaParallelBase", "PipelineParallel", "TensorParallel",
           "ShardingParallel"]
