"""Elastic training manager (reference:
python/paddle/distributed/fleet/elastic/manager.py:126 ElasticManager;
ElasticLevel at manager.py:43).

Reference behavior: nodes register in etcd, a watcher tracks membership;
on scale-in/out (or node death) training is killed and relaunched with a
regenerated rank map; checkpoint/resume provides continuity.

TPU-native redesign: the registry is the native C++ TCPStore (no etcd in a
TPU pod; the coordinator host plays master), membership is heartbeat keys
checked against a timeout window, and the relaunch path reuses
distributed.launch. ISSUE 15 adds the reference's ``_update_hosts`` half:
a CHANGED world size is survivable, not just a restart of the same one —
:meth:`ElasticManager.run_elastic` re-enters training when membership
changes (full-jitter backoff, no restart budget burned), and
:func:`replan_and_apply` asks the auto-parallel planner for the best legal
config on the surviving devices and re-places the trainer's state through
``Trainer.apply_plan``; the resharded checkpoint restore is
``resilience/reshard.py``. ``pt_elastic_*`` counters publish the flow
through the PR 4 registry; ``observability.sentry.elastic_rules()`` is the
matching alert pack.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from enum import IntEnum
from typing import Callable, Optional


def backoff_delays(base: float, cap: float, attempts: int,
                   rng: Optional[random.Random] = None):
    """Jittered exponential backoff schedule ("full jitter": U(0, base·2^k),
    capped). A restarting coordinator must not be stampeded by every worker
    retrying in lockstep — the jitter spreads the reconnect wave."""
    rng = rng or random.Random()
    delay = float(base)
    for _ in range(attempts):
        yield rng.uniform(0.0, min(delay, cap))
        delay = min(delay * 2.0, cap)


class WorldSizeChanged(RuntimeError):
    """Membership changed under a live run (a worker died or joined).

    Raised from inside a training callable (e.g. a heartbeat-driven
    ``membership_probe`` callback) to unwind to
    :meth:`ElasticManager.run_elastic`, which re-plans on the surviving
    devices and re-enters — WITHOUT burning the failure-restart budget
    (losing a host is the normal weather of preemptible pods, not a bug
    in the training code)."""

    def __init__(self, old_size: int, new_size: int):
        super().__init__(f"world size changed {old_size} -> {new_size}")
        self.old_size = int(old_size)
        self.new_size = int(new_size)


class ElasticLevel(IntEnum):
    FAULT_TOLERANCE = 1   # fixed world size, relaunch on failure
    ELASTIC = 2           # world size may change between restarts


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Membership + restart-policy driver.

    ``host_port`` addresses the rank-0 TCPStore (None → host one in-process
    as master). Each node heartbeats ``node/<id>``; :meth:`watch` reports
    membership health; :meth:`run` relaunches a training callable on failure
    up to ``max_restarts`` times, passing the restart ordinal so the callable
    can resume from its latest checkpoint.
    """

    def __init__(self, host_port: Optional[str] = None, *,
                 np: Optional[int] = None, is_master: bool = False,
                 elastic_level: ElasticLevel = ElasticLevel.FAULT_TOLERANCE,
                 heartbeat_interval: float = 2.0,
                 heartbeat_timeout: float = 30.0, max_restarts: int = 3,
                 node_id: Optional[str] = None,
                 reconnect_backoff_base: float = 0.5,
                 reconnect_backoff_cap: float = 30.0,
                 max_reconnect_attempts: int = 8):
        from paddle_tpu import native
        self.np = np or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.elastic_level = elastic_level
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        self.max_restarts = max_restarts
        self.node_id = node_id or os.environ.get(
            "PADDLE_TRAINER_ID", f"node-{os.getpid()}")
        if host_port is None:
            self.store = native.TCPStore(is_master=True, world_size=self.np)
            self.host, self.port = "127.0.0.1", self.store.port
        else:
            host, port = host_port.rsplit(":", 1)
            self.store = native.TCPStore(host=host, port=int(port),
                                         is_master=is_master,
                                         world_size=self.np)
            self.host, self.port = host, int(port)
        self.reconnect_backoff_base = reconnect_backoff_base
        self.reconnect_backoff_cap = reconnect_backoff_cap
        self.max_reconnect_attempts = max_reconnect_attempts
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self.restarts = 0
        self.preemptions = 0
        self.reconnects = 0

    # -- membership --------------------------------------------------------

    def register(self) -> None:
        """Announce membership and start heartbeating (reference register +
        etcd lease refresh). Node ids are also indexed through a shared
        counter because the store (like the reference's) has no prefix scan."""
        self._register_keys()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()

    def _register_keys(self) -> None:
        slot = self.store.add("node_count", 1) - 1
        self.store.set(f"node_ids/{slot}", self.node_id)
        self._beat()

    def _beat(self) -> None:
        self.store.set(f"node/{self.node_id}",
                       json.dumps({"ts": time.time(), "pid": os.getpid()}))

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except Exception:
                if not self._reregister():
                    return

    def _reregister(self) -> bool:
        """Heartbeat hit a dead/restarting coordinator: retry registration
        with jittered exponential backoff. All workers land in this path at
        once when the master restarts; without jitter they would retry in
        lockstep and stampede the fresh store."""
        for delay in backoff_delays(self.reconnect_backoff_base,
                                    self.reconnect_backoff_cap,
                                    self.max_reconnect_attempts):
            if self._stop.wait(delay):
                return False
            try:
                self._register_keys()
                self.reconnects += 1
                return True
            except Exception:
                continue
        return False

    def alive_nodes(self) -> list[str]:
        """Nodes whose latest heartbeat is inside the timeout window."""
        alive = []
        # scan every ALLOCATED slot (add(k, 0) reads the counter), skipping
        # holes: a registration that died between the slot add and the id
        # set must not truncate the scan and hide later registrants
        total = self.store.add("node_count", 0)
        for slot in range(total):
            raw = self.store.try_get(f"node_ids/{slot}")
            if raw is None:
                continue
            node_id = raw.decode()
            # re-registration after a coordinator restart can index the same
            # node under a second slot — count each node once
            if node_id not in alive:
                hb = self.store.try_get(f"node/{node_id}")
                if hb is not None:
                    data = json.loads(hb)
                    if time.time() - data["ts"] <= self.heartbeat_timeout:
                        alive.append(node_id)
        return alive

    def watch(self) -> str:
        """One health poll (reference ElasticManager.watch loop body)."""
        alive = self.alive_nodes()
        if len(alive) >= self.np:
            return (ElasticStatus.COMPLETED if self._stop.is_set()
                    else ElasticStatus.HOLD)
        if not alive:
            return ElasticStatus.ERROR
        if self.elastic_level == ElasticLevel.ELASTIC:
            return ElasticStatus.RESTART
        return ElasticStatus.RESTART

    def world_size(self) -> int:
        """Live membership count (heartbeats inside the timeout window)."""
        return len(self.alive_nodes())

    def membership_probe(self, expected: int) -> Callable[..., None]:
        """An ``on_metrics``-shaped callback that raises
        :class:`WorldSizeChanged` when the heartbeat registry disagrees
        with ``expected`` — the detection half of the reference's
        ``_update_hosts`` watch loop, wired into the step loop the
        trainer already runs."""
        expected = int(expected)

        def probe(*_args, **_kw):
            ws = self.world_size()
            if ws != expected:
                raise WorldSizeChanged(expected, ws)
        return probe

    # -- restart policy ----------------------------------------------------

    def run(self, train_fn: Callable[[int], None],
            max_preemptions: int = 100) -> bool:
        """Run with restart-on-failure (the relaunch half of manager.py; the
        reference shells out to launch — here train_fn encapsulates it).
        train_fn receives the restart ordinal (0 = first run) and should
        resume from its latest checkpoint when > 0.

        A :class:`~paddle_tpu.resilience.TrainingPreempted` exit (or a
        SystemExit carrying RESUMABLE_EXIT_CODE) is an ORDERLY preemption:
        state was checkpointed, so the relaunch resumes without consuming
        the failure-restart budget (bounded separately by
        ``max_preemptions`` so a flapping host still terminates)."""
        from ..resilience.preemption import RESUMABLE_EXIT_CODE
        while True:
            try:
                train_fn(self.restarts + self.preemptions)
                return True
            except SystemExit as e:
                if e.code != RESUMABLE_EXIT_CODE:
                    raise
                if self.preemptions >= max_preemptions:
                    print(f"[elastic] giving up after {self.preemptions} "
                          f"preemptions")
                    return False
                self.preemptions += 1
                print(f"[elastic] preempted (checkpointed); resume "
                      f"{self.preemptions}/{max_preemptions}")
            except Exception as e:  # noqa: BLE001 — any training failure
                if self.restarts >= self.max_restarts:
                    print(f"[elastic] giving up after {self.restarts} "
                          f"restarts: {e}")
                    return False
                self.restarts += 1
                print(f"[elastic] training failed ({e}); restart "
                      f"{self.restarts}/{self.max_restarts}")

    def run_elastic(self, train_fn: Callable[[int, int], None], *,
                    world_size_fn: Optional[Callable[[], int]] = None,
                    max_membership_changes: int = 32,
                    max_preemptions: int = 100,
                    sleep: Callable[[float], None] = time.sleep) -> bool:
        """:meth:`run` upgraded to the ELASTIC level: survive a CHANGED
        world size, not just restarts of the same one.

        ``train_fn(attempt, world_size)`` trains on ``world_size``
        workers and is expected to (a) resume from its latest checkpoint
        when ``attempt > 0`` and (b) raise :class:`WorldSizeChanged`
        when its membership probe sees the registry disagree. On a
        membership change the manager backs off with full jitter (the
        survivors must not stampede re-registration), re-reads the world
        size, and re-enters — burning ``max_membership_changes``, NOT
        the failure-restart budget. Orderly preemptions keep their own
        budget as in :meth:`run`. ``world_size_fn`` defaults to the
        heartbeat registry; tests inject a schedule."""
        from ..resilience.preemption import RESUMABLE_EXIT_CODE
        ws_fn = world_size_fn or self.world_size
        changes = 0
        last_ws: Optional[int] = None
        backoff = backoff_delays(self.reconnect_backoff_base,
                                 self.reconnect_backoff_cap,
                                 max(1, max_membership_changes))
        while True:
            ws = int(ws_fn())
            if last_ws is not None and ws != last_ws:
                changes += 1
                _elastic_counter("pt_elastic_membership_changes_total",
                                 "world-size changes survived",
                                 direction=("in" if ws < last_ws
                                            else "out"))
                _elastic_gauge("pt_elastic_world_size", ws)
                if changes > max_membership_changes:
                    print(f"[elastic] giving up after {changes - 1} "
                          f"membership changes")
                    return False
                sleep(next(backoff))
                ws = int(ws_fn())    # may have changed again during backoff
            elif last_ws is None:
                _elastic_gauge("pt_elastic_world_size", ws)
            last_ws = ws
            attempt = self.restarts + self.preemptions + changes
            try:
                train_fn(attempt, ws)
                return True
            except WorldSizeChanged as e:
                last_ws = e.old_size    # next loop top counts the change
                print(f"[elastic] membership change detected "
                      f"({e.old_size} -> {e.new_size}); re-planning "
                      f"({changes + 1}/{max_membership_changes})")
            except SystemExit as e:
                if e.code != RESUMABLE_EXIT_CODE:
                    raise
                if self.preemptions >= max_preemptions:
                    print(f"[elastic] giving up after {self.preemptions} "
                          f"preemptions")
                    return False
                self.preemptions += 1
                _elastic_counter("pt_elastic_resumes_total",
                                 "orderly preemption resumes")
                print(f"[elastic] preempted (checkpointed); resume "
                      f"{self.preemptions}/{max_preemptions}")
            except Exception as e:  # noqa: BLE001 — any training failure
                if self.restarts >= self.max_restarts:
                    print(f"[elastic] giving up after {self.restarts} "
                          f"restarts: {e}")
                    return False
                self.restarts += 1
                print(f"[elastic] training failed ({e}); restart "
                      f"{self.restarts}/{self.max_restarts}")

    def exit(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
        self.store.close()


# -- metrics (PR 4 registry; no-ops when observability is disabled) ----------

def _elastic_counter(name: str, desc: str, **labels) -> None:
    from ..observability.metrics import REGISTRY
    if REGISTRY.enabled:
        REGISTRY.counter(name, desc).inc(**labels)


def _elastic_gauge(name: str, value: float) -> None:
    from ..observability.metrics import REGISTRY
    if REGISTRY.enabled:
        REGISTRY.gauge(name, "live world size seen by the elastic "
                             "manager").set(float(value))


# -- the replan half of a membership change ----------------------------------

def replan_and_apply(trainer, model_cfg, *, devices=None, global_batch=8,
                     seq_len=32, configs=None, drift="ignore", **plan_kw):
    """Membership changed: ask the auto-parallel planner (ISSUE 11) for
    the best legal config on the surviving ``devices`` (HBM-prune
    included) and re-place the trainer's params/optimizer state through
    ``Trainer.apply_plan``. Returns ``(plan, mesh)`` — the caller enters
    the mesh and re-enters ``fit(resume='auto')``; the checkpoint
    manager reshards the restore against the recorded source plan.
    Raises ``InfeasibleMeshError`` when no legal config exists on the
    survivors (e.g. fewer devices than any tp that divides the heads)."""
    import time as _time
    from .auto_parallel import plan as _plan
    t0 = _time.perf_counter()
    report = _plan(model_cfg, devices=devices, global_batch=global_batch,
                   seq_len=seq_len, configs=configs, drift=drift, **plan_kw)
    chosen = report.chosen.plan
    hm = trainer.apply_plan(chosen, devices=devices)
    _elastic_counter("pt_elastic_replans_total",
                     "planner-picked re-configurations",
                     config=chosen.config_str)
    from ..observability.metrics import REGISTRY
    if REGISTRY.enabled:
        REGISTRY.histogram("pt_elastic_replan_seconds",
                           "plan + re-place duration on membership change",
                           "s").observe(_time.perf_counter() - t0)
    return chosen, hm
