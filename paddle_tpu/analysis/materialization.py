"""Materialization audit: which buffers the compiled graph actually holds.

The repo's flagship perf wins are *absence* properties — the fused CE head
means no [B,S,V] logits buffer exists anywhere in the optimized HLO (PR 5),
blockwise kernels mean working sets stay O(block) — and absences are what
refactors silently destroy. This pass generalizes the hand-rolled
``_bsv_buffers`` guard from tests/test_fused_vocab_ce.py into the reusable
check every graph contract calls:

* ``banned_buffers`` — shapes matching a declarative rule (last dim == V,
  remaining dims multiply to N: the logits-materialization signature —
  exactly the predicate the PR 5 test hard-coded), reported with the
  producing instruction so the failure says WHO re-materialized it;
* ``largest_buffers`` — the top-k biggest instruction results, the number
  a byte *budget* pins so a refactor that balloons an intermediate (a
  dropped rematerialization, an accidental fp32 upcast of a bf16 buffer)
  fails the snapshot diff even when no ban rule names its shape.

Buffer enumeration walks instruction DEF sites in every computation
(fusion-internal defs included — conservative, same coverage the original
text-scan guard had) and skips opcodes that never own a distinct buffer
(parameter/tuple plumbing).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from .hlo import HloInstruction, HloModule

__all__ = ["BanRule", "BufferHit", "materialization_report",
           "banned_buffers"]

# plumbing opcodes whose "result" is an existing buffer, not a new one
_NO_BUFFER = {"parameter", "tuple", "get-tuple-element", "bitcast"}


@dataclass(frozen=True)
class BanRule:
    """Declarative buffer ban: an array whose LAST dim equals ``last_dim``
    and whose remaining dims multiply to ``leading_product`` — with
    ``last_dim=V`` and ``leading_product=B*S`` this is precisely "a
    logits tensor materialized". Dtype-blind by default; an explicit
    ``dtype`` (XLA primitive name, e.g. "f32") narrows the ban to that
    element type — the int8-KV contract bans a *widened* pool-shaped
    buffer while the legitimate int8 pool update shares its dims."""
    last_dim: int
    leading_product: int
    label: str = "banned"
    dtype: Optional[str] = None

    def matches(self, dims: Sequence[int],
                dtype: Optional[str] = None) -> bool:
        if self.dtype is not None and dtype is not None \
                and dtype != self.dtype:
            return False
        if len(dims) < 2 or dims[-1] != self.last_dim:
            return False
        prod = 1
        for d in dims[:-1]:
            prod *= d
        return prod == self.leading_product


@dataclass
class BufferHit:
    shape: str
    bytes: int
    instruction: str
    opcode: str
    op_name: str
    source: str

    def describe(self) -> str:
        where = f" [{self.op_name}]" if self.op_name else ""
        src = f" ({self.source})" if self.source else ""
        return (f"{self.shape} ({self.bytes:,} B) <- %{self.instruction} "
                f"{self.opcode}{where}{src}")


def _buffers(mod: HloModule):
    for ins in mod.instructions:
        if ins.opcode in _NO_BUFFER:
            continue
        for leaf in ins.shape_leaves:
            if leaf.dims or leaf.dtype not in ("token", "opaque"):
                yield ins, leaf


def banned_buffers(mod: HloModule, rules: Sequence[BanRule]
                   ) -> List[BufferHit]:
    """All buffers matching any ban rule — the one definition of the
    "did the logits materialize?" check (test_fused_vocab_ce's HLO guard
    and the train-step contract both call this)."""
    hits: List[BufferHit] = []
    seen = set()
    for ins, leaf in _buffers(mod):
        for rule in rules:
            if rule.matches(leaf.dims, leaf.dtype):
                key = (str(leaf), ins.name)
                if key in seen:
                    continue
                seen.add(key)
                hits.append(BufferHit(str(leaf), leaf.bytes, ins.name,
                                      ins.opcode, ins.op_name, ins.source))
    hits.sort(key=lambda h: -h.bytes)
    return hits


def materialization_report(mod: HloModule,
                           rules: Sequence[BanRule] = (),
                           top_k: int = 5) -> Dict:
    """Summary the contract checker and budget snapshots consume."""
    largest: List[BufferHit] = []
    biggest = 0
    for ins, leaf in _buffers(mod):
        biggest = max(biggest, leaf.bytes)
        largest.append(BufferHit(str(leaf), leaf.bytes, ins.name,
                                 ins.opcode, ins.op_name, ins.source))
    largest.sort(key=lambda h: -h.bytes)
    return {
        "largest_intermediate_bytes": biggest,
        "largest_buffers": [h.describe() for h in largest[:top_k]],
        "banned": banned_buffers(mod, rules),
    }
