"""Host-sync / host-transfer detector for hot compiled graphs.

PR 2's superstep exists so that NOTHING in the train step talks to the
host; PR 3's serving tick made stop detection device-resident for the
same reason. A single stray ``jax.debug.print``, ``io_callback``,
``host_callback`` or infeed/outfeed inside one of these graphs
reintroduces a device→host fence per step and silently caps throughput —
and nothing in the test suite would notice, because numerics are
unchanged. This pass scans the optimized HLO for every construct that
implies host traffic:

* ``custom-call`` instructions whose target names a python/host callback
  (``xla_python_cpu_callback``, ``xla_python_gpu_callback``,
  ``xla_ffi_python_*``, anything containing "callback" or "host");
* ``infeed`` / ``outfeed`` instructions;
* ``send`` / ``recv`` (+ ``-done``) pairs — host transfers on TPU are
  lowered this way (``is_host_transfer=true``);
* ``copy-start``/``copy-done`` pairs that cross memory spaces into host
  memory (S(5) annotations in TPU dumps).

Each finding carries its op_name/source metadata so the failure message
points at the python line that planted the callback.
"""

from __future__ import annotations

import re
from typing import Dict, List

from .hlo import HloModule

__all__ = ["host_transfer_report"]

_CALLBACK_PAT = re.compile(r"callback|host", re.IGNORECASE)


def host_transfer_report(mod: HloModule) -> Dict:
    callbacks: List[str] = []
    infeed: List[str] = []
    outfeed: List[str] = []
    sendrecv: List[str] = []
    host_copies: List[str] = []

    def where(ins) -> str:
        bits = [f"%{ins.name}"]
        if ins.op_name:
            bits.append(ins.op_name)
        if ins.source:
            bits.append(ins.source)
        return " ".join(bits)

    for ins in mod.instructions:
        if ins.opcode == "custom-call":
            tgt = ins.attr("custom_call_target") or ""
            if _CALLBACK_PAT.search(tgt):
                callbacks.append(f"{tgt}: {where(ins)}")
        elif ins.opcode in ("infeed", "infeed-done"):
            infeed.append(where(ins))
        elif ins.opcode in ("outfeed", "outfeed-done"):
            outfeed.append(where(ins))
        elif ins.opcode in ("send", "send-done", "recv", "recv-done"):
            if "is_host_transfer=true" in ins.raw:
                sendrecv.append(where(ins))
        elif ins.opcode in ("copy-start", "copy-done"):
            # TPU memory-space crossing: S(5) marks host memory space in
            # the dump; plain device copies carry no space annotation
            if re.search(r"S\(5\)", ins.raw):
                host_copies.append(where(ins))

    return {
        "host_callbacks": callbacks,
        "infeed": infeed,
        "outfeed": outfeed,
        "host_sendrecv": sendrecv,
        "host_copies": host_copies,
        "host_transfer_count": (len(callbacks) + len(infeed) + len(outfeed)
                                + len(sendrecv) + len(host_copies)),
    }
