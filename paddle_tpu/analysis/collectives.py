"""Collective census: every cross-device operation in a compiled graph,
classified per mesh axis.

The TP/shard_map paths are one sharding-annotation typo away from GSPMD
inserting an implicit all-gather that re-materializes exactly the tensor
a kernel was built to keep sharded (the fused CE head's vocab shards, the
ring-attention KV blocks) — and the step still produces the right
numbers, just slower and fatter. The census makes the communication
pattern an ASSERTABLE artifact: for each collective instruction it
records opcode, payload bytes, ``replica_groups``, ``channel_id`` and the
jax-level op that introduced it (pmax/psum/... via op_name metadata), and
classifies which mesh axis the groups span by matching them against the
axis groupings a ``jax.sharding.Mesh`` implies.

The summary (counts per opcode+axis, bytes per opcode) is what budget
snapshots pin, and the per-graph comm table is the input the ROADMAP
item 3 sharding planner's cost model will price (bytes over an axis ×
per-axis link bandwidth = predicted comm time).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .hlo import HloModule

__all__ = ["CollectiveInstr", "collective_census", "mesh_axis_groups"]

COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)
# async variants lower as <op>-start/<op>-done; count the -start only
_START_SUFFIX = "-start"
_DONE_SUFFIX = "-done"


@dataclass
class CollectiveInstr:
    opcode: str
    bytes: int
    replica_groups: Optional[str]
    channel_id: Optional[str]
    axis: str                   # mesh axis name, "?" when unclassified
    op_name: str
    source: str
    # position of the (start) instruction in module walk order, and the
    # matched -done for async lowerings. The census is the ONE place
    # start->done pairing happens; analysis/overlap.py consumes these
    # indices instead of re-walking the module.
    index: int = -1
    name: str = ""
    computation: str = ""
    is_async: bool = False
    done_index: Optional[int] = None
    done_name: Optional[str] = None

    def describe(self) -> str:
        src = f" ({self.source})" if self.source else ""
        return (f"{self.opcode}[{self.axis}] {self.bytes:,} B "
                f"groups={self.replica_groups or '-'}"
                f" <- {self.op_name or '?'}{src}")


def mesh_axis_groups(mesh) -> Dict[str, frozenset]:
    """axis name -> canonical replica grouping (frozenset of sorted
    device-id tuples) for a ``jax.sharding.Mesh`` (or an object exposing
    ``.mesh``, e.g. HybridMesh). A collective whose replica_groups equals
    an axis's grouping communicates over exactly that axis."""
    mesh = getattr(mesh, "mesh", mesh)
    ids = mesh.devices  # ndarray of Device objects
    import numpy as np
    id_arr = np.vectorize(lambda d: d.id)(ids)
    out: Dict[str, frozenset] = {}
    names = list(mesh.axis_names)
    for i, name in enumerate(names):
        # move this axis last; every other index tuple is one group
        moved = np.moveaxis(id_arr, i, -1).reshape(-1, id_arr.shape[i])
        out[name] = frozenset(tuple(sorted(int(x) for x in row))
                              for row in moved)
    return out


def _parse_groups(text: str) -> Optional[frozenset]:
    if not text:
        return None
    rows = re.findall(r"\{([0-9, ]+)\}", text)
    if not rows:
        return None
    return frozenset(tuple(sorted(int(x) for x in row.replace(" ", "")
                                  .split(",") if x != ""))
                     for row in rows)


def _find_done(flat, start_idx: int) -> Tuple[Optional[int], Optional[str]]:
    """Index+name of the ``<op>-done`` consuming ``flat[start_idx]``'s
    value, or (None, None) when the module is truncated / unpaired.
    A -done names its -start as an operand, so the match is textual:
    same computation, matching opcode, start's name referenced."""
    start = flat[start_idx]
    want = start.opcode[:-len(_START_SUFFIX)] + _DONE_SUFFIX
    ref = re.compile(r"%?" + re.escape(start.name) + r"(?![\w.-])")
    for j in range(start_idx + 1, len(flat)):
        ins = flat[j]
        if ins.computation != start.computation:
            break  # instructions of one computation are contiguous
        if ins.opcode == want and ref.search(ins.raw):
            return j, ins.name
    return None, None


def collective_census(mod: HloModule, mesh=None) -> Dict:
    """Per-instruction table + summary. ``mesh`` (optional) enables axis
    classification; without it every collective reports axis "?"."""
    axis_groups: Dict[str, frozenset] = {}
    if mesh is not None:
        try:
            axis_groups = mesh_axis_groups(mesh)
        except Exception:
            axis_groups = {}

    flat = list(mod.instructions)
    table: List[CollectiveInstr] = []
    for idx, ins in enumerate(flat):
        op = ins.opcode
        if op.endswith(_DONE_SUFFIX):
            continue
        is_async = op.endswith(_START_SUFFIX)
        base = op[:-len(_START_SUFFIX)] if is_async else op
        if base not in COLLECTIVE_OPS:
            continue
        groups_txt = ins.attr("replica_groups")
        groups = _parse_groups(groups_txt or "")
        axis = "?"
        if groups is not None:
            for name, ag in axis_groups.items():
                if groups == ag:
                    axis = name
                    break
        done_index, done_name = (_find_done(flat, idx) if is_async
                                 else (None, None))
        table.append(CollectiveInstr(
            opcode=base, bytes=ins.bytes, replica_groups=groups_txt,
            channel_id=ins.attr("channel_id"), axis=axis,
            op_name=ins.op_name, source=ins.source,
            index=idx, name=ins.name, computation=ins.computation,
            is_async=is_async, done_index=done_index,
            done_name=done_name))

    counts: Dict[str, int] = {}
    bytes_by_op: Dict[str, int] = {}
    for c in table:
        key = f"{c.opcode}[{c.axis}]" if c.axis != "?" else c.opcode
        counts[key] = counts.get(key, 0) + 1
        bytes_by_op[c.opcode] = bytes_by_op.get(c.opcode, 0) + c.bytes
    return {
        "table": table,
        "counts": dict(sorted(counts.items())),
        "bytes_by_op": dict(sorted(bytes_by_op.items())),
        "total_collectives": len(table),
        "total_collective_bytes": sum(c.bytes for c in table),
    }
