"""Declarative graph contracts + JSON budget snapshots.

A ``GraphContract`` states the INVARIANTS a compiled graph must hold —
the properties a PR review can't see and a numerics test can't catch:

* ``ban_rules``       — buffers that must not exist (the [B,S,V] logits);
* ``require_aliased`` — label prefixes of entry parameters that MUST be
  donated (params/opt_state in the train step, pools/hist in serving);
* ``max_host_transfers`` — callbacks/infeed/outfeed ceiling (0 for every
  hot graph: PR 2/3's no-per-step-host-sync property);
* ``expect_collectives`` — exact per-axis collective counts where the
  comm pattern is part of the design (the TP fused-CE pmax/psum trio),
  ``None`` where the budget snapshot pins it instead.

The checked-in budget file (tools/graph_budgets.json) pins the MEASURED
side: largest intermediate bytes (ceiling), donated bytes and aliased
param count (floors), host transfer count (ceiling), collective counts
(exact) and the set of known donat-able-but-undonated inputs, each
covered by a hand-written waiver with a rationale. A failing check prints
a diff — budget vs actual, plus the producing instruction — and says how
to accept an intentional change (``tools/graph_lint.py --update-budgets``,
which preserves waivers).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .collectives import collective_census
from .donation import donation_report
from .hlo import HloModule, parse_hlo
from .materialization import BanRule, materialization_report
from .overlap import overlap_report
from .transfers import host_transfer_report

__all__ = [
    "GraphContract", "GraphReport", "Violation", "analyze",
    "snapshot_report", "check_contract", "check_budget",
    "render_violations", "load_budgets", "save_budgets", "BanRule",
]


@dataclass
class GraphContract:
    name: str
    ban_rules: Tuple[BanRule, ...] = ()
    require_aliased: Tuple[str, ...] = ()     # param-label prefixes
    max_host_transfers: int = 0
    expect_collectives: Optional[Dict[str, int]] = None
    # ISSUE 14 overlap invariants: floor on the smallest async
    # start->done window (priced independent ops), ceiling on the
    # fraction of priced comm seconds no window compute covers. ``None``
    # leaves enforcement to the budget snapshot (CPU CI lowers
    # collectives synchronously, so canonical contracts pin via budgets)
    min_overlap_distance: Optional[int] = None
    max_exposed_comm_fraction: Optional[float] = None
    notes: str = ""


@dataclass
class GraphReport:
    name: str
    module: HloModule
    materialization: Dict
    donation: Dict
    transfers: Dict
    collectives: Dict
    overlap: Dict = field(default_factory=dict)


@dataclass
class Violation:
    graph: str
    rule: str
    message: str
    lines: List[str] = field(default_factory=list)

    def render(self) -> str:
        out = [f"FAIL {self.graph} :: {self.rule}", f"  {self.message}"]
        out += [f"    {l}" for l in self.lines]
        return "\n".join(out)


def analyze(compiled_or_text, name: str = "graph",
            contract: Optional[GraphContract] = None,
            mesh=None) -> GraphReport:
    """Run every analyzer over one compiled graph (a
    ``jax.stages.Compiled``, or raw optimized-HLO text)."""
    if isinstance(compiled_or_text, str):
        text = compiled_or_text
    else:
        text = compiled_or_text.as_text()
    mod = parse_hlo(text)
    rules = contract.ban_rules if contract is not None else ()
    census = collective_census(mod, mesh=mesh)
    return GraphReport(
        name=name, module=mod,
        materialization=materialization_report(mod, rules),
        donation=donation_report(mod),
        transfers=host_transfer_report(mod),
        collectives=census,
        # shares the census's single pairing walk (ISSUE 14)
        overlap=overlap_report(mod, census=census),
    )


# -- contract invariants -----------------------------------------------------

def check_contract(contract: GraphContract,
                   report: GraphReport) -> List[Violation]:
    v: List[Violation] = []
    banned = report.materialization["banned"]
    if banned:
        v.append(Violation(
            report.name, "materialization.ban",
            f"{len(banned)} banned buffer(s) materialized "
            f"(rule: {', '.join(r.label for r in contract.ban_rules)})",
            [h.describe() for h in banned[:8]]))

    if contract.require_aliased:
        mod = report.module
        aliased = set(mod.aliased_param_numbers())
        labels = {n: mod.param_label(n)
                  for n in range(len(mod.entry_param_shapes))}
        for prefix in contract.require_aliased:
            matching = [n for n, l in labels.items()
                        if l.startswith(prefix)]
            if not matching:
                v.append(Violation(
                    report.name, f"donation.require_aliased[{prefix}]",
                    f"no entry parameter labeled '{prefix}*' exists — "
                    f"the contract references a renamed/removed argument"))
                continue
            missing = [n for n in matching if n not in aliased]
            if missing:
                v.append(Violation(
                    report.name, f"donation.require_aliased[{prefix}]",
                    f"{len(missing)}/{len(matching)} '{prefix}*' "
                    f"parameter(s) are NOT donated "
                    f"(input_output_alias has no entry); fix the jit's "
                    f"donate_argnums or waive with a rationale",
                    [f"{labels[n]} "
                     f"({mod.entry_param_shapes[n]})" for n in missing[:8]]))

    ht = report.transfers["host_transfer_count"]
    if ht > contract.max_host_transfers:
        details = (report.transfers["host_callbacks"]
                   + report.transfers["infeed"]
                   + report.transfers["outfeed"]
                   + report.transfers["host_sendrecv"]
                   + report.transfers["host_copies"])
        v.append(Violation(
            report.name, "transfers.max_host_transfers",
            f"{ht} host transfer(s) in a hot graph "
            f"(budget {contract.max_host_transfers}) — a per-step host "
            f"sync re-entered the compiled path", details[:8]))

    if contract.expect_collectives is not None:
        actual = report.collectives["counts"]
        if actual != contract.expect_collectives:
            v.append(Violation(
                report.name, "collectives.expect",
                "collective census diverged from the contract",
                _dict_diff(contract.expect_collectives, actual)))

    ov = report.overlap or {}
    if contract.min_overlap_distance is not None:
        actual_d = ov.get("min_overlap_distance", 0)
        if actual_d < contract.min_overlap_distance:
            v.append(Violation(
                report.name, "overlap.min_overlap_distance",
                f"a collective's start->done window collapsed: contract "
                f"floor {contract.min_overlap_distance} -> actual "
                f"{actual_d} independent op(s) in the window",
                [l for l in [ov.get("min_distance_collective", "")] if l]))
    if contract.max_exposed_comm_fraction is not None:
        actual_f = ov.get("exposed_comm_fraction", 0.0)
        if actual_f > contract.max_exposed_comm_fraction:
            v.append(Violation(
                report.name, "overlap.max_exposed_comm_fraction",
                f"exposed (un-overlapped) comm fraction "
                f"{actual_f:.4f} exceeds the contract ceiling "
                f"{contract.max_exposed_comm_fraction:.4f}",
                [l for l in [ov.get("most_exposed_collective", "")] if l]))
    return v


# -- budget snapshots --------------------------------------------------------

def snapshot_report(report: GraphReport) -> Dict:
    """The JSON-able measured quantities a budget pins."""
    # lazy import: the cost analyzer lives in observability/costs (ISSUE
    # 9) but is driven by THIS module's budget machinery — deferred so
    # `analysis` stays importable for jax-free saved-dump workflows
    from ..observability.costs import attribute_costs
    flops = int(attribute_costs(report.module).total_flops)
    ov = report.overlap or overlap_report(report.module,
                                          census=report.collectives)
    return {
        "largest_intermediate_bytes":
            report.materialization["largest_intermediate_bytes"],
        "donated_bytes": report.donation["donated_bytes"],
        "aliased_param_count": report.donation["aliased_param_count"],
        "undonated_candidates": sorted(
            c.label for c in report.donation["undonated_candidates"]),
        "host_transfer_count": report.transfers["host_transfer_count"],
        "collective_counts": report.collectives["counts"],
        "collective_bytes": report.collectives["total_collective_bytes"],
        # floor: the fused train step's analytical flop count — an op
        # silently falling OUT of the fused/compiled path (a loss head
        # reverting to naive-elsewhere, a layer dropped by a refactor)
        # shows up as a flop drop long before anyone reads a bench row
        "analytical_flops": flops,
        # ISSUE 14: floor on the tightest async start->done window and
        # ceiling on the comm seconds no window compute covers. A graph
        # whose collectives lower synchronously (CPU CI) honestly pins
        # distance 0 / fraction 1.0; a comm-free graph pins 0 / 0.0 —
        # the ceiling then has real teeth: ANY exposed comm appearing
        # later breaks the budget
        "min_overlap_distance": ov["min_overlap_distance"],
        "exposed_comm_fraction": ov["exposed_comm_fraction"],
    }


def _dict_diff(budget: Dict, actual: Dict) -> List[str]:
    lines = []
    for k in sorted(set(budget) | set(actual)):
        b, a = budget.get(k, 0), actual.get(k, 0)
        if b != a:
            lines.append(f"{k}: budget {b} -> actual {a}")
    return lines


def check_budget(report: GraphReport, entry: Dict) -> List[Violation]:
    """Compare a report against one budget-file entry
    (``{"budget": {...}, "waivers": {label: rationale}}``)."""
    budget = entry.get("budget", {})
    waivers = entry.get("waivers", {})
    snap = snapshot_report(report)
    v: List[Violation] = []

    def ceiling(key, why, details=()):
        if key in budget and snap[key] > budget[key]:
            extra = (report.materialization["largest_buffers"][:4]
                     if key == "largest_intermediate_bytes"
                     else list(details))
            v.append(Violation(
                report.name, f"budget.{key}",
                f"{why}: budget {budget[key]:,} -> actual {snap[key]:,} "
                f"(+{snap[key] - budget[key]:,}); intentional? re-pin with "
                f"--update-budgets", extra))

    def floor(key, why, details=()):
        if key in budget and snap[key] < budget[key]:
            v.append(Violation(
                report.name, f"budget.{key}",
                f"{why}: budget {budget[key]:,} -> actual {snap[key]:,} "
                f"({snap[key] - budget[key]:,})", list(details)))

    donated = [a["label"] for a in report.donation["aliased"][:8]]
    ceiling("largest_intermediate_bytes",
            "largest live buffer grew past its budget")
    ceiling("host_transfer_count", "host transfers appeared in a hot graph")
    ceiling("collective_bytes", "collective payload bytes grew")
    floor("donated_bytes",
          "donated bytes dropped — a buffer donation was lost", donated)
    floor("aliased_param_count",
          "fewer parameters are donated than the budget pins", donated)
    floor("analytical_flops",
          "analytical flop count dropped — an op fell out of the "
          "fused/compiled path (intentional? re-pin with "
          "--update-budgets)")

    ov = report.overlap or {}
    floor("min_overlap_distance",
          "a collective's start->done overlap window collapsed — the "
          "latency-hiding scheduler no longer places independent "
          "compute inside the async window",
          [l for l in [ov.get("min_distance_collective", "")] if l])
    ceiling("exposed_comm_fraction",
            "exposed (un-overlapped) comm fraction grew — more of the "
            "collective lane now serializes against compute",
            [l for l in [ov.get("most_exposed_collective", "")] if l])

    if "collective_counts" in budget:
        if snap["collective_counts"] != budget["collective_counts"]:
            v.append(Violation(
                report.name, "budget.collective_counts",
                "collective census changed (an implicit GSPMD "
                "reshard/all-gather, or an intentional graph change — "
                "re-pin with --update-budgets)",
                _dict_diff(budget["collective_counts"],
                           snap["collective_counts"])))

    if "undonated_candidates" in budget:
        known = set(budget["undonated_candidates"]) | set(waivers)
        new = [c for c in report.donation["undonated_candidates"]
               if c.label not in known]
        if new:
            v.append(Violation(
                report.name, "budget.undonated_candidates",
                f"{len(new)} NEW donat-able-but-undonated input(s): donate "
                f"them at the jit site or add a waiver with a rationale",
                [c.describe() for c in new[:8]]))
    return v


# -- budget file I/O ---------------------------------------------------------

def load_budgets(path: str) -> Dict:
    if not os.path.exists(path):
        return {"_meta": {}, "graphs": {}}
    with open(path) as f:
        return json.load(f)


def save_budgets(path: str, budgets: Dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(budgets, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def render_violations(violations: Sequence[Violation]) -> str:
    if not violations:
        return "OK: all graph contracts hold"
    return "\n".join(x.render() for x in violations)
