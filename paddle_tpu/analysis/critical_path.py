"""Critical-path attribution over serving traces (ISSUE 19).

Input: one completed trace dict from
:mod:`paddle_tpu.observability.tracing` — a span tree stitched across
frontdoor, router, breaker and replica processes. Output: *exclusive
self-time per hop* over a time interval, the attribution operators
reason with ("queue ate 60% of the TTFT") and the SLO sentry breaches
on (``pt_trace_ttft_frac{hop=queue}``).

The attribution sweep is deepest-span-wins: the interval is cut at
every span boundary, and each elementary segment is charged to the
deepest span covering it (ties to the latest-started — the innermost
retry). A segment no span covers — or only the root covers — is
``untracked``: the residual the acceptance bound keeps honest (≥95% of
TTFT must land on named hops).

Two intervals matter per trace: TTFT (root start → ``first_tok`` event)
and the worst inter-token gap (consecutive ``tok`` events on the
``fabric::request`` span) — the p99-ITL culprit for that request.

Pure stdlib over plain dicts: importable by the tracer's gauge hook,
the trace_report CLI, and tests without touching JAX.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

__all__ = ["HOPS", "hop_of", "span_depths", "attribute_interval",
           "attribute_trace", "aggregate", "format_table",
           "format_span_tree", "chrome_trace", "export_chrome",
           "load_trace_dir"]

# span-name prefix -> hop, FIRST match wins (most specific first).
# "untracked" for the frontdoor root: its exclusive self-time is
# precisely the time no instrumented hop owns.
HOPS: List[Tuple[str, str]] = [
    ("frontdoor::submit", "accept"),
    ("frontdoor::resume", "resume"),
    ("frontdoor::drain", "stream_drain"),
    ("frontdoor::request", "untracked"),
    ("fabric::queue", "queue"),
    ("fabric::route", "route"),
    ("fabric::submit", "dispatch"),
    ("fabric::handoff", "handoff"),
    ("fabric::request", "router"),
    ("breaker::attempt", "breaker_retry"),
    ("replica::queue", "admission"),
    ("replica::prefill", "prefill"),
    ("replica::decode", "decode"),
    ("replica::resident", "replica_stall"),
]


def hop_of(name: str) -> str:
    for prefix, hop in HOPS:
        if name.startswith(prefix):
            return hop
    return name.rsplit("::", 1)[-1]


def span_depths(trace: dict) -> Dict[str, int]:
    """span_id -> tree depth (root = 0). Orphans (parent missing —
    crashed replica) hang at depth 1 so their time still attributes
    deeper than the root."""
    spans = trace["spans"]
    parent = {s["span_id"]: s["parent_id"] for s in spans}
    depths: Dict[str, int] = {}

    def depth(sid: str, hops: int = 0) -> int:
        if sid in depths:
            return depths[sid]
        if hops > len(parent) + 1:        # cycle guard: corrupt input
            return 1
        p = parent.get(sid)
        if p is None:
            d = 0
        elif p not in parent:
            d = 1                         # orphan: parent never arrived
        else:
            d = depth(p, hops + 1) + 1
        depths[sid] = d
        return d

    for s in spans:
        depth(s["span_id"])
    return depths


def _root_span(trace: dict) -> Optional[dict]:
    rid = trace.get("root")
    for s in trace["spans"]:
        if s["span_id"] == rid:
            return s
    return None


def attribute_interval(trace: dict, t0: float,
                       t1: float) -> Dict[str, float]:
    """Exclusive self-time per hop over [t0, t1]; see module doc.
    An unfinished span (end=None — flagged orphan work) extends to t1:
    the dead replica owned that time until the interval closed."""
    if t1 <= t0:
        return {}
    depths = span_depths(trace)
    root_id = trace.get("root")
    clipped = []
    for s in trace["spans"]:
        a = max(float(s["start"]), t0)
        b = min(t1 if s["end"] is None else float(s["end"]), t1)
        if b > a:
            clipped.append((a, b, depths.get(s["span_id"], 1),
                            float(s["start"]), s))
    cuts = sorted({t0, t1} | {c[0] for c in clipped}
                  | {c[1] for c in clipped})
    out: Dict[str, float] = {}
    for a, b in zip(cuts, cuts[1:]):
        mid = (a + b) / 2.0
        best = None
        for ca, cb, d, st, s in clipped:
            if ca <= mid < cb:
                key = (d, st)
                if best is None or key > best[0]:
                    best = (key, s)
        if best is None or best[1]["span_id"] == root_id:
            hop = "untracked"
        else:
            hop = hop_of(best[1]["name"])
        out[hop] = out.get(hop, 0.0) + (b - a)
    return out


def _tok_events(trace: dict) -> List[Tuple[float, int]]:
    """Token-arrival (ts, n) pairs from the fabric request span (the
    router-side delivery stamps)."""
    evs: List[Tuple[float, int]] = []
    for s in trace["spans"]:
        if s["name"].startswith("fabric::request"):
            for ts, name, n in s.get("events", ()):
                if name == "tok":
                    evs.append((float(ts), int(n)))
    evs.sort()
    return evs


def attribute_trace(trace: dict) -> dict:
    """TTFT + worst-ITL-gap attribution for one trace. Keys:
    ``ttft_s``, ``ttft_hops`` (seconds), ``ttft_frac``, ``untracked_s``,
    ``itl_worst_gap_s``, ``itl_hops``."""
    root = _root_span(trace)
    out = {"trace_id": trace.get("trace_id"), "ttft_s": None,
           "ttft_hops": {}, "ttft_frac": {}, "untracked_s": 0.0,
           "itl_worst_gap_s": None, "itl_hops": {}}
    if root is None:
        return out
    first_tok = None
    for ts, name, _n in root.get("events", ()):
        if name == "first_tok":
            first_tok = float(ts)
            break
    if first_tok is None:                 # fabric-only trace: root IS
        evs = _tok_events(trace)          # fabric::request; use its toks
        if evs:
            first_tok = evs[0][0]
    if first_tok is not None and first_tok > root["start"]:
        ttft = first_tok - root["start"]
        hops = attribute_interval(trace, root["start"], first_tok)
        out["ttft_s"] = ttft
        out["ttft_hops"] = hops
        out["ttft_frac"] = {h: v / ttft for h, v in hops.items()}
        out["untracked_s"] = hops.get("untracked", 0.0)
    evs = _tok_events(trace)
    worst: Optional[Tuple[float, float, float]] = None
    for (ta, _na), (tb, _nb) in zip(evs, evs[1:]):
        gap = tb - ta
        if worst is None or gap > worst[0]:
            worst = (gap, ta, tb)
    if worst is not None and worst[0] > 0:
        out["itl_worst_gap_s"] = worst[0]
        out["itl_hops"] = attribute_interval(trace, worst[1], worst[2])
    return out


def aggregate(traces: List[dict]) -> Dict[str, dict]:
    """Per-hop p50/p99 of TTFT share across traces: hop ->
    {n, p50_s, p99_s, p50_frac, p99_frac}."""
    per_hop: Dict[str, List[Tuple[float, float]]] = {}
    for t in traces:
        att = attribute_trace(t)
        if att["ttft_s"] is None:
            continue
        for hop, sec in att["ttft_hops"].items():
            per_hop.setdefault(hop, []).append(
                (sec, att["ttft_frac"].get(hop, 0.0)))
    def pct(vals, q):
        vals = sorted(vals)
        if not vals:
            return 0.0
        i = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
        return vals[i]
    out: Dict[str, dict] = {}
    for hop, pairs in per_hop.items():
        secs = [p[0] for p in pairs]
        fracs = [p[1] for p in pairs]
        out[hop] = {"n": len(pairs),
                    "p50_s": pct(secs, 0.50), "p99_s": pct(secs, 0.99),
                    "p50_frac": pct(fracs, 0.50),
                    "p99_frac": pct(fracs, 0.99)}
    return out


def format_table(agg: Dict[str, dict]) -> str:
    """The per-hop critical-path table, worst p99 share first."""
    lines = [f"{'hop':<14} {'n':>4} {'p50_ms':>9} {'p99_ms':>9} "
             f"{'p50_frac':>9} {'p99_frac':>9}"]
    for hop, row in sorted(agg.items(),
                           key=lambda kv: -kv[1]["p99_frac"]):
        lines.append(
            f"{hop:<14} {row['n']:>4} {row['p50_s'] * 1e3:>9.2f} "
            f"{row['p99_s'] * 1e3:>9.2f} {row['p50_frac']:>9.3f} "
            f"{row['p99_frac']:>9.3f}")
    return "\n".join(lines)


def format_span_tree(trace: dict) -> str:
    """One trace as an indented tree (children by start time), with
    durations, hop names and noteworthy tags."""
    spans = trace["spans"]
    by_parent: Dict[Optional[str], List[dict]] = {}
    ids = {s["span_id"] for s in spans}
    for s in spans:
        p = s["parent_id"] if s["parent_id"] in ids else None
        by_parent.setdefault(p, []).append(s)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s["start"])
    t0 = min(s["start"] for s in spans) if spans else 0.0
    lines = [f"trace {trace.get('trace_id')} "
             f"(ttft={trace['summary'].get('ttft_s')})"]

    def walk(sid: Optional[str], indent: int) -> None:
        for s in by_parent.get(sid, ()):
            dur = ("open" if s["end"] is None
                   else f"{(s['end'] - s['start']) * 1e3:.2f}ms")
            tags = {k: v for k, v in s["tags"].items()
                    if k in ("outcome", "how", "replica", "state",
                             "reason", "orphan", "unfinished",
                             "readmission", "n")}
            tag_s = f" {tags}" if tags else ""
            lines.append(f"{'  ' * indent}- {s['name']} "
                         f"[+{(s['start'] - t0) * 1e3:.2f}ms "
                         f"{dur}]{tag_s}")
            walk(s["span_id"], indent + 1)

    walk(None, 1)
    return "\n".join(lines)


def chrome_trace(trace: dict) -> dict:
    """Perfetto/chrome-trace JSON for one trace — the profiler
    exporter's shape (complete "X" events, µs timestamps) so the same
    chrome://tracing / Perfetto flow renders request traces too.
    pid = the span's real OS process (cross-process hops land on
    separate tracks), tid = tree depth (nesting stays readable)."""
    depths = span_depths(trace)
    t0 = min((s["start"] for s in trace["spans"]), default=0.0)
    events = []
    for s in trace["spans"]:
        end = s["end"] if s["end"] is not None else s["start"]
        events.append({
            "name": s["name"], "ph": "X", "cat": hop_of(s["name"]),
            "pid": int(s.get("pid", 0)),
            "tid": depths.get(s["span_id"], 1),
            "ts": (s["start"] - t0) * 1e6,
            "dur": max(0.0, (end - s["start"]) * 1e6),
            "args": dict(s.get("tags", {})),
        })
    return {"traceEvents": events,
            "metadata": {"trace_id": trace.get("trace_id"),
                         "source": "paddle_tpu.tracing",
                         "summary": trace.get("summary", {})}}


def export_chrome(trace: dict, path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(chrome_trace(trace), f)
    return path


def load_trace_dir(dir_path: str) -> List[dict]:
    """Every trace in a tracer JSONL dir (torn tails tolerated — the
    exporter's crash contract, one definition)."""
    import os
    from paddle_tpu.observability.exporters import JSONLExporter
    out: List[dict] = []
    if not os.path.exists(dir_path):
        return out
    if os.path.isfile(dir_path):
        return [t for t in JSONLExporter.load_jsonl(dir_path)
                if isinstance(t, dict) and t.get("spans")]
    for name in sorted(os.listdir(dir_path)):
        if name.endswith(".jsonl"):
            out.extend(t for t in JSONLExporter.load_jsonl(
                os.path.join(dir_path, name))
                if isinstance(t, dict) and t.get("spans"))
    return out
