"""Graph contracts: static analysis over lowered/compiled XLA artifacts.

The repo's perf wins are graph-SHAPE properties — no materialized logits
(PR 5), no per-step host sync (PR 2/3), donated carries (PR 2/6), a
designed collective pattern (TP fused CE) — and graph shape is invisible
to numerics tests. This subsystem makes it checkable:

* :mod:`hlo`             — the one parser over optimized-HLO text;
* :mod:`materialization` — buffer bans + largest-intermediate budgets;
* :mod:`donation`        — input/output aliasing audit (donated bytes,
                           donat-able-but-undonated candidates);
* :mod:`transfers`       — host callbacks / infeed / outfeed / host
                           copies inside hot graphs;
* :mod:`collectives`     — per-mesh-axis collective census (the comm
                           table ROADMAP item 3's planner will price),
                           including the ONE start→done pairing walk;
* :mod:`overlap`         — async-collective overlap windows: per-pair
                           distance, priced in-window compute, exposed
                           comm fraction (ISSUE 14 budget kinds);
* :mod:`contracts`       — declarative ``GraphContract`` + JSON budget
                           snapshots with diff-style failures;
* :mod:`graphs`          — canonical compiled entrypoints (train step
                           K=1/K=4, serving tick spec on/off, prefix
                           admit, fused CE) the budgets pin;
* :mod:`trace_lint`      — AST linter for retrace/host-sync hazards in
                           jit-reachable python (waivable inline);
* :mod:`critical_path`   — exclusive self-time per serving hop over the
                           distributed request traces (ISSUE 19):
                           TTFT/ITL attribution, per-hop tables,
                           Perfetto export.

CLI: ``python tools/graph_lint.py`` (tier-1 gated);
``--update-budgets`` re-pins tools/graph_budgets.json preserving waivers.
"""

from . import critical_path as critical_path  # noqa: F401 (re-export)
from .collectives import collective_census, mesh_axis_groups
from .contracts import (BanRule, GraphContract, GraphReport, Violation,
                        analyze, check_budget, check_contract,
                        load_budgets, render_violations, save_budgets,
                        snapshot_report)
from .donation import donation_report
from .graphs import (REGISTRY, BuiltGraph, GraphSkipped, build_graph,
                     graph_names)
from .hlo import HloModule, parse_hlo
from .materialization import banned_buffers, materialization_report
from .overlap import (OverlapWindow, UnmatchedCollectiveError,
                      overlap_report)
from .transfers import host_transfer_report

__all__ = [
    "analyze", "parse_hlo", "HloModule",
    "BanRule", "GraphContract", "GraphReport", "Violation",
    "check_budget", "check_contract", "snapshot_report",
    "load_budgets", "save_budgets", "render_violations",
    "materialization_report", "banned_buffers", "donation_report",
    "host_transfer_report", "collective_census", "mesh_axis_groups",
    "OverlapWindow", "UnmatchedCollectiveError", "overlap_report",
    "REGISTRY", "BuiltGraph", "GraphSkipped", "build_graph", "graph_names",
    "critical_path",
]
