"""Donation audit over the compiled module's input/output aliasing table.

XLA records buffer donation as ``input_output_alias`` in the module header;
jax's ``donate_argnums`` is only a *request* — a silently dropped donation
(an arg reordered, a wrapper rebuilt without the argnums, jax.export's
call wrapper which forgets them entirely) doubles the HBM footprint of
whatever was being threaded (params+opt_state in training, KV pools and
the speculation history in serving) without failing a single numerics
test. This pass turns the aliasing table into facts a contract can pin:

* ``aliased`` — which entry parameters ARE donated (label, bytes, kind),
  with ``donated_bytes`` as the budget-floor metric (a refactor that
  drops a donation shrinks it and fails the snapshot);
* ``undonated_candidates`` — parameters that are NOT aliased but whose
  (shape, dtype) matches a not-yet-aliased output leaf, i.e. buffers XLA
  *could* have reused in place. Matching is structural, so persistent
  inputs (sampling knobs read every tick) show up too — that is what the
  budget file's per-graph ``waivers`` are for: each candidate is either
  fixed at the jit site or waived WITH A RATIONALE, and a new candidate
  appearing (someone added a threaded buffer without donating it) fails
  the check until triaged.

Parameter labels come from the parameter instructions' op_name metadata
(``pools[0][0]``, ``opt_state['...']``), so reports name the python-level
argument, not an XLA parameter number.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

from .hlo import HloModule

__all__ = ["DonationCandidate", "donation_report"]


@dataclass
class DonationCandidate:
    param_number: int
    label: str
    shape: str
    bytes: int

    def describe(self) -> str:
        return f"{self.label} ({self.shape}, {self.bytes:,} B)"


def donation_report(mod: HloModule) -> Dict:
    """Aliasing facts + donat-able-but-undonated candidates."""
    aliased_params = set(mod.aliased_param_numbers())
    aliased_out_leaves = {a.output_index for a in mod.aliases}

    aliased = []
    donated_bytes = 0
    for a in mod.aliases:
        shape = (mod.entry_param_shapes[a.param_number]
                 if a.param_number < len(mod.entry_param_shapes) else None)
        nbytes = shape.bytes if shape is not None else 0
        donated_bytes += nbytes
        aliased.append({
            "param": a.param_number,
            "label": mod.param_label(a.param_number),
            "shape": str(shape) if shape is not None else "?",
            "bytes": nbytes,
            "kind": a.kind,
            "output_index": list(a.output_index),
        })

    # output leaves not already backed by a donated input, keyed by
    # (dtype, dims) — the pool a donat-able input could have aliased into
    free_outputs: Dict[tuple, int] = {}
    for i, leaf in enumerate(mod.entry_output_shapes):
        if (i,) in aliased_out_leaves or leaf.dims == ():
            continue
        key = (leaf.dtype, leaf.dims)
        free_outputs[key] = free_outputs.get(key, 0) + 1

    candidates: List[DonationCandidate] = []
    for num, shape in enumerate(mod.entry_param_shapes):
        if num in aliased_params or shape.dims == ():
            continue            # scalars are not worth a donation slot
        key = (shape.dtype, shape.dims)
        if free_outputs.get(key, 0) > 0:
            free_outputs[key] -= 1
            candidates.append(DonationCandidate(
                num, mod.param_label(num), str(shape), shape.bytes))
    candidates.sort(key=lambda c: -c.bytes)

    return {
        "aliased": aliased,
        "aliased_param_count": len(aliased_params),
        "donated_bytes": donated_bytes,
        "undonated_candidates": candidates,
        "undonated_candidate_bytes": sum(c.bytes for c in candidates),
    }
