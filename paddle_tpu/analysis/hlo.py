"""Structured view over XLA's optimized-HLO text dump.

Every graph-contract analyzer (materialization, donation, host-sync,
collective census) consumes ``jax.jit(...).lower(...).compile().as_text()``
through this ONE parser, so the regexes that understand HLO live in exactly
one place. The parser is deliberately text-based: the HLO proto bindings
differ across jaxlib versions, while the text format (instruction lines,
``input_output_alias`` header, ``replica_groups`` attributes) has been
stable for years and is what the repo's hand-rolled guards (PR 5's
``_bsv_buffers``) already matched against.

Parsed facts:

* **instructions** — every ``%name = shape opcode(...)`` line across every
  computation, with opcode, output shape leaves (dtype, dims, bytes),
  ``metadata={op_name=...}`` attribution and the raw attribute tail
  (``replica_groups``, ``channel_id``, ``custom_call_target`` live there);
* **input_output_alias** — the donation table from the module header:
  which output buffer aliases which entry parameter (``may-alias`` /
  ``must-alias``);
* **entry parameters** — number → (shape, jax-level name from the
  parameter instruction's op_name metadata), the names donation reports
  are keyed on (``pools[0][0]``, ``opt_state['m']['...']``).

Nothing here imports jax: the analyzers stay usable on a saved ``.hlo``
dump (e.g. one captured from a real pod) without a device in sight.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "ShapeLeaf", "HloInstruction", "HloComputation", "HloModule",
    "parse_hlo", "parse_shape", "dtype_bytes",
]

# XLA primitive-type byte widths (token/opaque/tuple carry no payload)
_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "f8e4m3fnuz": 1, "f8e5m2fnuz": 1, "f8e3m4": 1, "f8e4m3": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "token": 0, "opaque": 0,
}


def dtype_bytes(dtype: str) -> int:
    return _DTYPE_BYTES.get(dtype, 4)


@dataclass(frozen=True)
class ShapeLeaf:
    """One array shape inside an instruction's (possibly tuple) result."""
    dtype: str
    dims: Tuple[int, ...]

    @property
    def num_elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def bytes(self) -> int:
        return self.num_elements * dtype_bytes(self.dtype)

    def __str__(self) -> str:
        return f"{self.dtype}[{','.join(str(d) for d in self.dims)}]"


@dataclass
class HloInstruction:
    name: str                       # %foo.12 (sans %)
    opcode: str                     # add / all-reduce / custom-call / ...
    shape_leaves: List[ShapeLeaf]
    computation: str                # owning computation's name
    is_entry: bool                  # defined in the ENTRY computation
    raw: str                        # full source line (attrs live here)
    op_name: str = ""               # metadata={op_name="..."} if present
    source: str = ""                # source_file:source_line if present

    @property
    def bytes(self) -> int:
        return sum(l.bytes for l in self.shape_leaves)

    def attr(self, key: str) -> Optional[str]:
        """Raw attribute text, e.g. attr("replica_groups") ->
        "{{0,1},{2,3}}", attr("custom_call_target") -> 'xla_..._callback'."""
        m = re.search(re.escape(key) + r"=", self.raw)
        if not m:
            return None
        rest = self.raw[m.end():]
        if rest.startswith("{"):
            depth = 0
            for i, ch in enumerate(rest):
                if ch == "{":
                    depth += 1
                elif ch == "}":
                    depth -= 1
                    if depth == 0:
                        return rest[:i + 1]
            return rest
        if rest.startswith('"'):
            end = rest.find('"', 1)
            return rest[1:end] if end > 0 else rest[1:]
        vm = re.match(r"[\w.\-]+", rest)
        return vm.group(0) if vm else None


@dataclass
class HloComputation:
    name: str
    is_entry: bool
    instructions: List[HloInstruction] = field(default_factory=list)


@dataclass
class AliasEntry:
    """One ``input_output_alias`` record: entry-output leaf ``output_index``
    is backed by entry-parameter ``param_number`` (leaf ``param_index``
    within that parameter, almost always () under jax's flat calling
    convention)."""
    output_index: Tuple[int, ...]
    param_number: int
    param_index: Tuple[int, ...]
    kind: str                       # may-alias | must-alias


@dataclass
class HloModule:
    name: str
    text: str
    computations: List[HloComputation]
    aliases: List[AliasEntry]
    entry_param_shapes: List[ShapeLeaf]
    entry_param_names: Dict[int, str]       # number -> jax op_name label
    entry_output_shapes: List[ShapeLeaf]

    # -- convenience views ---------------------------------------------------

    @property
    def instructions(self) -> Iterable[HloInstruction]:
        for c in self.computations:
            for ins in c.instructions:
                yield ins

    def find(self, opcode: str) -> List[HloInstruction]:
        return [i for i in self.instructions if i.opcode == opcode]

    def aliased_param_numbers(self) -> List[int]:
        return sorted({a.param_number for a in self.aliases})

    def param_label(self, number: int) -> str:
        return self.entry_param_names.get(number, f"param#{number}")


_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# `  ROOT %name = <shape+layout> opcode(...)`; shape may be a tuple
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(\([^=]*?\)|[a-z][a-z0-9]*\[[0-9,]*\]"
    r"(?:\{[^}]*\})?)\s+([\w\-]+)\(")
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\((.*)$")
_META_RE = re.compile(
    r"metadata=\{[^}]*?op_name=\"([^\"]*)\"[^}]*?"
    r"(?:source_file=\"([^\"]*)\"[^}]*?source_line=(\d+))?[^}]*\}")
_ALIAS_ENTRY_RE = re.compile(
    r"\{([0-9,\s]*)\}:\s*\((\d+),\s*\{([0-9,\s]*)\},\s*([\w\-]+)\)")


def parse_shape(text: str) -> List[ShapeLeaf]:
    """Every array leaf mentioned in a shape string — handles scalars
    (``f32[]``), arrays and (nested) tuples."""
    leaves = []
    for dtype, dims in _SHAPE_RE.findall(text):
        if dtype in ("token", "opaque"):
            leaves.append(ShapeLeaf(dtype, ()))
            continue
        t = tuple(int(d) for d in dims.split(",")) if dims else ()
        leaves.append(ShapeLeaf(dtype, t))
    return leaves


def _parse_index(text: str) -> Tuple[int, ...]:
    text = text.strip()
    if not text:
        return ()
    return tuple(int(x) for x in text.split(","))


def parse_hlo(text: str) -> HloModule:
    """Parse one HLO module dump (``Compiled.as_text()``)."""
    mod_name = ""
    m = re.search(r"HloModule\s+([\w.\-]+)", text)
    if m:
        mod_name = m.group(1)

    aliases: List[AliasEntry] = []
    start = text.find("input_output_alias={")
    if start >= 0:
        # brace-counted block: the table nests {output_index} inside the
        # outer braces, so a regex-to-first-close silently drops it all
        i = start + len("input_output_alias=")
        depth, end = 0, i
        for j in range(i, min(len(text), i + 200_000)):
            if text[j] == "{":
                depth += 1
            elif text[j] == "}":
                depth -= 1
                if depth == 0:
                    end = j + 1
                    break
        block = text[i:end]
        for out_idx, pnum, pidx, kind in _ALIAS_ENTRY_RE.findall(block):
            aliases.append(AliasEntry(_parse_index(out_idx), int(pnum),
                                      _parse_index(pidx), kind))

    computations: List[HloComputation] = []
    current: Optional[HloComputation] = None
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped:
            continue
        # computation header: `%comp (args) -> shape {` or `ENTRY %main ...`
        if not stripped.startswith("%") or " = " not in stripped:
            cm = _COMP_RE.match(stripped)
            if cm and stripped.rstrip().endswith("{"):
                current = HloComputation(name=cm.group(2),
                                         is_entry=bool(cm.group(1)))
                computations.append(current)
                continue
        # long tuple shapes/operand lists carry /*index=N*/ comments whose
        # '=' breaks the shape match — strip them before parsing
        clean = re.sub(r"/\*.*?\*/", "", line)
        im = _INSTR_RE.match(clean)
        if im and current is not None:
            name, shape_txt, opcode = im.groups()
            ins = HloInstruction(
                name=name, opcode=opcode,
                shape_leaves=parse_shape(shape_txt),
                computation=current.name, is_entry=current.is_entry,
                raw=line)
            mm = _META_RE.search(line)
            if mm:
                # the dump escapes quotes inside op_name (params[\'w\'])
                ins.op_name = mm.group(1).replace("\\'", "'").replace(
                    '\\"', '"')
                if mm.group(2):
                    ins.source = f"{mm.group(2)}:{mm.group(3)}"
            current.instructions.append(ins)

    entry = next((c for c in computations if c.is_entry), None)
    param_shapes: List[ShapeLeaf] = []
    param_names: Dict[int, str] = {}
    out_shapes: List[ShapeLeaf] = []
    if entry is not None:
        params = {}
        for ins in entry.instructions:
            if ins.opcode != "parameter":
                continue
            pm = re.search(r"parameter\((\d+)\)", ins.raw)
            if not pm:
                continue
            num = int(pm.group(1))
            params[num] = ins
            if ins.op_name:
                param_names[num] = ins.op_name
        for num in sorted(params):
            leaves = params[num].shape_leaves
            param_shapes.append(leaves[0] if leaves else ShapeLeaf("token",
                                                                  ()))
        root = entry.instructions[-1] if entry.instructions else None
        for ins in entry.instructions:
            if "ROOT" in ins.raw.split("=")[0]:
                root = ins
        if root is not None:
            out_shapes = list(root.shape_leaves)

    return HloModule(name=mod_name, text=text, computations=computations,
                     aliases=aliases, entry_param_shapes=param_shapes,
                     entry_param_names=param_names,
                     entry_output_shapes=out_shapes)
