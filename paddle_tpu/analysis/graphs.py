"""Canonical-graph registry: the compiled entrypoints whose shape the
graph contracts pin.

Each builder constructs the REAL jitted program (the Trainer's step jit,
the serving engine's decode/spec tick, the prefix-hit admit dispatch, the
fused CE head) at a micro model size, lowers+compiles it for the current
backend, and returns it with its contract. Builders reach into the same
internals the runtime dispatches through (``Trainer._step_jit``,
``ContinuousBatchingEngine._build_decode``...), so a refactor that
changes what those paths compile changes exactly what the lint sees —
there is no parallel "model of the model" to drift.

Sizes are chosen so the banned-shape signatures are unambiguous
(B*S and V collide with no other dimension product) and a full
``build_all`` stays test-suite-cheap on CPU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from .contracts import BanRule, GraphContract

__all__ = ["BuiltGraph", "GraphSkipped", "REGISTRY", "build_graph",
           "graph_names"]

# micro-Llama the canonical graphs share. V=320 and B*S=40 are chosen so
# the banned-shape signature [*, V] x prod(*)==B*S collides with nothing:
# hidden=64, gate_up=2*96=192, qkv=128 — no other buffer has a 320 last
# dim (V=256 collided with the MLP's 2*intermediate and turned every
# gate_up activation into a false logits hit)
_B, _S = 2, 20
_VOCAB, _HIDDEN = 320, 64


class GraphSkipped(Exception):
    """Raised by a builder whose environment can't host the graph (e.g.
    the dp2xtp2 census graph on a single-device process)."""


@dataclass
class BuiltGraph:
    name: str
    compiled: object                   # jax.stages.Compiled
    contract: GraphContract
    mesh: Optional[object] = None
    #: the concrete arrays the graph was lowered on — lets the cost probe
    #: (tools/op_cost_probe.py) EXECUTE the canonical graph for measured
    #: timings (donation-safe: the probe copies per call)
    example_args: Optional[tuple] = None


def _micro_cfg():
    from ..models import LlamaConfig
    return LlamaConfig(vocab_size=_VOCAB, hidden_size=_HIDDEN,
                       intermediate_size=96, num_hidden_layers=2,
                       num_attention_heads=4, num_key_value_heads=2,
                       max_position_embeddings=128)


def _micro_model():
    import paddle_tpu as pt
    pt.seed(0)
    from ..models import LlamaForCausalLM
    return LlamaForCausalLM(_micro_cfg())


def _trainer():
    from ..optimizer import AdamW
    from ..trainer import Trainer
    model = _micro_model()
    tr = Trainer(model, AdamW(learning_rate=1e-4, parameters=model))
    tr._ensure_built()
    return tr


def _batch():
    import jax.numpy as jnp
    return {"input_ids": jnp.zeros((_B, _S), jnp.int32),
            "labels": jnp.zeros((_B, _S), jnp.int32)}


_TRAIN_CONTRACT_KW = dict(
    # the PR 5 property: no [B,S,V]/[B*S,V] logits buffer, any dtype
    ban_rules=(BanRule(_VOCAB, _B * _S, label="BSV-logits"),),
    require_aliased=("params", "opt_state"),
    max_host_transfers=0,
)


def build_train_step_k1() -> BuiltGraph:
    """Trainer._dispatch's per-step program: fused-CE loss + grads +
    AdamW update, params/opt_state donated."""
    tr = _trainer()
    args = (tr.params, tr.opt_state, _batch(), tr._lr_scalar(),
            tr._key_data())
    compiled = tr._step_jit.lower(*args).compile()
    return BuiltGraph("train_step_k1", compiled, GraphContract(
        "train_step_k1", notes="per-step trainer dispatch",
        **_TRAIN_CONTRACT_KW), example_args=args)


def build_train_step_k4() -> BuiltGraph:
    """The superstep: K=4 optimizer steps in one lax.scan dispatch
    (PR 2's no-per-step-host-work property rides on transfers==0)."""
    import jax.numpy as jnp

    from ..io.dataloader import stack_batches
    tr = _trainer()
    stack = stack_batches([_batch()] * 4)
    args = (tr.params, tr.opt_state, stack, jnp.zeros((4,), jnp.float32),
            tr._key_data())
    compiled = tr._superstep_jit.lower(*args).compile()
    return BuiltGraph("train_step_k4", compiled, GraphContract(
        "train_step_k4", notes="K=4 superstep scan",
        **_TRAIN_CONTRACT_KW), example_args=args)


def _engine(**kw):
    import jax.numpy as jnp

    from ..inference.serving import ContinuousBatchingEngine
    model = _micro_model()
    eng = ContinuousBatchingEngine(model, max_batch=2, page_size=8,
                                   max_len=64, **kw)
    eng._init_state(jnp.zeros((_VOCAB,), jnp.float32))
    return eng


def build_serving_tick() -> BuiltGraph:
    """The non-speculative decode tick (K=4 paged scan): pools donated,
    stop detection on device, zero host transfers."""
    import jax.numpy as jnp
    eng = _engine()
    fn = eng._build_decode(4, any_sample=False, attn_impl="paged")
    args = (eng._params, eng.pools, jnp.asarray(eng.tables),
            eng._base_key, eng._state, eng._knobs)
    compiled = fn.lower(*args).compile()
    return BuiltGraph("serving_tick", compiled, GraphContract(
        "serving_tick", require_aliased=("pools",),
        max_host_transfers=0,
        notes="decode_block=4 paged scan, spec off"), example_args=args)


def build_serving_tick_quant() -> BuiltGraph:
    """The quantized decode tick (ISSUE 17): int8 weights + int8 KV
    pages. Beyond the plain tick's contract (pools donated, zero host
    transfers), NO widened pool-shaped f32/bf16 buffer may materialize:
    dequant must stay fused into the attention read — per-sequence
    gather working sets are fine, a whole-pool dequant pass is the
    regression the ban exists for. num_pages is deliberately NOT
    max_batch*pages_per_seq so the pool shape cannot collide with the
    legitimate gathered working set's dims."""
    import jax.numpy as jnp

    from ..inference.serving import ContinuousBatchingEngine
    from ..quantization import quantize_model
    model = quantize_model(_micro_model(), kv_dtype="int8")
    eng = ContinuousBatchingEngine(model, max_batch=2, page_size=8,
                                   max_len=64, num_pages=24)
    eng._init_state(jnp.zeros((_VOCAB,), jnp.float32))
    fn = eng._build_decode(4, any_sample=False, attn_impl="paged")
    args = (eng._params, eng.pools, jnp.asarray(eng.tables),
            eng._base_key, eng._state, eng._knobs)
    compiled = fn.lower(*args).compile()
    hkv, npages, ps, hd = eng.pools[0][0].shape
    return BuiltGraph("serving_tick_quant", compiled, GraphContract(
        "serving_tick_quant", require_aliased=("pools",),
        max_host_transfers=0,
        ban_rules=(BanRule(hd, hkv * npages * ps, label="f32-pool",
                           dtype="f32"),
                   BanRule(hd, hkv * npages * ps, label="bf16-pool",
                           dtype="bf16")),
        notes="decode_block=4 paged scan, int8 weights + int8 KV"),
        example_args=args)


def build_serving_tick_spec() -> BuiltGraph:
    """The speculative tick (draft + (k+1)-wide verify + commit): pools
    AND the [B, max_len] history carry donated — un-donating either is a
    contract failure (the ISSUE 8 acceptance case)."""
    import jax.numpy as jnp
    eng = _engine(spec_k=3)
    fn = eng._build_spec_decode(3, any_sample=False)
    args = (eng._params, eng.pools, jnp.asarray(eng.tables),
            eng._base_key, eng._state, eng._knobs, eng._hist)
    compiled = fn.lower(*args).compile()
    return BuiltGraph("serving_tick_spec", compiled, GraphContract(
        "serving_tick_spec", require_aliased=("pools", "hist"),
        max_host_transfers=0,
        notes="spec_k=3 draft+verify tick"), example_args=args)


def build_prefix_admit() -> BuiltGraph:
    """The full-prompt-hit admit dispatch: COW of the boundary page fused
    with the single-token logits re-forward — ONE dispatch, pools
    donated."""
    import jax.numpy as jnp
    eng = _engine()
    fn = eng._tail_logits_fn()
    args = (eng._params, jnp.zeros((1, 1), jnp.int32),
            jnp.zeros((1,), jnp.int32), eng.pools,
            jnp.asarray(eng.tables[0:1]), jnp.int32(1),
            jnp.int32(2))
    compiled = fn.lower(*args).compile()
    return BuiltGraph("prefix_admit", compiled, GraphContract(
        "prefix_admit", require_aliased=("pools",),
        max_host_transfers=0,
        notes="prefix-hit COW + 1-token re-forward"), example_args=args)


def build_fused_ce() -> BuiltGraph:
    """The fused vocab-CE primitive, fwd+bwd, standalone: the op-level
    version of the train-step ban (no [N, V] block)."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas.fused_vocab_ce import fused_linear_cross_entropy
    N, H = 64, 32
    h = jnp.zeros((N, H), jnp.float32)
    w = jnp.zeros((H, _VOCAB), jnp.float32)
    lab = jnp.zeros((N,), jnp.int32)

    def loss(h, w):
        return fused_linear_cross_entropy(h, w, lab, block_n=16,
                                          block_v=64, impl="xla")

    compiled = jax.jit(
        jax.value_and_grad(loss, argnums=(0, 1))).lower(h, w).compile()
    return BuiltGraph("fused_ce", compiled, GraphContract(
        "fused_ce",
        ban_rules=(BanRule(_VOCAB, N, label="NV-logits"),),
        max_host_transfers=0,
        notes="lse_and_target fwd+bwd, xla impl"), example_args=(h, w))


def build_tp_fused_ce() -> BuiltGraph:
    """TP composition of the fused CE head on a dp=2 x tp=2 mesh: the
    collective census contract — exactly one pmax + two psums over the tp
    axis (global LSE + target logit), and NO all-gather (an implicit
    GSPMD reshard re-materializing a vocab shard)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    if jax.device_count() < 4:
        raise GraphSkipped("needs >= 4 devices (dp=2 x tp=2 mesh); run "
                           "under XLA_FLAGS=--xla_force_host_platform_"
                           "device_count=8")
    from ..parallel import HybridMesh, shard_tensor
    from ..parallel.mp_layers import parallel_fused_linear_cross_entropy

    hm = HybridMesh.build(dp=2, tp=2, devices=jax.devices()[:4])
    B, S, H = 2, 16, _HIDDEN
    rs = np.random.RandomState(0)
    h = jnp.asarray(rs.randn(B, S, H).astype(np.float32))
    w = jnp.asarray(rs.randn(H, _VOCAB).astype(np.float32) * 0.1)
    lab = jnp.asarray(rs.randint(0, _VOCAB, (B, S)))
    with hm:
        h_s = shard_tensor(h, spec=P("dp", None, None))
        w_s = shard_tensor(w, spec=P(None, "tp"))
        lab_s = shard_tensor(lab, spec=P("dp", None))
        f = jax.jit(lambda h, w, l: parallel_fused_linear_cross_entropy(
            h, w, l, mesh=hm, block_n=8, block_v=64))
        compiled = f.lower(h_s, w_s, lab_s).compile()
    return BuiltGraph("tp_fused_ce", compiled, GraphContract(
        "tp_fused_ce",
        ban_rules=(BanRule(_VOCAB, B * S, label="global-logits"),),
        max_host_transfers=0,
        expect_collectives={"all-reduce[tp]": 3},
        notes="dp2xtp2 shard_map fused CE: pmax + 2 psum, 0 all-gather"),
        mesh=hm)


def build_planner() -> BuiltGraph:
    """The sharding planner's emit/price contract (ISSUE 11): price the
    dp2×tp2 micro-model config, then compile the train step THROUGH the
    emitted ``ShardingPlan`` (``Trainer.apply_plan`` — the consumer
    path) and require the emitted graph's collective census to EXACTLY
    match the priced census the planner ranked with. A pricing/emission
    divergence (plan says replicate, runtime shards — or vice versa)
    changes the census and fails CI like any other contract."""
    import jax

    if jax.device_count() < 4:
        raise GraphSkipped("needs >= 4 devices (dp=2 x tp=2 mesh); run "
                           "under XLA_FLAGS=--xla_force_host_platform_"
                           "device_count=8")
    import numpy as np
    import jax.numpy as jnp

    import paddle_tpu as pt
    from ..distributed.auto_parallel import (ParallelConfig,
                                             price_config)
    from ..models import LlamaForCausalLM
    from ..optimizer import AdamW
    from ..trainer import Trainer

    cfg = _micro_cfg()
    priced = price_config(ParallelConfig(dp=2, tp=2), cfg,
                          devices=jax.devices()[:4], global_batch=4,
                          seq_len=32, check_memory=False)

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    tr = Trainer(model, AdamW(learning_rate=1e-3, parameters=model),
                 donate=False)
    hm = tr.apply_plan(priced.plan, devices=jax.devices()[:4])
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (4, 33))
    with hm:
        batch = priced.plan.shard_batch(
            {"input_ids": jnp.asarray(ids[:, :-1]),
             "labels": jnp.asarray(ids[:, 1:])}, hm)
        tr._ensure_built()
        args = (tr.params, tr.opt_state, batch, tr._lr_scalar(),
                tr._key_data())
        compiled = tr._step_jit.lower(*args).compile()
    return BuiltGraph("planner", compiled, GraphContract(
        "planner",
        expect_collectives=dict(priced.graph.census_counts),
        max_host_transfers=0,
        notes=f"emitted {priced.config} plan == priced census "
              f"(closed set)"),
        mesh=hm, example_args=args)


def build_train_step_fsdp() -> BuiltGraph:
    """The ZeRO-3 train step (ISSUE 18): price the fsdp2×tp2 micro
    config, then compile the step THROUGH the emitted plan
    (``Trainer.apply_plan``) and require the emitted census to EXACTLY
    match the priced one (closed set) — the fsdp axis's param
    all-gathers and grad reduce-scatters are part of that set, so a
    refactor that drops the sharding (silently replicating params) or
    doubles the gathers fails CI. The budget snapshot additionally pins
    ``exposed_comm_fraction``/``min_overlap_distance`` over the gather
    windows: a serialized all-gather regression is a budget diff, not a
    silent 2× step-time tax."""
    import jax

    if jax.device_count() < 4:
        raise GraphSkipped("needs >= 4 devices (fsdp=2 x tp=2 mesh); "
                           "run under XLA_FLAGS=--xla_force_host_"
                           "platform_device_count=8")
    import numpy as np
    import jax.numpy as jnp

    import paddle_tpu as pt
    from ..distributed.auto_parallel import (ParallelConfig,
                                             price_config)
    from ..models import LlamaForCausalLM
    from ..optimizer import AdamW
    from ..trainer import Trainer

    cfg = _micro_cfg()
    priced = price_config(ParallelConfig(fsdp=2, tp=2), cfg,
                          devices=jax.devices()[:4], global_batch=4,
                          seq_len=32, check_memory=False)

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    tr = Trainer(model, AdamW(learning_rate=1e-3, parameters=model),
                 donate=False)
    hm = tr.apply_plan(priced.plan, devices=jax.devices()[:4])
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (4, 33))
    with hm:
        batch = priced.plan.shard_batch(
            {"input_ids": jnp.asarray(ids[:, :-1]),
             "labels": jnp.asarray(ids[:, 1:])}, hm)
        tr._ensure_built()
        args = (tr.params, tr.opt_state, batch, tr._lr_scalar(),
                tr._key_data())
        compiled = tr._step_jit.lower(*args).compile()
    return BuiltGraph("train_step_fsdp", compiled, GraphContract(
        "train_step_fsdp",
        expect_collectives=dict(priced.graph.census_counts),
        max_host_transfers=0,
        notes=f"emitted {priced.config} ZeRO-3 plan == priced census "
              f"(closed set, gather windows budget-pinned)"),
        mesh=hm, example_args=args)


def build_train_step_moe_ep() -> BuiltGraph:
    """The expert-parallel MoE train step (ISSUE 20): price the
    ep-pure dp2_ep2 micro config — the shard_map dispatch path, so the
    census carries the real ``all-to-all[ep]`` rows, not a GSPMD
    approximation — compile the step THROUGH the emitted plan and
    require the emitted census to EXACTLY match the priced one (closed
    set). A refactor that drops the expert all-to-all (silently
    replicating experts) or doubles it fails CI as a census diff."""
    import jax

    if jax.device_count() < 2:
        raise GraphSkipped("needs >= 2 devices (dp=2/ep=2 subgroup "
                           "mesh); run under XLA_FLAGS=--xla_force_"
                           "host_platform_device_count=8")
    import numpy as np
    import jax.numpy as jnp

    import paddle_tpu as pt
    from ..distributed.auto_parallel import (ParallelConfig,
                                             price_config)
    from ..models import MoEForCausalLM
    from ..models.moe_lm import MoEConfig
    from ..optimizer import AdamW
    from ..trainer import Trainer

    cfg = MoEConfig(vocab_size=_VOCAB, hidden_size=_HIDDEN,
                    intermediate_size=96, moe_intermediate_size=48,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, num_experts=4,
                    num_experts_per_tok=2, num_shared_experts=1,
                    first_k_dense_replace=1, capacity_factor=None,
                    max_position_embeddings=128)
    priced = price_config(ParallelConfig(dp=2, ep=2), cfg,
                          devices=jax.devices()[:2], global_batch=4,
                          seq_len=32, check_memory=False)

    pt.seed(0)
    model = MoEForCausalLM(cfg)
    tr = Trainer(model, AdamW(learning_rate=1e-3, parameters=model),
                 donate=False)
    hm = tr.apply_plan(priced.plan, devices=jax.devices()[:2])
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (4, 33))
    with hm:
        batch = priced.plan.shard_batch(
            {"input_ids": jnp.asarray(ids[:, :-1]),
             "labels": jnp.asarray(ids[:, 1:])}, hm)
        tr._ensure_built()
        args = (tr.params, tr.opt_state, batch, tr._lr_scalar(),
                tr._key_data())
        compiled = tr._step_jit.lower(*args).compile()
    return BuiltGraph("train_step_moe_ep", compiled, GraphContract(
        "train_step_moe_ep",
        expect_collectives=dict(priced.graph.census_counts),
        max_host_transfers=0,
        notes=f"emitted {priced.config} expert-parallel plan == priced "
              f"census (closed set incl. all-to-all[ep])"),
        mesh=hm, example_args=args)


REGISTRY: Dict[str, Callable[[], BuiltGraph]] = {
    "train_step_k1": build_train_step_k1,
    "train_step_k4": build_train_step_k4,
    "serving_tick": build_serving_tick,
    "serving_tick_quant": build_serving_tick_quant,
    "serving_tick_spec": build_serving_tick_spec,
    "prefix_admit": build_prefix_admit,
    "fused_ce": build_fused_ce,
    "tp_fused_ce": build_tp_fused_ce,
    "planner": build_planner,
    "train_step_fsdp": build_train_step_fsdp,
    "train_step_moe_ep": build_train_step_moe_ep,
}


def graph_names() -> List[str]:
    return list(REGISTRY)


def build_graph(name: str) -> BuiltGraph:
    return REGISTRY[name]()
