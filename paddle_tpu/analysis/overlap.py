"""Overlap analyzer: how much of each collective's latency is hidden.

The paper's fleet stack exists to hide communication behind compute
(mp_async_allreduce, allreduce_matmul_grad_overlapping); on TPU the
equivalent lever is XLA's latency-hiding scheduler placing async
collectives as ``<op>-start`` / ``<op>-done`` pairs with independent
compute scheduled inside the window. This module turns that placement
into a MEASURABLE, BUDGETABLE artifact:

* every ``-start`` is paired with its ``-done`` — the pairing itself is
  recorded by the collective census (``analysis/collectives.py``) during
  its single module walk; this analyzer only CONSUMES those indices, so
  there is exactly one pairing definition in the repo;
* the **overlap distance** of a pair is the number of priced (nonzero
  flop/byte) non-collective instructions strictly between start and done
  — ops that by construction cannot consume the in-flight result and are
  therefore schedulable concurrently with the transfer;
* the window's **compute seconds** price those instructions against the
  device roofline (``max(flops/peak, bytes/hbm_bw)`` per op, via the
  ISSUE 9 cost walker — no second flop formula);
* a collective's **exposed** seconds are its priced comm time minus the
  window compute covering it (floored at zero); a synchronously lowered
  collective (no ``-start``) has a zero-width window and is fully
  exposed by definition.

``min_overlap_distance`` (floor) and ``max_exposed_comm_fraction``
(ceiling) become graph-budget kinds: ``tools/graph_lint.py`` fails when
a start→done window collapses, the same way it fails when the logits
re-materialize. An unmatched ``-start`` (truncated module, parser miss)
raises :class:`UnmatchedCollectiveError` naming the op rather than
silently reporting the collective as free.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from .collectives import COLLECTIVE_OPS, collective_census
from .hlo import HloModule

__all__ = ["OverlapWindow", "UnmatchedCollectiveError", "overlap_report"]


class UnmatchedCollectiveError(ValueError):
    """An async collective ``-start`` has no matching ``-done``."""


@dataclass
class OverlapWindow:
    """One collective and the compute scheduled inside its window."""
    name: str                    # HLO instruction name of the (start) op
    opcode: str                  # base opcode (suffix stripped)
    axis: str
    op_name: str
    is_async: bool
    index: int                   # module-walk position of the start
    done_index: Optional[int]    # position of the paired -done
    distance: int                # priced independent ops inside window
    window_compute_s: float      # roofline seconds of those ops
    comm_s: float                # priced transfer seconds (census bw)
    hidden_s: float              # min(comm_s, window_compute_s)
    exposed_s: float             # comm_s - hidden_s

    def describe(self) -> str:
        kind = "async" if self.is_async else "sync"
        return (f"{self.opcode}[{self.axis}] %{self.name} ({kind}) "
                f"distance={self.distance} "
                f"window={self.window_compute_s:.3e}s "
                f"comm={self.comm_s:.3e}s exposed={self.exposed_s:.3e}s")


def _is_collective_op(opcode: str) -> bool:
    base = opcode
    for suf in ("-start", "-done"):
        if base.endswith(suf):
            base = base[:-len(suf)]
            break
    return base in COLLECTIVE_OPS


def overlap_report(mod: HloModule, census: Optional[Dict] = None,
                   mesh=None, spec=None,
                   bandwidths: Optional[Dict[str, float]] = None) -> Dict:
    """Pair every collective with its window and price the overlap.

    ``census`` (a :func:`collective_census` result) is accepted so a
    caller that already ran the census — ``analysis.analyze`` does —
    shares the single pairing walk; when omitted one is taken here.
    Returns windows plus the two budgetable aggregates:
    ``min_overlap_distance`` (min distance over async pairs; 0 when
    collectives exist but none lowered async — fully serialized — and 0
    when there are no collectives at all) and ``exposed_comm_fraction``
    (exposed ÷ total priced comm seconds, 0.0 for a comm-free module).
    """
    # lazy: analysis/ stays importable without the observability stack
    from ..observability.costs.analyzer import _Walker
    from ..observability.costs.device_db import device_spec

    if census is None:
        census = collective_census(mod, mesh=mesh)
    spec = spec or device_spec()
    bandwidths = bandwidths or {}
    flat = list(mod.instructions)
    walker = _Walker(mod)

    windows: List[OverlapWindow] = []
    for c in census.get("table", []):
        if c.index < 0:
            raise ValueError(
                "census table lacks instruction indices — rebuild it with "
                "collective_census() (stale or hand-built table?)")
        if c.is_async and c.done_index is None:
            raise UnmatchedCollectiveError(
                f"async collective '%{c.name}' ({c.opcode}-start in "
                f"computation '{c.computation}', module position "
                f"{c.index}) has no matching {c.opcode}-done — truncated "
                f"module text or a lowering this parser does not pair; "
                f"refusing to report the transfer as hidden")
        comm_s = c.bytes / float(bandwidths.get(c.axis, spec.link_bw))
        distance = 0
        window_s = 0.0
        if c.is_async:
            for ins in flat[c.index + 1:c.done_index]:
                # other collectives occupy the comm lane; they do not
                # hide THIS transfer, so only compute/HBM work counts
                if _is_collective_op(ins.opcode):
                    continue
                f, b, _ = walker.ins_cost(ins, fused=False)
                if f == 0.0 and b == 0.0:
                    continue
                distance += 1
                window_s += max(f / spec.peak_flops, b / spec.hbm_bw)
        hidden = min(comm_s, window_s)
        windows.append(OverlapWindow(
            name=c.name, opcode=c.opcode, axis=c.axis, op_name=c.op_name,
            is_async=c.is_async, index=c.index, done_index=c.done_index,
            distance=distance, window_compute_s=window_s, comm_s=comm_s,
            hidden_s=hidden, exposed_s=comm_s - hidden))

    total = sum(w.comm_s for w in windows)
    exposed = sum(w.exposed_s for w in windows)
    async_ws = [w for w in windows if w.is_async]
    min_distance = min((w.distance for w in async_ws), default=0)
    if not async_ws and windows:
        min_distance = 0  # collectives present, all serialized
    worst = max(windows, key=lambda w: w.exposed_s, default=None)
    tightest = min(async_ws, key=lambda w: w.distance, default=None)
    return {
        "windows": windows,
        "async_collectives": len(async_ws),
        "sync_collectives": len(windows) - len(async_ws),
        "min_overlap_distance": int(min_distance),
        "min_distance_collective": tightest.describe() if tightest else "",
        "total_comm_s": total,
        "hidden_comm_s": total - exposed,
        "exposed_comm_s": exposed,
        "exposed_comm_fraction": (round(exposed / total, 6) if total > 0.0
                                  else 0.0),
        "most_exposed_collective": worst.describe() if worst else "",
    }
