"""AST linter for retrace / host-sync hazards in jit-reachable python.

The HLO analyzers catch what a bad pattern COMPILED INTO; this linter
catches the pattern at the source line, before anyone pays a trace. It
walks a module's AST, marks the functions that get traced — arguments to
``jax.jit`` / ``pmap`` / ``shard_map`` / ``lax.scan|cond|while_loop|map``
/ ``custom_vjp`` / ``pallas_call`` / ``checkpoint``, jit-decorated
defs, and every ``def`` nested inside one (scan bodies) — and flags,
INSIDE traced code only:

* ``host-sync``    — ``float()/int()/bool()`` on computed values,
  ``.item()``/``.tolist()``, ``np.asarray``/``np.array``: a device fence
  (or a ConcretizationError) inside the compiled region;
* ``host-time``    — ``time.time()/perf_counter()``, ``datetime.now()``:
  traces bake the trace-time clock in as a constant;
* ``host-rng``     — ``np.random.*``, ``jax.random.key/PRNGKey``: host
  randomness is a per-trace constant (replay-breaking) — keys must enter
  as arguments and derive via ``fold_in``/``split`` on device;
* ``nonstatic-branch`` — ``if``/``while`` on a bare traced-function
  parameter: python control flow on a traced value.

Plus one host-side rule, applied everywhere:

* ``jit-in-loop``  — ``jax.jit(...)`` constructed inside a ``for``/
  ``while`` body: a fresh jit wrapper per iteration retraces every time
  (cache it outside the loop, like the engine's ``_decode_fns``).

False positives are expected at the margins (the linter has no dataflow)
— that is what inline waivers are for::

    x = float(n_static)   # trace-lint: waive(host-sync) static python int

A waiver comment on the flagged line (or the line directly above) names
the rule it waives and MUST carry a reason; unwaived violations fail
``tools/graph_lint.py`` and the tier-1 contract test.

CLI: ``python -m paddle_tpu.analysis.trace_lint <paths...>``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["Violation", "lint_source", "lint_file", "lint_paths",
           "RULES", "main"]

RULES = ("host-sync", "host-time", "host-rng", "nonstatic-branch",
         "jit-in-loop")

# callables whose function-typed arguments get traced
_TRACERS = {
    "jit", "pmap", "vmap_with_jit",  # jax.jit / jax.pmap
    "scan", "cond", "while_loop", "map", "switch", "fori_loop",
    "shard_map", "pallas_call", "custom_vjp", "custom_jvp", "checkpoint",
    "remat", "named_call", "export",
}
_HOST_SYNC_CASTS = {"float", "int", "bool", "complex"}
_HOST_SYNC_ATTRS = {"item", "tolist"}
_NP_ARRAYIFY = {"asarray", "array", "copy"}
_TIME_FNS = {"time", "perf_counter", "monotonic", "process_time",
             "perf_counter_ns", "time_ns"}
_HOST_KEY_FNS = {"key", "PRNGKey"}

_WAIVE_RE = re.compile(r"trace-lint:\s*waive\(([\w\-, ]+)\)\s*(.*)")


@dataclass
class Violation:
    path: str
    line: int
    rule: str
    message: str
    waived: bool = False
    waiver_reason: str = ""

    def render(self) -> str:
        tag = " [waived]" if self.waived else ""
        return f"{self.path}:{self.line}: {self.rule}{tag}: {self.message}"


def _dotted(node: ast.AST) -> str:
    """'jax.lax.scan' for an Attribute/Name chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_static_arg(node: ast.AST) -> bool:
    """Arguments that are obviously NOT traced values: literals, shape
    tuples/attribute chains ending in .shape/.ndim/.size/.dtype, len(),
    and arithmetic over those — enough to keep static shape math quiet."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in ("shape", "ndim", "size", "dtype")
    if isinstance(node, ast.Subscript):
        return _is_static_arg(node.value)
    if isinstance(node, ast.Call):
        f = _dotted(node.func)
        if f == "len" or f.endswith(".prod") or f.endswith(".ceil") \
                or f.endswith(".floor"):
            return all(_is_static_arg(a) for a in node.args)
        return False
    if isinstance(node, ast.BinOp):
        return _is_static_arg(node.left) and _is_static_arg(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_arg(node.operand)
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str):
        self.path = path
        self.lines = source.splitlines()
        self.violations: List[Violation] = []
        # lexical state
        self._traced_depth = 0          # >0: inside a traced function body
        self._loop_depth = 0
        self._params: List[Set[str]] = []   # traced fn param-name stack
        self._traced_defs: Set[ast.AST] = set()

    # -- waiver lookup -------------------------------------------------------

    def _waiver(self, line: int, rule: str) -> Optional[str]:
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _WAIVE_RE.search(self.lines[ln - 1])
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    if rule in rules or "all" in rules:
                        return m.group(2).strip() or "(no reason given)"
        return None

    def _flag(self, node: ast.AST, rule: str, message: str) -> None:
        reason = self._waiver(node.lineno, rule)
        self.violations.append(Violation(
            self.path, node.lineno, rule, message,
            waived=reason is not None, waiver_reason=reason or ""))

    # -- traced-function discovery ------------------------------------------

    def _mark_traced_args(self, call: ast.Call) -> None:
        """jax.jit(fn) / lax.scan(body, ...) / pallas_call(kernel):
        function-typed arguments (Name refs and lambdas) become traced."""
        fn_name = _dotted(call.func)
        last = fn_name.rsplit(".", 1)[-1]
        if last not in _TRACERS:
            return
        if last == "map" and "lax" not in fn_name:
            return          # jax.tree.map / builtins map: NOT a tracer
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Lambda):
                self._traced_defs.add(arg)
            elif isinstance(arg, ast.Name):
                self._names_traced.add(arg.id)

    def visit_Module(self, node: ast.Module):
        # pass 1: collect names referenced as tracer arguments anywhere in
        # the module (jit sites routinely appear AFTER or BEFORE the def)
        self._names_traced: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self._mark_traced_args(n)
        self.generic_visit(node)

    def _is_traced_def(self, node) -> bool:
        if node in self._traced_defs:
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # name matching is module-wide, so a `def run(self)` METHOD
            # must not inherit traced-ness from a jitted local `run`
            # closure elsewhere — traced functions never take self/cls
            args = node.args.posonlyargs + node.args.args
            is_method = bool(args) and args[0].arg in ("self", "cls")
            if node.name in self._names_traced and not is_method:
                return True
            for dec in node.decorator_list:
                d = _dotted(dec if not isinstance(dec, ast.Call)
                            else dec.func)
                if d.rsplit(".", 1)[-1] in ("jit", "custom_vjp",
                                            "custom_jvp", "checkpoint",
                                            "remat"):
                    return True
        return self._traced_depth > 0      # nested def inside traced code

    def _visit_fn(self, node, args: Optional[ast.arguments]):
        traced = self._is_traced_def(node)
        if traced:
            self._traced_depth += 1
            names = set()
            if args is not None:
                for a in (list(args.posonlyargs) + list(args.args)
                          + list(args.kwonlyargs)):
                    if a.arg not in ("self", "cls"):
                        names.add(a.arg)
            self._params.append(names)
        outer_loop = self._loop_depth
        self._loop_depth = 0            # loops outside a def don't leak in
        self.generic_visit(node)
        self._loop_depth = outer_loop
        if traced:
            self._traced_depth -= 1
            self._params.pop()

    def visit_FunctionDef(self, node):
        self._visit_fn(node, node.args)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._visit_fn(node, node.args)

    # -- rules ---------------------------------------------------------------

    def visit_For(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_While(self, node):
        if self._traced_depth and self._references_param(node.test):
            self._flag(node, "nonstatic-branch",
                       "`while` on a traced-function parameter — python "
                       "control flow cannot depend on traced values "
                       "(use lax.while_loop)")
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def _references_param(self, test: ast.AST) -> bool:
        if not self._params:
            return False
        params = self._params[-1]
        # `x is None` / isinstance / hasattr tests are static dispatch on
        # python structure, not traced-value branching
        for n in ast.walk(test):
            if isinstance(n, ast.Compare) and any(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                return False
            if isinstance(n, ast.Call) and _dotted(n.func) in (
                    "isinstance", "hasattr", "callable", "len"):
                return False
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in params:
                return True
        return False

    def visit_If(self, node):
        if self._traced_depth and self._references_param(node.test):
            self._flag(node, "nonstatic-branch",
                       "`if` on a traced-function parameter — python "
                       "branching on a traced value (use jnp.where / "
                       "lax.cond, or mark the arg static)")
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = _dotted(node.func)
        last = fn.rsplit(".", 1)[-1]

        if self._loop_depth and last == "jit" and fn.split(".")[0] in (
                "jax", "jit"):
            self._flag(node, "jit-in-loop",
                       "jax.jit constructed inside a loop body — a fresh "
                       "wrapper per iteration retraces every time; build "
                       "it once and cache it")

        if self._traced_depth:
            if fn in _HOST_SYNC_CASTS and node.args \
                    and not _is_static_arg(node.args[0]):
                self._flag(node, "host-sync",
                           f"{fn}() on a computed value inside traced "
                           f"code — device fence / ConcretizationError")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_SYNC_ATTRS:
                self._flag(node, "host-sync",
                           f".{node.func.attr}() inside traced code — "
                           f"forces a device->host transfer")
            elif fn.startswith("np.") and last in _NP_ARRAYIFY:
                self._flag(node, "host-sync",
                           f"{fn}() inside traced code materializes a "
                           f"host array from a traced value")
            elif (fn.startswith("time.") and last in _TIME_FNS) \
                    or fn in ("datetime.now", "datetime.datetime.now"):
                self._flag(node, "host-time",
                           f"{fn}() inside traced code bakes the "
                           f"trace-time clock in as a constant")
            elif fn.startswith("np.random.") or fn.startswith(
                    "numpy.random."):
                self._flag(node, "host-rng",
                           f"{fn}() inside traced code is a per-trace "
                           f"host constant — thread a jax key instead")
            elif last in _HOST_KEY_FNS and "random" in fn:
                self._flag(node, "host-rng",
                           f"{fn}() inside traced code — keys must enter "
                           f"as arguments and derive via fold_in/split")
        self.generic_visit(node)


def lint_source(source: str, path: str = "<string>") -> List[Violation]:
    tree = ast.parse(source)
    linter = _Linter(path, source)
    linter.visit(tree)
    linter.violations.sort(key=lambda v: (v.path, v.line))
    return linter.violations


def lint_file(path: str) -> List[Violation]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        return lint_source(src, path)
    except SyntaxError as e:
        return [Violation(path, e.lineno or 0, "parse-error", str(e))]


def lint_paths(paths: Sequence[str]) -> List[Violation]:
    out: List[Violation] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                if "__pycache__" in root:
                    continue
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.extend(lint_file(os.path.join(root, f)))
        elif p.endswith(".py"):
            out.extend(lint_file(p))
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    show_waived = "--show-waived" in argv
    argv = [a for a in argv if not a.startswith("--")]
    if not argv:
        print("usage: python -m paddle_tpu.analysis.trace_lint "
              "[--show-waived] <paths...>")
        return 2
    violations = lint_paths(argv)
    hard = [v for v in violations if not v.waived]
    for v in violations:
        if v.waived and not show_waived:
            continue
        print(v.render())
    print(f"{len(hard)} violation(s), "
          f"{sum(v.waived for v in violations)} waived")
    return 1 if hard else 0


if __name__ == "__main__":
    sys.exit(main())
