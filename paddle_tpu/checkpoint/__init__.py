"""Distributed sharded checkpoint with topology-reshard on load.

Reference: python/paddle/distributed/checkpoint/{save_state_dict.py:104,
load_state_dict.py,metadata.py} — per-rank shard files + a metadata manifest,
and automatic resharding when the load-time parallel topology differs from
save-time. Single-process paddle.save/load live in paddle_tpu.framework.

TPU redesign: orbax is the storage engine (tensorstore/OCDBT — per-shard
writes from every host, a manifest, atomic commit). The reference's
flat-param manifest + slice-reassembly logic collapses into restoring with a
*target tree of ShapeDtypeStructs carrying the new NamedShardings*: each
device reads exactly the byte ranges of its new shard, which is the
cross-topology reshard-on-load. Async save (reference's async_save flag)
uses orbax's AsyncCheckpointer: the device→host copy is synchronous, the
filesystem write happens on a background thread between steps.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_async_ckptr: Optional[ocp.AsyncCheckpointer] = None


def _abs(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


def _get_async() -> ocp.AsyncCheckpointer:
    global _async_ckptr
    if _async_ckptr is None:
        _async_ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _async_ckptr


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    async_save: bool = False, overwrite: bool = True) -> None:
    """Save a (nested) dict of arrays, sharded (reference:
    save_state_dict.py:104). Every host writes only its local shards."""
    path = _abs(path)
    if async_save:
        ck = _get_async()
        ck.save(path, args=ocp.args.StandardSave(state_dict), force=overwrite)
        return
    ck = ocp.StandardCheckpointer()
    ck.save(path, state_dict, force=overwrite)
    ck.wait_until_finished()


def wait_until_finished(watchdog=None, poll_s: float = 0.5,
                        hang_timeout_s: Optional[float] = None) -> None:
    """Block until pending async saves are durable (reference: the implicit
    barrier before the next save).

    A background write failure is re-raised HERE — the caller must learn
    the checkpoint is not durable before it matters, not at process exit.
    When ``watchdog`` (a distributed.watchdog.StepWatchdog) is given it is
    ticked every ``poll_s`` while waiting — a slow-but-healthy save must not
    false-trip the hung-step detector — but only up to ``hang_timeout_s``
    (default 4x the watchdog's own step timeout): past that budget the wait
    goes silent, the armed watchdog stops seeing progress and fires, so a
    truly hung GCS/NFS write is detected instead of stalling forever behind
    a stream of fake progress ticks."""
    if _async_ckptr is None:
        return
    if watchdog is None:
        _async_ckptr.wait_until_finished()
        return
    if hang_timeout_s is None:
        wd_t = getattr(watchdog, "timeout_s", None)
        hang_timeout_s = 4.0 * wd_t if wd_t else float("inf")
    import threading
    import time as _time
    done = threading.Event()
    err: list = []
    def _wait():
        try:
            _async_ckptr.wait_until_finished()
        except BaseException as e:  # noqa: BLE001 — carried to the caller
            err.append(e)
        finally:
            done.set()
    t = threading.Thread(target=_wait, daemon=True,
                         name="pt-ckpt-wait")
    t.start()
    start = _time.monotonic()
    while not done.wait(poll_s):
        if _time.monotonic() - start < hang_timeout_s:
            watchdog.tick()
    t.join()
    if err:
        raise err[0]


def _target_like(state_dict: Dict[str, Any], mesh: Optional[Mesh],
                 spec_tree: Optional[Dict[str, PartitionSpec]]):
    """Build the restore target: same shapes/dtypes, NEW shardings.

    ``spec_tree`` keys are matched against the leaf's full "/"-joined tree
    path, its final dict key (the param name), then any enclosing path
    component innermost-first — so the same name → PartitionSpec dict used
    for the model (param_spec_tree) also reshards its optimizer slots
    (``slots/<param name>/m`` picks up the param's spec via the component
    match).
    """
    from jax.tree_util import tree_map_with_path

    def one(path, x):
        keys = [str(getattr(p, "key", p)) for p in path]
        full = "/".join(keys)
        last = keys[-1] if keys else ""
        shape = tuple(x.shape) if hasattr(x, "shape") else tuple(np.shape(x))
        dtype = getattr(x, "dtype", None) or np.asarray(x).dtype
        sharding = None
        if mesh is not None:
            spec = None
            if spec_tree is not None:
                spec = spec_tree.get(full)
                if spec is None:
                    spec = spec_tree.get(last)
                if spec is None:
                    for k in reversed(keys[:-1]):
                        if k in spec_tree:
                            spec = spec_tree[k]
                            break
            if spec is None:
                # scalars can't take a param's spec; keep replicated
                spec = PartitionSpec()
            if len(spec) > len(shape):
                spec = PartitionSpec()
            sharding = NamedSharding(mesh, spec)
        elif isinstance(x, jax.Array) and isinstance(
                getattr(x, "sharding", None), NamedSharding):
            sharding = x.sharding
        if sharding is not None:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        return jax.ShapeDtypeStruct(shape, dtype)

    return tree_map_with_path(one, state_dict)


def load_state_dict(path: str, state_dict: Dict[str, Any],
                    mesh: Optional[Mesh] = None,
                    spec_tree: Optional[Dict[str, PartitionSpec]] = None
                    ) -> Dict[str, Any]:
    """Restore into the shapes of ``state_dict`` with NEW shardings — the
    cross-topology reshard (reference: load_state_dict.py). ``state_dict``
    supplies shapes/dtypes (its values may be abstract); sharding comes from
    ``spec_tree`` (name → PartitionSpec) over ``mesh``, falling back to each
    value's current sharding. Returns the restored tree."""
    path = _abs(path)
    target = _target_like(state_dict, mesh, spec_tree)
    ck = ocp.StandardCheckpointer()
    return ck.restore(path, target)


# -- whole-training-state checkpoint (step/params/opt/lr) --------------------

def save_training_state(path: str, step: int, params: Dict[str, jax.Array],
                        opt_state: Dict[str, Any], extra: Optional[Dict] = None,
                        async_save: bool = False) -> None:
    """One-call trainer checkpoint (reference analogue: auto_checkpoint's
    TrainEpochRange snapshot — base/incubate/checkpoint/auto_checkpoint.py:278)."""
    # 0-d ndarray, not np.int64: orbax's StandardSave leaf whitelist is
    # (int, float, np.ndarray, jax.Array)
    tree = {"step": np.asarray(step, np.int64), "params": params,
            "opt_state": opt_state}
    if extra:
        tree["extra"] = extra
    save_state_dict(tree, path, async_save=async_save)


def load_training_state(path: str, params_like: Dict[str, jax.Array],
                        opt_state_like: Dict[str, Any],
                        mesh: Optional[Mesh] = None,
                        spec_tree: Optional[Dict[str, PartitionSpec]] = None
                        ) -> Dict[str, Any]:
    tree = {"step": np.asarray(0, np.int64), "params": params_like,
            "opt_state": opt_state_like}
    return load_state_dict(path, tree, mesh=mesh, spec_tree=spec_tree)


def is_complete_checkpoint(path: str) -> bool:
    """True when ``path`` holds a fully-written checkpoint.

    Completeness evidence, in order: a CheckpointManager ``_COMMITTED``
    marker wins; a ``<path>.PENDING`` sidecar (manager save in flight or
    died mid-save) disqualifies; bare orbax dirs (save_state_dict without
    a manager) count when orbax's own metadata is present — orbax commits
    via atomic tmp-dir rename, so the metadata's existence implies the
    rename happened. An empty or unrecognizable dir (crash during
    makedirs) never qualifies. (Corrupt dirs are MOVED to ``_quarantine/``
    by the manager, so they never appear at a ``step_N`` path.)"""
    path = _abs(path)
    if not os.path.isdir(path):
        return False
    if os.path.isfile(os.path.join(path, "_COMMITTED")):
        # marker wins over an orphan .PENDING sidecar: a crash between
        # writing the marker and removing the sidecar leaves both, and the
        # commit happened
        return True
    if os.path.isfile(path + ".PENDING"):
        return False
    try:
        names = os.listdir(path)
    except OSError:
        return False
    return "_CHECKPOINT_METADATA" in names or "manifest.ocdbt" in names


def latest_step(root: str) -> Optional[int]:
    """Scan ``root`` for step_N checkpoint dirs; return the largest N whose
    dir is a COMPLETE checkpoint. Incomplete/uncommitted dirs (crash
    mid-save) and in-progress orbax tmp dirs are skipped — auto-resume must
    never pick up a partial write."""
    root = _abs(root)
    if not os.path.isdir(root):
        return None
    steps = []
    for name in os.listdir(root):
        if not name.startswith("step_"):
            continue
        try:
            n = int(name.split("_", 1)[1])
        except ValueError:
            continue          # orbax tmp dirs, quarantine tags, etc.
        if is_complete_checkpoint(os.path.join(root, name)):
            steps.append(n)
    return max(steps) if steps else None


__all__ = ["save_state_dict", "load_state_dict", "wait_until_finished",
           "save_training_state", "load_training_state", "latest_step",
           "is_complete_checkpoint"]

from . import auto_checkpoint  # noqa: E402  (TrainEpochRange, LocalFS)
