"""paddle_tpu.sparse.nn.functional — sparse functionals.

Reference: python/paddle/sparse/nn/functional/ — activation.py
(relu/relu6/leaky_relu/softmax), conv.py (conv2d/3d, subm_conv2d/3d),
pooling.py (max_pool3d), transformer.py (attention).

TPU design notes:
- CSR softmax is a TRUE sparse softmax: per-row segment max/sum over the
  stored values only (reference semantics: softmax over the non-zeros of
  each row), no densification.
- sparse attention computes QK^T ONLY at the stored positions of the CSR
  mask via gathers — O(nnz·d) instead of O(s²·d) — then a per-row segment
  softmax and a scatter-weighted sum against V. All static shapes, jit
  and vmap friendly (the nnz is the stored size of the mask).
- Sparse convolutions compute via the dense MXU conv on the densified
  tensor: on TPU a dense conv at < extreme sparsity beats gather-scatter
  kernels (no TPU atomics), and the subm variant masks the output to the
  input's active pattern, which reproduces submanifold semantics exactly.
  The reference's gather-GEMM-scatter pipeline (conv.py _conv3d) is the
  CUDA design; the contract (active-site outputs) is preserved.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import jax.experimental.sparse as jsparse

from . import (is_sparse, is_sparse_coo, is_sparse_csr, to_dense,
               to_sparse_coo, to_sparse_csr, _unary)


def relu(x, name=None):
    return _unary(jax.nn.relu, x)


def relu6(x, name=None):
    return _unary(jax.nn.relu6, x)


def leaky_relu(x, negative_slope: float = 0.01, name=None):
    return _unary(lambda v: jax.nn.leaky_relu(v, negative_slope), x)


def softmax(x, axis: int = -1, name=None):
    """Sparse softmax over the stored values of each row (reference:
    sparse/nn/functional/activation.py softmax — 'only supports axis=-1',
    softmax over non-zero entries per row)."""
    if axis != -1:
        raise ValueError("sparse softmax only supports axis=-1 "
                         "(reference contract)")
    if is_sparse_csr(x):
        data, indices, indptr = x.data, x.indices, x.indptr
        n_rows = x.shape[-2]
        nnz = data.shape[-1]
        # row id per stored element from indptr (searchsorted: static)
        row_of = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
        mx = jax.ops.segment_max(data, row_of, num_segments=n_rows)
        ex = jnp.exp(data - mx[row_of])
        sm = jax.ops.segment_sum(ex, row_of, num_segments=n_rows)
        new = ex / jnp.maximum(sm[row_of], 1e-30)
        return jsparse.BCSR((new, indices, indptr), shape=x.shape)
    if is_sparse_coo(x):
        # COO-native: segment softmax over stored values per ROW,
        # preserving the COO format and pattern (no densification).
        # indices: [nnz, n_sparse]; 2D = (row, col), 3D = (batch, row, col)
        data = x.data
        idx = x.indices
        n_sparse = idx.shape[-1]
        n_rows = x.shape[-2]
        if n_sparse == 2:
            rows = idx[:, 0]
            n_seg = n_rows
        elif n_sparse == 3:
            rows = idx[:, 0] * n_rows + idx[:, 1]   # (batch, row) key
            n_seg = x.shape[0] * n_rows
        else:
            raise ValueError(
                f"sparse softmax supports 2D/3D COO, got {n_sparse} "
                f"sparse dims")
        mx = jax.ops.segment_max(data, rows, num_segments=n_seg)
        ex = jnp.exp(data - mx[rows])
        sm = jax.ops.segment_sum(ex, rows, num_segments=n_seg)
        new = ex / jnp.maximum(sm[rows], 1e-30)
        return jsparse.BCOO((new, idx), shape=x.shape)
    return jax.nn.softmax(jnp.asarray(x), axis=axis)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """softmax(QK^T/sqrt(d), over the CSR mask's pattern) @ V.

    query/key/value: [b, h, s, d]; sparse_mask: CSR with dense shape
    [b*h, s, s] (reference transformer.py attention contract).
    key_padding_mask [b, s] / attn_mask [s, s]: additive 0/-inf masks.
    Computation touches only the mask's stored positions: O(nnz·d).
    """
    q = jnp.asarray(query)
    k = jnp.asarray(key)
    v = jnp.asarray(value)
    b, h, s, d = q.shape
    if not is_sparse_csr(sparse_mask):
        raise ValueError("sparse_mask must be a CSR tensor "
                         "(sparse_csr_tensor)")
    indptr = sparse_mask.indptr      # [(b*h,)? , s+1] or [s+1]
    cols = sparse_mask.indices
    # normalize to per-(b,h) layout
    if indptr.ndim == 1:
        indptr = jnp.broadcast_to(indptr, (b * h,) + indptr.shape)
        cols = jnp.broadcast_to(cols, (b * h,) + cols.shape)
    nnz = cols.shape[-1]
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)
    scale = 1.0 / jnp.sqrt(jnp.float32(d))

    kp = (None if key_padding_mask is None
          else jnp.asarray(key_padding_mask))
    am = None if attn_mask is None else jnp.asarray(attn_mask)

    def per_head(qh, kh, vh, colh, ptrh, bi):
        rows = jnp.searchsorted(ptrh, jnp.arange(nnz), side="right") - 1
        qg = qh[rows]                     # [nnz, d]
        kg = kh[colh]                     # [nnz, d]
        score = jnp.sum(qg.astype(jnp.float32) * kg.astype(jnp.float32),
                        axis=-1) * scale
        if kp is not None:
            score = score + kp[bi][colh].astype(jnp.float32)
        if am is not None:
            score = score + am[rows, colh].astype(jnp.float32)
        mx = jax.ops.segment_max(score, rows, num_segments=s)
        ex = jnp.exp(score - mx[rows])
        sm = jax.ops.segment_sum(ex, rows, num_segments=s)
        w = ex / jnp.maximum(sm[rows], 1e-30)
        out = jax.ops.segment_sum(w[:, None] * vh[colh].astype(jnp.float32),
                                  rows, num_segments=s)
        return out.astype(qh.dtype)

    bi = jnp.repeat(jnp.arange(b), h)
    out = jax.vmap(per_head)(qf, kf, vf, cols, indptr, bi)
    return out.reshape(b, h, s, d)


# ---------------------------------------------------------------------------
# sparse convolution / pooling (dense-MXU compute, sparse contracts)
# ---------------------------------------------------------------------------

def _dense_conv(x_dense, weight, bias, stride, padding, dilation, groups,
                nd: int):
    """channel-last conv: x [N, *spatial, C_in], weight [*k, C_in, C_out]
    (the reference sparse conv layout)."""
    dn = ("NHWC", "HWIO", "NHWC") if nd == 2 else ("NDHWC", "DHWIO", "NDHWC")
    stride = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    dilation = (dilation,) * nd if isinstance(dilation, int) \
        else tuple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()              # "SAME"/"VALID" pass through
    elif isinstance(padding, int):
        pad = [(padding, padding)] * nd
    else:
        pad = [tuple(int(q) for q in p) if isinstance(p, (tuple, list))
               else (int(p), int(p)) for p in padding]
    out = jax.lax.conv_general_dilated(
        x_dense.astype(jnp.float32),
        jnp.asarray(weight, jnp.float32),
        window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + jnp.asarray(bias, jnp.float32)
    return out.astype(x_dense.dtype)


def _sparse_conv(x, weight, bias, stride, padding, dilation, groups, nd,
                 subm: bool):
    dense = to_dense(x) if is_sparse(x) else jnp.asarray(x)
    out = _dense_conv(dense, weight, bias, stride, padding, dilation,
                      groups, nd)
    if subm:
        # submanifold: outputs exist only at the INPUT's active sites
        # (requires stride 1 / shape-preserving conv, like the reference)
        if out.shape != dense.shape[:-1] + (out.shape[-1],):
            raise ValueError(
                "subm_conv needs a shape-preserving configuration "
                "(stride 1, 'same'-style padding)")
        active = jnp.any(dense != 0, axis=-1, keepdims=True)
        out = jnp.where(active, out, 0)
    return to_sparse_coo(out, sparse_dim=out.ndim - 1)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse 3D conv (reference: sparse/nn/functional/conv.py conv3d;
    x [N, D, H, W, C] COO, weight [kD, kH, kW, C_in/g, C_out])."""
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        3, subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        3, subm=True)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        2, subm=False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        2, subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Sparse 3D max pool (reference: sparse/nn/functional/pooling.py)."""
    if ceil_mode:
        raise NotImplementedError("sparse max_pool3d: ceil_mode=False only "
                                  "(reference raises likewise on CPU)")
    dense = to_dense(x) if is_sparse(x) else jnp.asarray(x)
    # reduce over ACTIVE sites only (reference rulebook semantics):
    # implicit zeros must not win over negative stored values
    active = jnp.any(dense != 0, axis=-1, keepdims=True)
    masked = jnp.where(active, dense, -jnp.inf)
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    out = jax.lax.reduce_window(
        masked, -jnp.inf, jax.lax.max,
        window_dimensions=(1,) + ks + (1,),
        window_strides=(1,) + st + (1,),
        padding=((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),))
    out = jnp.where(jnp.isneginf(out), 0, out)  # windows with no active site
    return to_sparse_coo(out, sparse_dim=out.ndim - 1)


__all__ = ["relu", "relu6", "leaky_relu", "softmax", "attention",
           "conv2d", "conv3d", "subm_conv2d", "subm_conv3d", "max_pool3d"]
