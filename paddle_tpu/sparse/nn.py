"""paddle_tpu.sparse.nn — sparse layers (reference: python/paddle/sparse/nn/).

Activation layers over sparse values plus SubmConv-style conv placeholders:
on TPU, sparse convolution is only profitable at extreme sparsity; the
layers here keep the reference surface and compute via gather/dense tiles.
"""

from __future__ import annotations

import jax

from ..nn.layer import Layer
from . import _unary, to_dense, is_sparse


class ReLU(Layer):
    def forward(self, x):
        return _unary(jax.nn.relu, x)


class ReLU6(Layer):
    def forward(self, x):
        return _unary(lambda v: jax.nn.relu6(v), x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope: float = 0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return _unary(lambda v: jax.nn.leaky_relu(v, self.negative_slope), x)


class Softmax(Layer):
    """Softmax over the dense form (pattern-preserving softmax of a sparse
    logits tensor requires segment ops; the dense path is exact)."""

    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return jax.nn.softmax(to_dense(x) if is_sparse(x) else x, axis=self.axis)


class BatchNorm(Layer):
    """BatchNorm over sparse values (reference: paddle.sparse.nn.BatchNorm):
    normalizes the stored values channel-wise."""

    def __init__(self, num_features: int, momentum: float = 0.9,
                 epsilon: float = 1e-5):
        super().__init__()
        from ..nn.common import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum, epsilon=epsilon)

    def forward(self, x):
        if is_sparse(x):
            import jax.experimental.sparse as jsparse
            new_vals = self._bn(x.data)
            if hasattr(x, "indptr"):
                return jsparse.BCSR((new_vals, x.indices, x.indptr), shape=x.shape)
            return jsparse.BCOO((new_vals, x.indices), shape=x.shape)
        return self._bn(x)


# ---------------------------------------------------------------------------
# conv / pooling / sync-norm layers (reference: sparse/nn/layer/{conv,
# pooling,norm}.py). Compute documented in sparse/functional.py.
# ---------------------------------------------------------------------------

from . import functional  # noqa: E402  (module attr: sparse.nn.functional)
from ..nn import initializer as _I  # noqa: E402


class _SparseConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd,
                 stride=1, padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        if padding_mode != "zeros":
            raise NotImplementedError("sparse conv supports zeros padding")
        if data_format is not None and data_format not in ("NDHWC", "NHWC"):
            raise ValueError(
                f"sparse conv supports channel-last layouts only "
                f"(NDHWC/NHWC), got {data_format!r} — the reference "
                f"raises likewise")
        ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = (stride, padding, dilation, groups, nd, subm)
        init_w = weight_attr if isinstance(weight_attr, _I.Initializer) \
            else getattr(weight_attr, "initializer", None)
        self.weight = self.create_parameter(
            list(ks) + [in_channels // groups, out_channels],
            initializer=init_w,
            default_initializer=_I.XavierUniform())
        if bias_attr is not False:
            init_b = bias_attr if isinstance(bias_attr, _I.Initializer) \
                else getattr(bias_attr, "initializer", None)
            self.bias = self.create_parameter([out_channels], is_bias=True,
                                              initializer=init_b)
        else:
            self.add_parameter("bias", None)

    def forward(self, x):
        stride, padding, dilation, groups, nd, subm = self._cfg
        return functional._sparse_conv(x, self.weight, self.bias, stride,
                                       padding, dilation, groups, nd, subm)


class Conv3D(_SparseConvNd):
    """Reference: sparse/nn/layer/conv.py Conv3D:239."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, False, padding_mode,
                         weight_attr, bias_attr, data_format)


class SubmConv3D(_SparseConvNd):
    """Reference: sparse/nn/layer/conv.py SubmConv3D:509 — outputs only at
    the input's active sites."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, True, padding_mode,
                         weight_attr, bias_attr, data_format)


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, False, padding_mode,
                         weight_attr, bias_attr, data_format)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, True, padding_mode,
                         weight_attr, bias_attr, data_format)


class MaxPool3D(Layer):
    """Reference: sparse/nn/layer/pooling.py MaxPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        if return_mask:
            raise NotImplementedError("sparse MaxPool3D: return_mask "
                                      "unsupported")
        if data_format != "NDHWC":
            raise ValueError(f"sparse MaxPool3D supports NDHWC only, got "
                             f"{data_format!r}")
        self._a = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        ks, st, pd, cm = self._a
        return functional.max_pool3d(x, ks, st, pd, cm)


class SyncBatchNorm(BatchNorm):
    """Reference: sparse/nn/layer/norm.py SyncBatchNorm — under GSPMD the
    batch statistics of a dp-sharded batch are already global (XLA inserts
    the cross-replica reduction), so the sparse BatchNorm IS sync."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer
