"""paddle_tpu.sparse — sparse tensors & ops (reference: python/paddle/sparse/,
C++ SparseCooTensor/SparseCsrTensor at paddle/phi/core/sparse_coo_tensor.h).

TPU-native redesign: sparse storage rides jax.experimental.sparse (BCOO /
BCSR), whose matmuls lower to XLA gather/scatter + dense MXU tiles. The
reference's COO/CSR user surface (sparse_coo_tensor, sparse_csr_tensor,
.to_dense, .to_sparse_csr, elementwise/matmul/nn ops) is preserved; on TPU,
genuinely sparse compute only wins at high sparsity — the docstrings say so
rather than pretending otherwise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "is_sparse", "is_sparse_coo",
    "is_sparse_csr", "to_dense", "to_sparse_coo", "to_sparse_csr",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "sum", "transpose", "relu", "sqrt", "sin", "tanh", "abs", "pow",
    "nnz", "coalesce",
]


# -- constructors -----------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """COO tensor from [sparse_ndim, nnz] indices + [nnz, ...] values
    (reference: paddle.sparse.sparse_coo_tensor)."""
    indices = jnp.asarray(indices)
    values = jnp.asarray(values, dtype=dtype)
    if indices.ndim != 2:
        raise ValueError("indices must be [sparse_ndim, nnz]")
    if shape is None:
        shape = tuple((indices.max(axis=1) + 1).tolist()) + values.shape[1:]
    return jsparse.BCOO((values, indices.T), shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """CSR tensor (reference: paddle.sparse.sparse_csr_tensor)."""
    crows = jnp.asarray(crows, dtype=jnp.int32)
    cols = jnp.asarray(cols, dtype=jnp.int32)
    values = jnp.asarray(values, dtype=dtype)
    return jsparse.BCSR((values, cols, crows), shape=tuple(shape))


def is_sparse(x) -> bool:
    return isinstance(x, (jsparse.BCOO, jsparse.BCSR))


def is_sparse_coo(x) -> bool:
    return isinstance(x, jsparse.BCOO)


def is_sparse_csr(x) -> bool:
    return isinstance(x, jsparse.BCSR)


def to_dense(x):
    return x.todense() if is_sparse(x) else jnp.asarray(x)


def to_sparse_coo(x, sparse_dim=None):
    if is_sparse_coo(x):
        return x
    if is_sparse_csr(x):
        return x.to_bcoo()
    x = jnp.asarray(x)
    # BCOO.fromdense takes n_dense (trailing dense dims); paddle's sparse_dim
    # counts leading sparse dims
    n_dense = 0 if sparse_dim is None else x.ndim - sparse_dim
    return jsparse.BCOO.fromdense(x, n_dense=n_dense)


def to_sparse_csr(x):
    if is_sparse_csr(x):
        return x
    if is_sparse_coo(x):
        return jsparse.BCSR.from_bcoo(x)
    x = jnp.asarray(x)
    # paddle's N-d CSR (N>2) is batched CSR over the leading dims
    return jsparse.BCSR.fromdense(x, n_batch=max(x.ndim - 2, 0))


def nnz(x) -> int:
    return int(x.nse)


def coalesce(x, name=None):
    """Merge duplicate indices (reference: sparse/unary.py coalesce)."""
    if is_sparse_coo(x):
        # BCOO.sum_duplicates is a METHOD on new jax, a property-like
        # bound attr historically; call defensively
        out = x.sum_duplicates
        return out() if callable(out) else out
    return x


# -- math -------------------------------------------------------------------

def _coo(x):
    return to_sparse_coo(x) if is_sparse_csr(x) else x


def _binary(op, x, y, keep_csr_of=None):
    xs, ys = is_sparse(x), is_sparse(y)
    was_csr = is_sparse_csr(x) or is_sparse_csr(y)
    if xs and ys:
        out = jsparse.BCOO.fromdense(op(to_dense(x), to_dense(y)))
        return jsparse.BCSR.from_bcoo(out) if was_csr else out
    if xs or ys:
        return op(to_dense(x), to_dense(y))
    return op(jnp.asarray(x), jnp.asarray(y))


def add(x, y, name=None):
    if is_sparse_coo(x) and is_sparse_coo(y) and x.shape == y.shape:
        # true sparse add: concatenate then merge duplicates — no densify
        data = jnp.concatenate([x.data, y.data])
        idx = jnp.concatenate([x.indices, y.indices])
        return jsparse.BCOO((data, idx), shape=x.shape).sum_duplicates()
    return _binary(jnp.add, x, y)


def subtract(x, y, name=None):
    if is_sparse_coo(y):
        return add(x, jsparse.BCOO((-y.data, y.indices), shape=y.shape))
    return _binary(jnp.subtract, x, y)


def multiply(x, y, name=None):
    if is_sparse_coo(x) and not is_sparse(y):
        y = jnp.asarray(y)
        if y.ndim == 0:
            return jsparse.BCOO((x.data * y, x.indices), shape=x.shape)
    return _binary(jnp.multiply, x, y)


def divide(x, y, name=None):
    if is_sparse_coo(x) and not is_sparse(y):
        y = jnp.asarray(y)
        if y.ndim == 0:
            return jsparse.BCOO((x.data / y, x.indices), shape=x.shape)
    return _binary(jnp.divide, x, y)


def matmul(x, y, name=None):
    """sparse @ dense / sparse @ sparse (reference: paddle.sparse.matmul).
    Sparse-dense lowers through BCOO dot_general."""
    if is_sparse_csr(x):
        x = x.to_bcoo()
    if is_sparse_csr(y):
        y = y.to_bcoo()
    if is_sparse_coo(x) and is_sparse_coo(y):
        return jsparse.BCOO.fromdense(x.todense() @ y.todense())
    return x @ y


def masked_matmul(x, y, mask, name=None):
    """(x @ y) sampled at mask's sparsity pattern (reference:
    paddle.sparse.masked_matmul, SDDMM)."""
    dense = jnp.asarray(x) @ jnp.asarray(y)
    m = to_sparse_coo(mask) if not is_sparse_coo(mask) else mask
    rows, cols = m.indices[:, 0], m.indices[:, 1]
    vals = dense[rows, cols]
    out = jsparse.BCOO((vals, m.indices), shape=m.shape)
    return jsparse.BCSR.from_bcoo(out) if is_sparse_csr(mask) else out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    out = jnp.sum(to_dense(x), axis=axis, dtype=dtype, keepdims=keepdim)
    return out


def transpose(x, perm, name=None):
    if is_sparse_coo(x):
        try:
            return x.transpose(tuple(perm))
        except NotImplementedError:
            # permutations mixing sparse and dense axes (partial-sparsity
            # tensors, e.g. to_sparse_coo(1) then [1, 0]): dense
            # round-trip, keeping the original sparse-dim count
            sd = x.ndim - x.n_dense
            out = jnp.transpose(x.todense(), tuple(perm))
            return to_sparse_coo(out, sparse_dim=min(sd, out.ndim))
    return jnp.transpose(to_dense(x), perm)


# -- elementwise unary (value-wise on the stored entries) -------------------

def _unary(fn, x):
    if is_sparse_coo(x):
        return jsparse.BCOO((fn(x.data), x.indices), shape=x.shape)
    if is_sparse_csr(x):
        return jsparse.BCSR((fn(x.data), x.indices, x.indptr), shape=x.shape)
    return fn(jnp.asarray(x))


def relu(x, name=None):
    return _unary(jax.nn.relu, x)


def sqrt(x, name=None):
    return _unary(jnp.sqrt, x)


def sin(x, name=None):
    return _unary(jnp.sin, x)


def tanh(x, name=None):
    return _unary(jnp.tanh, x)


def abs(x, name=None):
    return _unary(jnp.abs, x)


def pow(x, factor, name=None):
    return _unary(lambda v: jnp.power(v, factor), x)


from . import nn  # noqa: E402  (re-export subpackage)


# -- round-3 parity batch: zero-preserving unary tail + utilities -----------
# (reference: python/paddle/sparse/unary.py — each op applies to the
# nonzero values only, preserving the sparsity pattern)

def asin(x, name=None):
    return _unary(jnp.arcsin, x)


def asinh(x, name=None):
    return _unary(jnp.arcsinh, x)


def atan(x, name=None):
    return _unary(jnp.arctan, x)


def atanh(x, name=None):
    return _unary(jnp.arctanh, x)


def sinh(x, name=None):
    return _unary(jnp.sinh, x)


def tan(x, name=None):
    return _unary(jnp.tan, x)


def square(x, name=None):
    return _unary(jnp.square, x)


def log1p(x, name=None):
    return _unary(jnp.log1p, x)


def expm1(x, name=None):
    return _unary(jnp.expm1, x)


def neg(x, name=None):
    return _unary(jnp.negative, x)


def deg2rad(x, name=None):
    return _unary(jnp.deg2rad, x)


def rad2deg(x, name=None):
    return _unary(jnp.rad2deg, x)


def isnan(x, name=None):
    return _unary(jnp.isnan, x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core.dtype import convert_dtype
    vd = convert_dtype(value_dtype) if value_dtype is not None else None
    id_ = convert_dtype(index_dtype) if index_dtype is not None else None
    if is_sparse_coo(x):
        idx = x.indices.astype(id_) if id_ is not None else x.indices
        dat = x.data.astype(vd) if vd is not None else x.data
        return jsparse.BCOO((dat, idx), shape=x.shape)
    if is_sparse_csr(x):
        dat = x.data.astype(vd) if vd is not None else x.data
        ind = x.indices.astype(id_) if id_ is not None else x.indices
        ptr = x.indptr.astype(id_) if id_ is not None else x.indptr
        return jsparse.BCSR((dat, ind, ptr), shape=x.shape)
    return jnp.asarray(x).astype(vd)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def reshape(x, shape, name=None):
    """COO reshape via dense round-trip (reference sparse/unary.py reshape
    supports re-distributing sparse dims; nnz is preserved). Paddle shape
    semantics: 0 copies the input dim, -1 infers."""
    dense = to_dense(x) if is_sparse(x) else jnp.asarray(x)
    dims = [dense.shape[i] if int(s) == 0 else int(s)
            for i, s in enumerate(shape)]
    out = dense.reshape(tuple(dims))
    if is_sparse_csr(x):
        return to_sparse_csr(out)
    if is_sparse_coo(x):
        return to_sparse_coo(out, sparse_dim=out.ndim)
    return out


def slice(x, axes, starts, ends, name=None):
    import builtins
    dense = to_dense(x) if is_sparse(x) else jnp.asarray(x)
    idx = [builtins.slice(None)] * dense.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = builtins.slice(int(st), int(en))
    out = dense[tuple(idx)]
    if is_sparse_csr(x):
        return to_sparse_csr(out)
    if is_sparse_coo(x):
        return to_sparse_coo(out, sparse_dim=out.ndim)
    return out


def mv(x, vec, name=None):
    """Sparse matrix x dense vector (reference sparse/binary.py mv)."""
    return matmul(x, jnp.asarray(vec)[:, None])[..., 0]


def addmm(input, x, y, beta: float = 1.0, alpha: float = 1.0, name=None):
    """beta*input + alpha*(x @ y) with sparse x (reference
    sparse/binary.py addmm)."""
    prod = matmul(x, y)
    dense_prod = to_dense(prod) if is_sparse(prod) else prod
    dense_in = to_dense(input) if is_sparse(input) else jnp.asarray(input)
    return beta * dense_in + alpha * dense_prod


def pca_lowrank(x, q=None, center: bool = True, niter: int = 2, name=None):
    from ..linalg import pca_lowrank as _dense_pca
    dense = to_dense(x) if is_sparse(x) else jnp.asarray(x)
    return _dense_pca(dense, q=q, center=center, niter=niter)


__all__ += ["asin", "asinh", "atan", "atanh", "sinh", "tan", "square",
            "log1p", "expm1", "neg", "deg2rad", "rad2deg", "isnan", "cast",
            "is_same_shape", "reshape", "slice", "mv", "addmm",
            "pca_lowrank"]


from . import functional  # noqa: E402
from . import nn  # noqa: E402
__all__ += ["functional", "nn"]


# paddle Tensor method spellings on the jax sparse classes (doctests call
# sp_x.to_dense() / sp_x.to_sparse_coo() on the objects themselves)
if not hasattr(jsparse.BCSR, "to_dense"):
    jsparse.BCSR.to_dense = lambda self: self.todense()
    jsparse.BCOO.to_dense = lambda self: self.todense()
    jsparse.BCOO.to_sparse_csr = lambda self: to_sparse_csr(self.todense())
    jsparse.BCSR.to_sparse_coo = (
        lambda self, sparse_dim=None: to_sparse_coo(self.todense(),
                                                    sparse_dim=sparse_dim))
    jsparse.BCOO.values = lambda self: self.data
    jsparse.BCSR.values = lambda self: self.data
