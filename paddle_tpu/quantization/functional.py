"""Quantized compute ops. int8 matmul accumulating in int32 runs on the MXU
(the performance payoff of PTQ on TPU); quantize/dequantize_linear mirror the
reference's ONNX-style linear-quant kernels (phi quantize_linear)."""

from __future__ import annotations

import jax.numpy as jnp


def quantize_linear(x, scale, zero_point=0, bit_length: int = 8,
                    axis=None, name=None):
    """x → int-k: round(x/scale) + zero_point (symmetric default).
    ``axis`` selects per-channel scales of that dim."""
    qmax = 2 ** (bit_length - 1) - 1
    if axis is not None:
        shape = [1] * x.ndim
        shape[axis] = -1
        scale = jnp.reshape(scale, shape)
    q = jnp.clip(jnp.round(x / scale) + zero_point, -qmax - 1, qmax)
    return q.astype(jnp.int8 if bit_length == 8 else jnp.int32)


def dequantize_linear(q, scale, zero_point=0, axis=None, name=None):
    if axis is not None:
        shape = [1] * q.ndim
        shape[axis] = -1
        scale = jnp.reshape(scale, shape)
    return (q.astype(jnp.float32) - zero_point) * scale


def int8_matmul(x_q, w_q, x_scale, w_scale, out_dtype=None):
    """Quantized matmul, resolved through THE ops-registry "int8_matmul"
    op (ISSUE 17 dedupe): the activation side is dequantized (one fused
    convert+scale), the weight stays int8 across HBM, and the registry
    picks the fused Pallas dequant-matmul on TPU (TuneDB blocks,
    PT_DISABLE_PALLAS honored) or the XLA composition elsewhere.

    x_q int8 [..., k]; w_q int8 [k, n] ("x @ w" layout — transposed to
    the registry's [n, k] weight layout at trace time, free under XLA);
    w_scale per-tensor or per-out-channel [n]. ``out_dtype=None`` follows
    the activation-dtype convention used everywhere else: the result
    lands in the dequantized activation's dtype (``x_scale``'s floating
    dtype; python-float scales mean fp32)."""
    xs = jnp.asarray(x_scale)
    act_dtype = xs.dtype if jnp.issubdtype(xs.dtype, jnp.floating) \
        else jnp.float32
    x = x_q.astype(act_dtype) * xs.astype(act_dtype)
    try:
        from ..ops.registry import dispatch
        out = dispatch("int8_matmul")(
            x, jnp.asarray(w_q, jnp.int8).T,
            jnp.asarray(w_scale, jnp.float32))
    except KeyError:  # pragma: no cover - jaxlib without pallas
        acc = jnp.dot(x_q.astype(jnp.int8), w_q.astype(jnp.int8),
                      preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * (x_scale * w_scale)
    return out.astype(act_dtype if out_dtype is None else out_dtype)
