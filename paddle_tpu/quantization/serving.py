"""Offline weight-only int8 conversion for serving (ISSUE 17).

Takes a TRAINED Llama checkpoint (float state dict) and produces the
serving layout ``LlamaConfig(weight_dtype="int8")`` expects: every dense
projection (qkv_proj / o_proj / gate_up_proj / down_proj / lm_head)
becomes a TRANSPOSED int8 ``[n, k]`` weight plus a per-out-channel fp32
``<name>_scale`` ``[n]`` — exactly ``nn.quantized_linear.weight_quantize``'s
contract, so the model's runtime dispatch (the one ops-registry
"int8_matmul" op) dequantizes on the same grid the converter rounded to.

Everything that is not a projection matmul stays float: embeddings (a
gather table, not a matmul), RMSNorm gains (numerically sensitive, tiny),
and rope caches. Tied-embedding models keep the float table as their
vocab head — there is no separate lm_head to quantize.

This is weight-only PTQ, not QAT and not activation quant: decode is
HBM-bandwidth-bound, so shrinking the weights (and fusing the dequant
into the matmul epilogue) is where the tok/s is; activations stay in the
model dtype and no calibration pass is needed.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import replace
from typing import Any, Dict

import jax.numpy as jnp

from ..nn.quantized_linear import weight_quantize

# final path component → quantize; everything else copies through
PROJ_SUFFIXES = ("qkv_proj", "o_proj", "gate_up_proj", "down_proj",
                 "lm_head")

__all__ = ["PROJ_SUFFIXES", "quantize_state_dict", "quantize_model",
           "int8_config"]


def int8_config(cfg, kv_dtype: str | None = None):
    """The serving twin of a training config: same architecture,
    ``weight_dtype="int8"`` (and optionally int8 KV pages)."""
    kw = {"weight_dtype": "int8"}
    if kv_dtype is not None:
        kw["kv_dtype"] = kv_dtype
    return replace(cfg, **kw)


def quantize_state_dict(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Float Llama state dict → int8 serving state dict.

    Each ``...<proj>`` float ``[k, n]`` entry becomes ``...<proj>`` int8
    ``[n, k]`` + ``...<proj>_scale`` fp32 ``[n]``; all other entries pass
    through unchanged. Idempotent-hostile on purpose: re-quantizing an
    already-int8 dict raises (the dtype check), rather than silently
    double-scaling."""
    out: Dict[str, Any] = OrderedDict()
    for name, value in state_dict.items():
        w = jnp.asarray(value)
        if name.rsplit(".", 1)[-1] in PROJ_SUFFIXES and w.ndim == 2:
            if w.dtype == jnp.int8:
                raise ValueError(f"{name} is already int8 — refusing to "
                                 f"quantize a quantized checkpoint")
            wq, scale = weight_quantize(w, algo="weight_only_int8")
            out[name] = wq                        # int8 [n, k]
            out[name + "_scale"] = scale          # fp32 [n]
        else:
            out[name] = w
    return out


def quantize_model(model, kv_dtype: str | None = None):
    """Trained ``LlamaForCausalLM`` → its int8 serving twin.

    Builds a fresh model under ``weight_dtype="int8"`` (projections
    allocated int8 + scale) and loads the quantized state dict into it.
    The result is serving-only: ``forward(labels=...)`` refuses."""
    from ..models.llama import LlamaForCausalLM
    qmodel = LlamaForCausalLM(int8_config(model.cfg, kv_dtype))
    qmodel.set_state_dict(quantize_state_dict(model.state_dict()))
    return qmodel
