"""paddle_tpu.quantization — QAT/PTQ (reference: python/paddle/quantization/:
QuantConfig in config.py, QAT/PTQ in qat.py/ptq.py, observers under
observer/, fake quanters under quanters/, plus the quantize/dequantize
kernels in phi).

TPU-native: fake-quant uses the straight-through estimator inside jax grad;
converted int8 layers compute with jnp.dot(..., preferred_element_type=int32)
— int8 matmul hits the MXU at 2x bf16 throughput, the reason PTQ matters on
TPU at all. Observers are functional (scale state lives on the layer), so
calibration runs under jit too.
"""

from .config import QuantConfig
from .observers import (BaseObserver, AbsmaxObserver,
                        MovingAverageAbsmaxObserver, PercentileObserver)
from .quanters import (BaseQuanter, quanter, FakeQuanterWithAbsMax, FakeQuanterChannelWiseAbsMax,
                       fake_quant, quantize_absmax, dequantize)
from .qat import QAT, PTQ
from .layers import QuantedLinear, QuantedConv2D, Int8Linear
from .functional import quantize_linear, dequantize_linear, int8_matmul
from .serving import quantize_state_dict, quantize_model, int8_config

__all__ = [
    "QuantConfig", "QAT", "PTQ",
    "BaseObserver", "AbsmaxObserver", "MovingAverageAbsmaxObserver",
    "PercentileObserver",
    "FakeQuanterWithAbsMax", "FakeQuanterChannelWiseAbsMax",
    "fake_quant", "quantize_absmax", "dequantize",
    "QuantedLinear", "QuantedConv2D", "Int8Linear",
    "quantize_linear", "dequantize_linear", "int8_matmul",
    "quantize_state_dict", "quantize_model", "int8_config",
]
