"""Quantized layer wrappers (reference: paddle/nn/quant/ QuantedLinear /
QuantedConv2D produced by QAT.quantize, and the converted inference layers)."""

from __future__ import annotations

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.common import Linear, Conv2D
from .quanters import (FakeQuanterWithAbsMax, FakeQuanterChannelWiseAbsMax,
                       fake_quant)
from .functional import quantize_linear, int8_matmul


class QuantedLinear(Layer):
    """QAT wrapper: fake-quant activations (per-tensor EMA scale) and weights
    (per-out-channel) around the dense matmul."""

    def __init__(self, layer: Linear, q_config):
        super().__init__()
        self.in_features = layer.in_features
        self.out_features = layer.out_features
        # adopt the Parameter objects themselves (attribute access on the
        # donor layer yields raw arrays, which would not be trainable here)
        self.add_parameter("weight", layer._parameters["weight"])
        self.add_parameter("bias", layer._parameters.get("bias"))
        self.activation_quanter = (q_config.activation() if q_config.activation
                                   else FakeQuanterWithAbsMax())
        self.weight_quanter = (q_config.weight() if q_config.weight
                               else FakeQuanterChannelWiseAbsMax(channel_axis=-1))

    def forward(self, x):
        x = self.activation_quanter(x, update=self.training)
        if not self.training:
            # eval/serving: the fake-quant weight grid IS an int8 grid, so
            # express the matmul through the one registry "int8_matmul" op
            # (tuned Pallas blocks + PT_DISABLE_PALLAS apply uniformly;
            # ISSUE 17). Same values as F.linear(x, fake_quant(w)):
            # round(w/s) lands exactly on the int grid the op dequants.
            from ..ops.pallas.int8_matmul import quantized_matmul
            s = self.weight_quanter.scales(self.weight)        # [1, n]
            wq = jnp.clip(jnp.round(self.weight / s), -128, 127) \
                .astype(jnp.int8)                              # [k, n]
            out = quantized_matmul(x, wq.T, s.reshape(-1))
            return out if self.bias is None else out + self.bias
        # training keeps the straight-through fake-quant path: gradients
        # must flow through the float master weight
        w = self.weight_quanter(self.weight)
        return F.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer: Conv2D, q_config):
        super().__init__()
        self._inner = layer
        self.activation_quanter = (q_config.activation() if q_config.activation
                                   else FakeQuanterWithAbsMax())
        # conv weight is [out, in/g, kh, kw] → channel axis 0
        self.weight_quanter = (q_config.weight() if q_config.weight
                               else FakeQuanterChannelWiseAbsMax(channel_axis=0))

    def forward(self, x):
        x = self.activation_quanter(x, update=self.training)
        w = self.weight_quanter(self._inner.weight)
        return F.conv2d(x, w, self._inner.bias, self._inner.stride,
                        self._inner.padding, self._inner.dilation,
                        self._inner.groups, self._inner.data_format)


class Int8Linear(Layer):
    """Converted inference layer: weights stored int8 (per-out-channel
    scales), activations quantized on the fly with the calibrated scale, the
    matmul runs int8×int8→int32 on the MXU."""

    def __init__(self, weight, bias, act_scale: float, quant_bits: int = 8):
        super().__init__()
        qmax = float(2 ** (quant_bits - 1) - 1)
        w = jnp.asarray(weight)
        w_absmax = jnp.max(jnp.abs(w), axis=0)          # per out-channel [N]
        self.w_scale = jnp.maximum(w_absmax, 1e-8) / qmax
        w_q = quantize_linear(w, self.w_scale[None, :], bit_length=quant_bits)
        self.register_buffer("weight_q", w_q)
        self.bias = bias
        self.act_scale = float(act_scale)
        self.quant_bits = quant_bits

    def forward(self, x):
        x_q = quantize_linear(x, self.act_scale, bit_length=self.quant_bits)
        shape = x_q.shape
        out = int8_matmul(x_q.reshape(-1, shape[-1]), self.weight_q,
                          self.act_scale, self.w_scale, out_dtype=x.dtype)
        out = out.reshape(*shape[:-1], -1)
        if self.bias is not None:
            out = out + self.bias
        return out
