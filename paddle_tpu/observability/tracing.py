"""Distributed request tracing across the serving fabric (ISSUE 19).

The metrics plane answers *how is the system doing*; the profiler's
RecordEvent answers *where did this microsecond go inside one process*.
This module answers the question operators actually ask a multi-hop
serving path: *for THIS slow request, which hop ate the TTFT?* — with
one span tree per request stitched across the frontdoor, the router,
the breaker, and every replica it touched, including replicas in other
processes behind the TCP transport.

Design contracts:

* **Zero-cost when disabled** — same discipline as the metrics
  registry: every instrumented call site guards on ``TRACER.enabled``
  (one attribute load + branch) before allocating anything. With
  tracing off, no :class:`Span` object is ever constructed (the
  regression test counts constructions, not wall clock).
* **Explicit context propagation** — a :class:`TraceContext`
  ``(trace_id, span_id)`` is minted at the FrontDoor edge, handed down
  call chains as plain arguments, and rides the request payload dict
  as a ``"trace"`` key. ``contextvars`` would silently stop at the TCP
  hop (a different process shares no interpreter state); a dict key
  crosses any JSON transport untouched, so in-proc and TCP replicas
  stitch identically.
* **Remote stitching via poll piggyback** — each process runs its own
  tracer. A replica process never owns a trace's root, so its finished
  spans are *foreign*: :meth:`Tracer.drain_for_wire` hands them to
  ``Replica.poll()``, which ships them in the poll response; the
  router ingests them into the root-owning tracer. In-proc replicas
  share the root-owning tracer, so the drain is empty and spans are
  already home — one rule covers both transports.
* **Orphans are flagged, never dropped** — at assembly (root span
  end), spans still open (a replica died mid-request) are emitted with
  ``unfinished: true``; spans whose parent never arrived (crashed
  replica lost the parent) carry ``orphan: true``. The evidence of a
  partial hop is exactly what a post-mortem needs.

Timestamps are ``time.time()`` (wall clock): spans from different
processes on one host must land on a shared axis, which perf_counter
cannot give. Cross-host skew would smear remote spans; the fabric is
single-host today and the choice is documented where it would bite.

Completed traces land in a bounded ring (:data:`TRACE_RING` = 32, the
flight recorder's attached-trace window), optionally one-JSON-line-per-
trace in ``dir`` (crash-safe append, torn tail tolerated by the JSONL
loader), and — when the metrics plane is live — as
``pt_trace_ttft_frac{hop=...}`` gauges so the SLO sentry can breach on
attribution *shifts*, not just totals.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Union

__all__ = ["TraceContext", "Span", "Tracer", "TRACER", "tracer",
           "TRACE_RING"]

TRACE_RING = 32          # complete traces retained for incidents/flight
_MAX_ACTIVE = 256        # concurrent unfinished traces before eviction


class TraceContext:
    """The propagated identity: which trace, and which span to parent
    under. This is the ONLY thing that crosses a hop — spans themselves
    stay in their owning process until drained."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_wire(cls, d) -> Optional["TraceContext"]:
        """Tolerant extraction: a payload without (or with a mangled)
        trace key yields None — an untraced request, never an error."""
        if isinstance(d, TraceContext):
            return d
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not tid or not sid:
            return None
        return cls(str(tid), str(sid))

    def __repr__(self):
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


class Span:
    """One timed hop. Constructed ONLY via :meth:`Tracer.start` behind
    the enabled guard — the zero-cost test counts these."""

    __slots__ = ("_tracer", "trace_id", "span_id", "parent_id", "name",
                 "start", "end_t", "tags", "events", "pid")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, start: float,
                 tags: Optional[dict]):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = float(start)
        self.end_t: Optional[float] = None
        self.tags: dict = dict(tags) if tags else {}
        self.events: List[list] = []      # [ts, name, n]
        self.pid = os.getpid()

    @property
    def ctx(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def tag(self, **kw) -> "Span":
        self.tags.update(kw)
        return self

    def event(self, name: str, ts: Optional[float] = None,
              n: int = 1) -> None:
        self.events.append([time.time() if ts is None else float(ts),
                            str(name), int(n)])

    def end(self, ts: Optional[float] = None) -> None:
        if self.end_t is not None:
            return                        # idempotent: first end wins
        self.end_t = time.time() if ts is None else float(ts)
        self._tracer._finish(self)

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "start": self.start, "end": self.end_t,
                "pid": self.pid, "tags": self.tags,
                "events": self.events}


class Tracer:
    """Process-local span factory + per-trace assembler; see module doc.
    The module singleton :data:`TRACER` is what instrumented sites load;
    extra instances exist so one test process can faithfully play both
    sides of the TCP hop (router tracer + replica tracer)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.RLock()
        self._dir: Optional[str] = None
        self._roots: Dict[str, Span] = {}      # locally-rooted traces
        self._open: Dict[str, Dict[str, Span]] = {}
        self._done: Dict[str, List[dict]] = {}  # finished, unassembled
        self.completed: deque = deque(maxlen=TRACE_RING)
        self.dropped = 0                       # evicted active traces
        self.spans_started = 0

    # -- lifecycle -----------------------------------------------------------

    def enable(self, dir: Optional[str] = None,
               ring: int = TRACE_RING) -> "Tracer":
        with self._lock:
            self._dir = dir
            if dir:
                os.makedirs(dir, exist_ok=True)
            self._roots.clear()
            self._open.clear()
            self._done.clear()
            self.completed = deque(maxlen=int(ring))
            self.dropped = 0
            self.enabled = True
        return self

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            self._roots.clear()
            self._open.clear()
            self._done.clear()

    # -- span factory --------------------------------------------------------

    def start(self, name: str,
              parent: Union[Span, TraceContext, dict, None] = None,
              tags: Optional[dict] = None, start: Optional[float] = None,
              trace_id: Optional[str] = None) -> Optional[Span]:
        """Open a span. ``parent=None`` mints a new trace root (or joins
        ``trace_id`` when a caller supplied one — client correlation).
        Returns None when disabled, so call sites can keep the
        ``sp = TRACER.start(...) if TRACER.enabled else None`` shape."""
        if not self.enabled:
            return None
        if isinstance(parent, dict):
            parent = TraceContext.from_wire(parent)
        if isinstance(parent, Span):
            parent = parent.ctx
        with self._lock:
            if parent is None:
                tid = (str(trace_id) if trace_id
                       else uuid.uuid4().hex[:16])
                pid = None
            else:
                tid, pid = parent.trace_id, parent.span_id
            sp = Span(self, tid, uuid.uuid4().hex[:16], pid, name,
                      time.time() if start is None else start, tags)
            self.spans_started += 1
            if parent is None and tid not in self._roots:
                self._roots[tid] = sp
            self._open.setdefault(tid, {})[sp.span_id] = sp
            self._evict_locked()
        return sp

    def _evict_locked(self) -> None:
        # bound unfinished-trace state: streams that orphan and never
        # resume leak a root each; cap the table rather than the server
        while len(self._open) > _MAX_ACTIVE:
            tid = next(iter(self._open))
            self._open.pop(tid, None)
            self._roots.pop(tid, None)
            self._done.pop(tid, None)
            self.dropped += 1

    # -- assembly ------------------------------------------------------------

    def _finish(self, sp: Span) -> None:
        if not self.enabled:
            return
        with self._lock:
            open_t = self._open.get(sp.trace_id)
            if open_t is not None:
                open_t.pop(sp.span_id, None)
                if not open_t and sp.trace_id not in self._roots:
                    # foreign trace fully quiesced: drop the table entry
                    # so the active-trace bound counts real work
                    del self._open[sp.trace_id]
            root = self._roots.get(sp.trace_id)
            if root is sp:
                self._complete_locked(sp.trace_id)
            else:
                self._done.setdefault(sp.trace_id, []).append(
                    sp.to_dict())

    def ingest(self, span_dicts: List[dict]) -> None:
        """Adopt finished spans another process shipped (poll
        piggyback). Spans of already-assembled traces are dropped —
        bounded, and only reachable by a late poll racing completion."""
        if not self.enabled or not span_dicts:
            return
        with self._lock:
            for d in span_dicts:
                tid = d.get("trace_id")
                if not tid:
                    continue
                self._done.setdefault(str(tid), []).append(dict(d))

    def drain_for_wire(self) -> List[dict]:
        """Finished spans of traces whose root lives elsewhere — the
        replica side of the poll piggyback. A tracer that owns the root
        (in-proc fabric) keeps everything and returns []."""
        if not self.enabled:
            return []
        with self._lock:
            out: List[dict] = []
            for tid in list(self._done):
                if tid not in self._roots:
                    out.extend(self._done.pop(tid))
            return out

    def _complete_locked(self, tid: str) -> None:
        root = self._roots.pop(tid)
        spans = self._done.pop(tid, [])
        for sp in self._open.pop(tid, {}).values():
            d = sp.to_dict()
            d["tags"]["unfinished"] = True   # flagged, not dropped
            spans.append(d)
        spans.append(root.to_dict())
        ids = {s["span_id"] for s in spans}
        for s in spans:
            if s["parent_id"] is not None and s["parent_id"] not in ids:
                s["tags"]["orphan"] = True   # parent lost with its proc
        spans.sort(key=lambda s: s["start"])
        ttft = None
        for ts, name, _n in root.events:
            if name == "first_tok":
                ttft = ts - root.start
                break
        trace = {"trace_id": tid, "root": root.span_id,
                 "spans": spans,
                 "summary": {"name": root.name,
                             "start": root.start, "end": root.end_t,
                             "total_s": (None if root.end_t is None
                                         else root.end_t - root.start),
                             "ttft_s": ttft,
                             "n_spans": len(spans),
                             "tags": dict(root.tags)}}
        self.completed.append(trace)
        if self._dir:
            try:
                path = os.path.join(self._dir, "traces.jsonl")
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(trace, sort_keys=True) + "\n")
                    f.flush()
            except OSError:
                pass                      # tracing must never kill serving
        self._publish_gauges(trace)

    def _publish_gauges(self, trace: dict) -> None:
        from .metrics import REGISTRY as _REG
        if not _REG.enabled or trace["summary"]["ttft_s"] is None:
            return
        try:
            from ..analysis.critical_path import attribute_trace
            att = attribute_trace(trace)
        except Exception:
            return                        # attribution is advisory
        g = _REG.gauge("pt_trace_ttft_frac",
                       "fraction of the last traced request's TTFT "
                       "attributed to each hop (critical path)")
        for hop, frac in att.get("ttft_frac", {}).items():
            g.set(float(frac), hop=str(hop))

    # -- consumers -----------------------------------------------------------

    def recent_traces(self) -> List[dict]:
        with self._lock:
            return list(self.completed)

    def take_completed(self) -> List[dict]:
        with self._lock:
            out = list(self.completed)
            self.completed.clear()
            return out

    def worst_traces(self, k: int = 3,
                     key: str = "ttft_s") -> List[dict]:
        """The K completed traces with the worst ``summary[key]`` — what
        a TTFT/ITL incident attaches as evidence."""
        with self._lock:
            have = [t for t in self.completed
                    if isinstance(t["summary"].get(key), (int, float))]
            have.sort(key=lambda t: t["summary"][key], reverse=True)
            return [dict(t) for t in have[:max(0, int(k))]]

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "spans_started": self.spans_started,
                    "active_traces": len(self._open),
                    "completed": len(self.completed),
                    "dropped": self.dropped}


TRACER = Tracer()


def tracer() -> Tracer:
    return TRACER
