"""Crash flight recorder — bounded ring of recent telemetry, dumped on
abort.

A post-mortem of a multi-day run needs the last few seconds of context, not
a live dashboard: what the loss was doing, which spans were in flight,
where the serving engine's queues stood. The recorder keeps two bounded
rings — recent metric samples (wired into the registry) and recent
``RecordEvent`` spans (wired into the profiler's flight sink, recorded even
when no Profiler is running) — and serializes both plus a full registry
snapshot to ``flight_<ts>.json`` when something dies:

* **anomaly abort** — ``AnomalyGuard.raise_divergence`` dumps with the
  final loss window attached;
* **unhandled exception** — a chained ``sys.excepthook``;
* **SIGTERM** — a chained signal handler (installed only when the slot is
  free or chainable; the PreemptionGuard's orderly path dumps explicitly
  from the trainer instead, since its TrainingPreempted exit never reaches
  the excepthook).

``Trainer.fit(checkpoint_manager=...)`` points the dump directory next to
the manager's quarantine dir (``<root>/_flight/``), so the post-mortem
ships with the checkpoint state it describes.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Optional

from .metrics import REGISTRY

__all__ = ["FlightRecorder", "recorder", "install", "maybe_dump", "set_dir"]

_SPAN_RING = 512         # recent RecordEvent spans kept
_SAMPLE_RING = 4096      # recent metric samples kept


def _strict_json(obj):
    """Replace non-finite floats with strings so the dump stays STRICT
    JSON (a NaN loss is exactly what an anomaly dump carries, and bare
    ``NaN`` tokens break every non-Python parser)."""
    if isinstance(obj, float):
        return obj if obj == obj and obj not in (float("inf"),
                                                 float("-inf")) else repr(obj)
    if isinstance(obj, dict):
        return {k: _strict_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_strict_json(v) for v in obj]
    return obj


class FlightRecorder:
    def __init__(self, dir: str = ".", span_capacity: int = _SPAN_RING,
                 sample_capacity: int = _SAMPLE_RING):
        self.dir = dir
        self.spans = deque(maxlen=span_capacity)
        self.samples = deque(maxlen=sample_capacity)
        self.active = False
        self.installed = False
        # RLock: a SIGTERM arriving mid-dump must not deadlock the
        # handler's own dump on the same (main) thread
        self._lock = threading.RLock()
        self._prev_excepthook = None
        self._prev_sigterm = None
        self.last_dump_path: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FlightRecorder":
        """Begin recording: metric samples flow into the sample ring (this
        enables the registry) and RecordEvent spans into the span ring."""
        from .. import profiler as _prof
        REGISTRY.attach_ring(self.samples)
        _prof.set_flight_sink(self.spans)
        self.active = True
        return self

    def stop(self) -> None:
        from .. import profiler as _prof
        if REGISTRY._ring is self.samples:
            REGISTRY.detach_ring()
        _prof.set_flight_sink(None)
        self.active = False

    def install(self, excepthook: bool = True, sigterm: bool = True
                ) -> "FlightRecorder":
        """Hook the process-death paths. Both hooks CHAIN the previous
        handler, so installing never hides an existing crash reporter."""
        if not self.active:
            self.start()
        if self.installed:
            return self
        if excepthook:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._excepthook
        if sigterm:
            try:
                self._prev_sigterm = signal.signal(signal.SIGTERM,
                                                   self._sigterm)
            except ValueError:       # not the main thread
                self._prev_sigterm = None
        self.installed = True
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
            self._prev_sigterm = None
        self.installed = False

    # -- triggers ------------------------------------------------------------

    def _excepthook(self, exc_type, exc, tb):
        if not issubclass(exc_type, (SystemExit, KeyboardInterrupt)):
            try:
                self.dump("unhandled_exception", extra={
                    "exception": "".join(
                        traceback.format_exception_only(exc_type, exc))
                    .strip(),
                    "traceback": "".join(
                        traceback.format_tb(tb))[-4000:],
                })
            except Exception:
                pass
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _sigterm(self, signum, frame):
        try:
            self.dump("sigterm")
        except Exception:
            pass
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    # -- dump ----------------------------------------------------------------

    def dump(self, reason: str, extra: Optional[dict] = None) -> str:
        """Serialize rings + a full registry snapshot to
        ``flight_<ts>.json`` (atomic rename) and return the path."""
        with self._lock:
            spans = [{"name": n, "start_ns": s, "end_ns": e, "tid": t,
                      "cat": c} for (n, s, e, t, c) in list(self.spans)]
            samples = [{"ts": ts, "name": n, "labels": dict(lb), "value": v}
                       for (ts, n, lb, v) in list(self.samples)]
        try:
            from .goodput import ledger
            goodput = ledger().totals()
        except Exception:
            goodput = {}
        try:
            # distributed tracing (ISSUE 19): the tracer's bounded ring
            # of complete request traces rides into the dump — serving
            # post-mortems carry request context, not just samples
            from .tracing import TRACER
            traces = TRACER.recent_traces() if TRACER.enabled else []
        except Exception:
            traces = []
        payload = {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "goodput": goodput,
            "metrics_snapshot": REGISTRY.collect(),
            "recent_samples": samples,
            "recent_spans": spans,
            "recent_traces": traces,
            "extra": extra or {},
        }
        os.makedirs(self.dir, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S")
        base = os.path.join(self.dir, f"flight_{stamp}")
        path, k = base + ".json", 0
        while os.path.exists(path):
            k += 1
            path = f"{base}-{k}.json"
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(_strict_json(payload), f, default=str,
                      allow_nan=False)
        os.replace(tmp, path)
        self.last_dump_path = path
        return path


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def install(dir: Optional[str] = None, **kw) -> FlightRecorder:
    if dir is not None:
        _RECORDER.dir = dir
    return _RECORDER.install(**kw)


def set_dir(dir: str) -> None:
    """Re-point dumps (Trainer.fit wires this next to the checkpoint
    quarantine dir)."""
    _RECORDER.dir = dir


def maybe_dump(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Dump iff the recorder is active — the hook instrumented code calls
    unconditionally (AnomalyGuard abort, preemption exit); a run that never
    opted into observability writes nothing."""
    if not _RECORDER.active:
        return None
    try:
        return _RECORDER.dump(reason, extra=extra)
    except Exception:
        return None
