"""paddle_tpu.observability.sentry — declarative SLOs over the metrics
plane, correlated incident capture, noise-aware bench regression gating.

The closing third of the observability loop (ISSUE 10): PR 4's registry
records, PR 9's cost observatory attributes, this package *watches*.

Quickstart::

    from paddle_tpu.observability import sentry as sn

    rules = sn.trainer_rules() + sn.serving_rules(itl_p99_ceiling_s=0.2)
    sn.install(sn.SloSentry(rules, incident_log="incidents.jsonl",
                            flight_dump=True, min_interval_s=1.0))
    trainer.fit(...)          # ticks at log boundaries
    engine.run()              # ticks at drain boundaries
    for inc in sn.active().incidents:
        print(inc.rule, inc.severity, inc.context["goodput"])

The bench half (:mod:`baselines` + ``tools/bench_diff.py``) applies the
same watch-the-ratios discipline to the checked-in bench artifacts.
"""

from __future__ import annotations

from . import baselines as baselines  # noqa: F401 (re-export module)
from .baselines import (RATIO_METRICS, BenchDiff, RatioMetric, backend_of,
                        diff_records, load_record, pin_baseline,
                        ratio_metrics_of)
from .rules import (EwmaSpike, RatioBand, SloRule, Staleness, Threshold,
                    default_rules, elastic_rules, fabric_rules,
                    frontdoor_rules, moe_rules, serving_rules,
                    trainer_rules)
from .sentry import (Incident, SloSentry, active, install, maybe_tick,
                     uninstall)

__all__ = [
    "SloRule", "Threshold", "EwmaSpike", "RatioBand", "Staleness",
    "trainer_rules", "serving_rules", "fabric_rules", "frontdoor_rules",
    "elastic_rules", "moe_rules", "default_rules",
    "Incident", "SloSentry", "install", "uninstall", "active",
    "maybe_tick",
    "baselines", "RatioMetric", "RATIO_METRICS", "BenchDiff",
    "load_record", "backend_of", "ratio_metrics_of", "pin_baseline",
    "diff_records",
]
