"""SloSentry — pull-based rule evaluation + correlated incident capture.

The closing third of the observability loop: the metrics plane (PR 4)
records, the cost observatory (PR 9) attributes, the sentry *watches*.
``tick()`` is called from the boundaries the runtimes already cross
(``Trainer.fit`` log boundaries, ``ContinuousBatchingEngine`` drain
boundaries) — no threads, no timers, and ONE attr-load + branch when the
metrics plane is disabled (the PR 4 contract).

A tick snapshots the registry, resolves each rule's series, applies the
rule's predicate, and runs hysteresis/cooldown: a rule must breach
``breach_for`` consecutive windows to fire, and while the breach persists
it re-fires at most every ``cooldown_s`` — no incident storms. Firing
emits an :class:`Incident` that carries the *correlated* context a
post-mortem starts from: the rule's windowed stats, the
``pt_step_time_breakdown`` buckets and the goodput ledger totals at
breach time. Incidents are appended to a crash-safe JSONL (same
single-write + flush discipline as the metric exporter — at worst one
torn final line, which the tolerant loader skips), mirrored into
``pt_slo_incidents_total{rule=...}``, and can trigger a flight-recorder
dump through the existing ``profiler.set_flight_sink`` ring path.

Module-level ``install()`` makes one sentry the process sentry;
``maybe_tick()`` is the near-zero hook the trainer and serving engine
call (no sentry installed → a global load + branch).
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from collections import deque
from typing import Dict, List, Optional

from ..metrics import REGISTRY

__all__ = ["Incident", "SloSentry", "install", "uninstall", "active",
           "maybe_tick"]

_INCIDENT_RING = 256          # recent incidents kept on the sentry


class Incident:
    """One fired rule: what breached, by how much, and what the system
    looked like at that instant."""

    def __init__(self, rule, value, stats: dict, breach_windows: int,
                 context: dict, ts: float):
        self.ts = ts
        self.rule = rule.name
        self.kind = rule.kind
        self.metric = rule.metric
        self.labels = dict(rule.labels)
        self.severity = rule.severity
        self.description = rule.description
        self.value = value
        self.stats = stats
        self.breach_windows = breach_windows
        self.context = context

    def to_dict(self) -> dict:
        return {"ts": self.ts, "rule": self.rule, "kind": self.kind,
                "metric": self.metric, "labels": self.labels,
                "severity": self.severity,
                "description": self.description, "value": self.value,
                "stats": self.stats,
                "breach_windows": self.breach_windows,
                "context": self.context}

    def __repr__(self):
        return (f"Incident({self.rule!r}, severity={self.severity!r}, "
                f"value={self.value!r}, windows={self.breach_windows})")


# incident payloads must stay strict JSON — the flight recorder owns
# that contract, reuse its sanitizer (ONE definition)
from ..flight_recorder import _strict_json as _finite


class SloSentry:
    """Evaluate ``rules`` against registry snapshots on each tick.

    ``incident_log`` — JSONL path incidents append to (None = in-memory
    only). ``flight_dump`` — also trigger a flight-recorder dump per
    incident (a no-op unless the recorder is active). ``min_interval_s``
    — rate-limit full snapshot evaluation from hot tick sites (a serving
    engine ticking every scheduler pass must not pay a collect() each
    time); 0 evaluates every tick, which is what unit tests want.
    """

    def __init__(self, rules, incident_log: Optional[str] = None,
                 flight_dump: bool = False, min_interval_s: float = 0.0,
                 refresh_derived: bool = True):
        rules = list(rules)     # a generator must survive the name scan
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self.rules = rules
        self.incident_log = incident_log
        self.flight_dump = bool(flight_dump)
        self.min_interval_s = float(min_interval_s)
        self.refresh_derived = bool(refresh_derived)
        self.incidents = deque(maxlen=_INCIDENT_RING)
        self.ticks = 0
        self._state: Dict[str, dict] = {r.name: {"streak": 0,
                                                 "last_fire": None}
                                        for r in self.rules}
        self._last_eval: Optional[float] = None
        self._lock = threading.Lock()

    # -- series resolution ---------------------------------------------------

    @staticmethod
    def _resolve(rule, by_name: Dict[str, List[dict]],
                 state: dict) -> Optional[float]:
        """Value of the rule's series in this snapshot, or None. Label
        subset match; exact label set preferred; non-numeric fields
        (histogram percentile absent on an empty series) read as
        missing — a rule never sees a stale zero.

        ``field="window_mean"`` derives the mean of a histogram's NEW
        observations since the previous tick (delta sum ÷ delta count,
        anchored in ``state``): the per-window statistic a spike rule
        needs — reservoir percentiles move only after a majority of a
        long horizon has already spiked."""
        entries = by_name.get(rule.metric)
        if not entries:
            return None
        want = rule.labels
        best = None
        for e in entries:
            lbs = e.get("labels", {})
            if all(lbs.get(k) == str(v) for k, v in want.items()):
                if {k: v for k, v in lbs.items()} == \
                        {str(k): str(v) for k, v in want.items()}:
                    best = e
                    break
                if best is None:
                    best = e
        if best is None:
            return None
        if rule.field == "window_mean":
            tot, cnt = best.get("sum"), best.get("count")
            if not isinstance(tot, (int, float)) \
                    or not isinstance(cnt, (int, float)):
                return None
            prev = state.get("_wm_prev")
            state["_wm_prev"] = (tot, cnt)
            if prev is None or cnt <= prev[1] or tot < prev[0]:
                # first sighting anchors; a count that went backwards is
                # a registry reset — re-anchor rather than divide noise
                return None
            return (tot - prev[0]) / (cnt - prev[1])
        v = best.get(rule.field)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        return float(v)

    @staticmethod
    def _context(by_name: Dict[str, List[dict]]) -> dict:
        """The correlated capture every incident carries: breakdown
        buckets (PR 9) and the goodput ledger (PR 4) at breach time."""
        breakdown: Dict[str, dict] = {}
        for e in by_name.get("pt_step_time_breakdown", ()):
            lbs = e.get("labels", {})
            comp = lbs.get("component", "")
            breakdown.setdefault(comp, {})[lbs.get("bucket", "?")] = \
                e.get("value")
        try:
            from ..goodput import ledger
            goodput = ledger().totals()
        except Exception:
            goodput = {}
        drift = {e.get("labels", {}).get("component", "?"): e.get("value")
                 for e in by_name.get(
                     "pt_step_time_predicted_over_measured", ())}
        return {"step_time_breakdown": breakdown, "goodput": goodput,
                "predicted_over_measured": drift}

    # -- the tick ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[Incident]:
        """Evaluate every rule once; returns the incidents fired by THIS
        tick. First line is the disabled-plane guard — parity with every
        other instrumented hot path."""
        if not REGISTRY.enabled:
            return []
        now = time.monotonic() if now is None else now
        with self._lock:
            if (self.min_interval_s > 0.0 and self._last_eval is not None
                    and now - self._last_eval < self.min_interval_s):
                return []
            self._last_eval = now
            self.ticks += 1
            if self.refresh_derived:
                # goodput gauges only land in the registry on publish();
                # refresh them so a floor rule sees the live fraction
                try:
                    from ..goodput import ledger
                    ledger().publish()
                except Exception:
                    pass
            by_name: Dict[str, List[dict]] = {}
            for e in REGISTRY.collect():
                by_name.setdefault(e["name"], []).append(e)
            fired: List[Incident] = []
            context = None
            for rule in self.rules:
                st = self._state[rule.name]
                try:
                    value = self._resolve(rule, by_name, st)
                    breached, stats = rule.check(value, st, now)
                except Exception as e:
                    # one faulty rule must not disable the sentry: skip
                    # it (warned once), keep evaluating the rest — the
                    # watcher can't be allowed to die silently
                    if not st.get("eval_warned"):
                        st["eval_warned"] = True
                        warnings.warn(
                            f"SloSentry: rule {rule.name!r} evaluation "
                            f"failed ({e!r}); rule skipped",
                            RuntimeWarning)
                    continue
                if not breached:
                    # a SKIPPED window (series missing / first delta
                    # anchor) is not a recovery: freezing the streak
                    # matters because this plane legitimately drops
                    # series (serving clears percentile gauges when the
                    # latency window empties) — bursty breaches must
                    # still accumulate to breach_for
                    if "skipped" not in stats:
                        st["streak"] = 0
                    continue
                st["streak"] += 1
                if st["streak"] < rule.breach_for:
                    continue
                last = st["last_fire"]
                if last is not None and now - last < rule.cooldown_s:
                    continue
                st["last_fire"] = now
                if context is None:        # one capture per tick
                    context = self._context(by_name)
                inc = Incident(rule, value, stats, st["streak"],
                               context, ts=time.time())
                self._attach_traces(inc)
                fired.append(inc)
            for inc in fired:
                self._record(inc)
        return fired

    # -- incident sinks ------------------------------------------------------

    @staticmethod
    def _attach_traces(inc: Incident) -> None:
        """Latency incidents carry their evidence (ISSUE 19): a TTFT or
        ITL breach attaches the K worst complete request traces so the
        post-mortem starts from the offending span trees, not just
        percentiles. The shared per-tick context capture is copied
        before mutation — other incidents this tick must not inherit
        the traces."""
        m = f"{inc.metric or ''} {inc.rule}"
        if "ttft" not in m and "itl" not in m:
            return
        try:
            from ..tracing import TRACER
            if not TRACER.enabled:
                return
            worst = TRACER.worst_traces(3)
        except Exception:
            return
        if worst:
            inc.context = dict(inc.context or {})
            inc.context["attached_traces"] = worst

    def _record(self, inc: Incident) -> None:
        self.incidents.append(inc)
        try:
            REGISTRY.counter(
                "pt_slo_incidents_total",
                "SLO incidents fired by the sentry").inc(rule=inc.rule)
        except Exception:
            pass
        if self.incident_log:
            try:
                d = os.path.dirname(os.path.abspath(self.incident_log))
                os.makedirs(d, exist_ok=True)
                line = json.dumps(_finite(inc.to_dict()), sort_keys=True,
                                  allow_nan=False)
                # one write + flush: at worst a torn final line, which
                # load_jsonl tolerates (the exporter's crash contract)
                with open(self.incident_log, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
                    f.flush()
            except Exception as e:
                # a bad path must not lose incidents INVISIBLY — the
                # in-memory ring and counter still have them, but the
                # operator reading the (absent) file must be told once
                if not getattr(self, "_log_warned", False):
                    self._log_warned = True
                    warnings.warn(
                        f"SloSentry: cannot append incidents to "
                        f"{self.incident_log!r} ({e}); incidents stay "
                        f"in memory only", RuntimeWarning)
        if self.flight_dump:
            try:
                from ..flight_recorder import maybe_dump
                maybe_dump(f"slo_incident:{inc.rule}",
                           extra=_finite(inc.to_dict()))
            except Exception:
                pass

    # -- reporting -----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"ticks": self.ticks,
                    "incidents": len(self.incidents),
                    "rules": {r.name: {"streak":
                                       self._state[r.name]["streak"]}
                              for r in self.rules}}

    @staticmethod
    def load_incidents(path: str) -> List[dict]:
        """Tolerant incident-JSONL loader (delegates to the exporter's
        torn-tail-tolerant parser — ONE definition of that contract)."""
        from ..exporters import JSONLExporter
        return JSONLExporter.load_jsonl(path)


# ---------------------------------------------------------------------------
# process-wide hook
# ---------------------------------------------------------------------------

_ACTIVE: Optional[SloSentry] = None


def install(sentry: SloSentry) -> SloSentry:
    """Make ``sentry`` the process sentry ticked by the trainer / serving
    engine hooks. Replaces any previous one (a re-run setup cell must not
    stack duplicate watchers)."""
    global _ACTIVE
    _ACTIVE = sentry
    return sentry


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[SloSentry]:
    return _ACTIVE


def maybe_tick() -> List[Incident]:
    """The hook instrumented boundaries call unconditionally: no sentry
    installed (the default) or plane disabled → a load + branch, nothing
    else. Evaluation failures never break the loop that hosts the tick."""
    s = _ACTIVE
    if s is None or not REGISTRY.enabled:
        return []
    try:
        return s.tick()
    except Exception as e:
        # last-resort catch so a systemic failure (collect() itself
        # raising) can't break the train/serve loop hosting the tick —
        # but the watcher must not die SILENTLY: warn once per sentry
        if not getattr(s, "_tick_warned", False):
            s._tick_warned = True
            warnings.warn(f"SloSentry: tick() failed ({e!r}); sentry "
                          f"evaluation is broken until fixed",
                          RuntimeWarning)
        return []
