"""Bench baselines: pin ratio metrics from BENCH artifacts, diff with
noise-aware bands.

The bench-variance policy (BASELINE.md, every round since PR 3): on this
noisy shared host, absolute tok/s is weather — RATIO metrics (MFU,
A/B speedups, efficiency and hit rates, the predicted-over-measured
drift) are the signal. This module turns that policy into a mechanical
gate:

* :data:`RATIO_METRICS` — the census of comparable ratio rows a bench
  record can carry, each with the direction that counts as *worse* and a
  per-metric relative noise band;
* :func:`pin_baseline` — extract those rows from an artifact into a
  small pinned-baseline dict (checked in as ``tools/bench_baseline.json``);
* :func:`diff_records` — compare a candidate record against a baseline
  (or a second artifact): a metric regresses only when it moves past its
  band in the *worse* direction. Ratios are backend-relative, so records
  from different backends (a TPU round vs a CPU fallback round) compare
  NOTHING — every row is skipped with the reason named, and the verdict
  is "incomparable", not a fake pass/fail.

Both the driver's round files (``BENCH_r*.json``, ``{"parsed": {...}}``)
and raw bench output records (``{"metric": ..., "detail": {...}}``) load
through :func:`load_record`.
"""

from __future__ import annotations

import json
import math
import os
import re
from typing import Dict, List, Optional, Tuple

__all__ = ["RatioMetric", "RATIO_METRICS", "load_record", "backend_of",
           "ratio_metrics_of", "pin_baseline", "diff_records",
           "BenchDiff", "BASELINE_SCHEMA"]

BASELINE_SCHEMA = "pt-bench-baseline-v1"

_DEFAULT_BAND = 0.25          # relative; generous for a shared noisy host


class RatioMetric:
    """One comparable row: where it lives in the record, which direction
    is worse, and how far it may move before the gate calls regression.

    ``worse`` — "lower" (throughput-like: MFU, speedups, hit rates),
    "higher" (overhead-like: obs_overhead_ratio), or "either" (a
    self-ratio whose healthy value is ~1.0, drifting in any direction is
    bad). ``band`` is relative: candidate ÷ baseline beyond
    ``1 ± band`` in the worse direction regresses.

    ``cpu_band`` widens the band when BOTH records ran the cpu tier:
    MFU and vs_baseline on a fixed config are linear rescalings of
    absolute tok/s, so comparing them ACROSS runs on this shared host
    re-imports the very noise the ratio policy exists to dodge
    (documented swings ~±40%). The wide cpu band keeps the gate able to
    catch catastrophic collapses (a wrong loss head, a dead fast path)
    without paging on weather; within-run A/B ratios (speedups,
    overhead, drift) keep their tight bands on every backend.
    """

    def __init__(self, name: str, worse: str = "lower",
                 band: float = _DEFAULT_BAND, headline: bool = False,
                 cpu_band: Optional[float] = None):
        assert worse in ("lower", "higher", "either")
        self.name = name
        self.worse = worse
        self.band = float(band)
        self.headline = headline        # lives at record top level
        self.cpu_band = cpu_band        # wider band on the cpu tier


RATIO_METRICS: Dict[str, RatioMetric] = {m.name: m for m in [
    RatioMetric("vs_baseline", "lower", headline=True, cpu_band=0.45),
    # MFU family (PaLM closed form + causal + fenced + HLO-attributed):
    # cross-RUN absolute-derived on a fixed config, hence cpu_band
    RatioMetric("mfu", "lower", cpu_band=0.45),
    RatioMetric("mfu_causal", "lower", cpu_band=0.45),
    RatioMetric("mfu_fenced_causal", "lower", cpu_band=0.45),
    RatioMetric("mfu_analytical", "lower", cpu_band=0.45),
    RatioMetric("longctx_mfu", "lower", cpu_band=0.45),
    RatioMetric("longctx_mfu_causal", "lower", cpu_band=0.45),
    # cost-model drift: healthy ~1.0, either direction is drift — wide
    # band, the live RatioBand rule holds the tight one
    RatioMetric("step_time_predicted_over_measured", "either", band=0.5),
    # observability overhead: metrics-on ÷ metrics-off, healthy ~1.0
    RatioMetric("obs_overhead_ratio", "higher", band=0.15),
    # distributed tracing (ISSUE 19): traced ÷ untraced smoke load-test
    # wall time, healthy ~1.0 — the zero-cost contract's bench gate.
    # Same shape as obs_overhead_ratio but the smoke leg is a full
    # serving stack (compiles amortized, still host-noisy): wider band
    RatioMetric("trace_overhead_ratio", "higher", band=0.25),
    # serving efficiency and A/B speedups (interleaved min-of-rounds
    # ratios, but still rider on host noise — keep the wide default)
    RatioMetric("serving_decode_efficiency", "lower", band=0.35),
    RatioMetric("spec_decode_speedup", "lower", band=0.35),
    RatioMetric("spec_decode_speedup_b4", "lower", band=0.35),
    RatioMetric("spec_decode_speedup_vs_block", "lower", band=0.35),
    RatioMetric("spec_decode_speedup_vs_block_b4", "lower", band=0.35),
    RatioMetric("spec_accept_rate", "lower"),
    RatioMetric("spec_accept_rate_b4", "lower"),
    RatioMetric("spec_mean_accepted_len", "lower"),
    RatioMetric("prefix_reuse_ttft_speedup", "lower", band=0.35),
    RatioMetric("prefix_hit_rate", "lower"),
    # serving fabric (ISSUE 12): within-run A/B ratios over interleaved
    # min-of-rounds legs — affinity÷round-robin TTFT and goodput, and
    # the disagg÷no-disagg decode ITL p99 (lower is better there, so
    # HIGHER is worse; generous band, ITL p99 tails ride host noise)
    RatioMetric("fabric_affinity_ttft_speedup", "lower", band=0.35),
    RatioMetric("fabric_goodput_ratio", "lower", band=0.35),
    RatioMetric("fabric_p99_itl_with_disagg_ratio", "higher", band=0.5),
    RatioMetric("loss_head_fused_speedup", "lower", band=0.35),
    # sharding planner (ISSUE 11): rank-order validation vs measured.
    # top1-in-top2 is binary (1.0 healthy) — any drop to 0 must page,
    # hence the tight band; agreement is a 0.5-1.0 concordance score
    # riding measured step times, so it keeps the wide default
    RatioMetric("planner_top1_is_measured_top2", "lower", band=0.01),
    RatioMetric("planner_rank_agreement", "lower", band=0.3),
    RatioMetric("planner_predicted_mfu", "lower", cpu_band=0.45),
    # ZeRO/FSDP axis (ISSUE 18): fsdp4 ÷ dp4 measured step time at
    # equal devices (the gather/reduce-scatter tax — growth means the
    # overlap contract stopped hiding the windows; rides host noise,
    # wide band) and the same pair's closed-form HBM high-water ratio
    # (deterministic arithmetic, tight band — a rise means the ZeRO
    # sharding of params/slots/grads eroded)
    RatioMetric("fsdp_step_overhead_ratio", "higher", band=0.5),
    RatioMetric("fsdp_hbm_ratio", "higher", band=0.1),
    # latency-hiding contract (ISSUE 14): exposed (un-overlapped) comm
    # fraction of the dp2xtp2 canonical step — structural per build, a
    # GROWING fraction means a hiding window collapsed (higher=worse) —
    # and the overlap-flags off÷on step-time ratio (interleaved
    # min-of-rounds subprocess A/B; rides host noise, wide band)
    RatioMetric("overlap_exposed_comm_fraction", "higher", band=0.5),
    RatioMetric("overlap_on_step_speedup", "lower", band=0.35),
    # front-door robustness (ISSUE 16): shed-enabled ÷ shed-disabled
    # admitted goodput at 2x capacity offered load (shedding must BUY
    # throughput for admitted work, lower = the ladder stopped paying
    # for itself), and hung-replica p99 TTFT with breaker ÷ without
    # (tight op budgets ÷ loose ones — the breaker's early trip must
    # keep the tail DOWN, so higher is worse; both ride host noise and
    # thread scheduling, generous bands)
    RatioMetric("frontdoor_goodput_under_overload", "lower", band=0.4),
    RatioMetric("frontdoor_p99_ttft_with_breaker_ratio", "higher",
                band=0.5),
    # quantized serving (ISSUE 17): int8 ÷ bf16 engine tok/s at EQUAL
    # HBM budget (interleaved min-of-rounds; the bf16 leg thrashes by
    # design, so the ratio rides recompute scheduling — wide band), the
    # max-resident-slots capacity ratio (integer slot counts over the
    # engine's own preemption machinery — near-deterministic, tight
    # band), the int8 leg's serving÷raw-kernel efficiency, and the
    # greedy int8-vs-bf16 stream agreement (free-running, one near-tie
    # flip cascades; the hard floor lives in the tests)
    RatioMetric("quant_decode_speedup", "lower", band=0.4),
    RatioMetric("quant_kv_capacity_ratio", "lower", band=0.15),
    RatioMetric("quant_serving_decode_efficiency", "lower", band=0.35),
    RatioMetric("quant_stream_agreement", "lower", band=0.4),
    # expert parallelism (ISSUE 20): replicated ÷ ep2 measured MoE step
    # at equal devices/experts (interleaved min-of-rounds; the a2a tax
    # vs the expert-HBM win — collapse means the dispatch path
    # regressed; rides host noise, wide band), the priced-census
    # per-a2a seconds ÷ a wall-clock shard_map all-to-all (cost-model
    # drift for the NEW collective; CPU constants are nominal, so only
    # the drift-of-the-ratio is gated, either direction), and XLA
    # ragged_dot ÷ Pallas grouped matmul (within-run A/B; the CPU leg
    # runs interpret mode — structurally stable but not a perf claim,
    # hence the wider cpu band)
    RatioMetric("moe_ep_step_speedup", "lower", band=0.35),
    RatioMetric("moe_ep_a2a_pred_over_measured", "either", band=0.5),
    RatioMetric("moe_grouped_matmul_speedup", "lower", band=0.35,
                cpu_band=0.6),
]}


# ---------------------------------------------------------------------------
# record loading / extraction
# ---------------------------------------------------------------------------

def load_record(path: str) -> dict:
    """Load a bench record from either shape: a driver round file
    (``BENCH_r*.json``: ``{"parsed": {...}}``) or a raw bench payload /
    pinned baseline."""
    with open(path, encoding="utf-8") as f:
        d = json.load(f)
    if isinstance(d.get("parsed"), dict):
        d = d["parsed"]
    return d


def is_baseline(record: dict) -> bool:
    return record.get("schema") == BASELINE_SCHEMA


def backend_of(record: dict) -> str:
    if is_baseline(record):
        return str(record.get("backend", "unknown"))
    det = record.get("detail") or {}
    return str(det.get("backend", "unknown"))


def ratio_metrics_of(record: dict) -> Dict[str, float]:
    """The finite ratio rows present in ``record`` (baseline dicts pass
    straight through)."""
    if is_baseline(record):
        src = record.get("metrics", {})
        return {k: float(v) for k, v in src.items()
                if k in RATIO_METRICS and _finite_num(v)}
    det = record.get("detail") or {}
    out: Dict[str, float] = {}
    for name, spec in RATIO_METRICS.items():
        v = record.get(name) if spec.headline else det.get(name)
        if _finite_num(v):
            out[name] = float(v)
    return out


def _finite_num(v) -> bool:
    # zero is a VALID candidate value (a collapsed hit rate is the most
    # extreme regression, not a missing row) — only non-numbers and
    # non-finite floats read as absent
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(v))


def pin_baseline(record: dict, source: str = "") -> dict:
    """Freeze ``record``'s ratio rows into the pinned-baseline shape the
    CI gate diffs against. Deliberately tiny and diff-friendly — this is
    a checked-in file. Zero-valued rows are not pinned: a zero baseline
    can anchor no ratio (and usually means the probe didn't run)."""
    return {"schema": BASELINE_SCHEMA,
            "source": source or record.get("metric", ""),
            "backend": backend_of(record),
            "metrics": {k: round(v, 6)
                        for k, v in sorted(ratio_metrics_of(record)
                                           .items()) if v != 0}}


# ---------------------------------------------------------------------------
# diffing
# ---------------------------------------------------------------------------

class BenchDiff:
    """Result of one baseline-vs-candidate comparison."""

    def __init__(self, rows: List[dict], backend_base: str,
                 backend_cand: str, note: str = ""):
        self.rows = rows
        self.backend_base = backend_base
        self.backend_cand = backend_cand
        self.note = note

    @property
    def regressions(self) -> List[str]:
        return [r["metric"] for r in self.rows
                if r["status"] == "regressed"]

    @property
    def improvements(self) -> List[str]:
        return [r["metric"] for r in self.rows
                if r["status"] == "improved"]

    @property
    def compared(self) -> int:
        return sum(r["status"] != "skipped" for r in self.rows)

    @property
    def ok(self) -> bool:
        return not self.regressions

    def verdict(self) -> str:
        if self.regressions:
            return "regressed"
        if self.compared == 0:
            return "incomparable"
        return "ok"

    def summary(self) -> dict:
        return {"verdict": self.verdict(), "compared": self.compared,
                "skipped": len(self.rows) - self.compared,
                "regressions": self.regressions,
                "improvements": self.improvements,
                "backend": f"{self.backend_base} vs {self.backend_cand}",
                **({"note": self.note} if self.note else {})}

    def format(self) -> str:
        lines = [f"{'metric':<38} {'base':>10} {'cand':>10} "
                 f"{'ratio':>7} {'band':>11}  status"]
        for r in self.rows:
            if r["status"] == "skipped":
                lines.append(f"{r['metric']:<38} {'-':>10} {'-':>10} "
                             f"{'-':>7} {'-':>11}  skipped"
                             f" ({r['reason']})")
                continue
            band = f"±{r['band']:.0%}" if r["worse"] == "either" else (
                f"-{r['band']:.0%}" if r["worse"] == "lower"
                else f"+{r['band']:.0%}")
            lines.append(
                f"{r['metric']:<38} {r['base']:>10.4g} {r['cand']:>10.4g}"
                f" {r['ratio']:>7.3f} {band:>11}  {r['status']}")
        if self.note:
            lines.append(f"note: {self.note}")
        s = self.summary()
        lines.append(f"verdict: {s['verdict']} "
                     f"(compared={s['compared']}, "
                     f"skipped={s['skipped']}"
                     + (f", regressions={','.join(self.regressions)}"
                        if self.regressions else "") + ")")
        return "\n".join(lines)


def diff_records(base: dict, cand: dict,
                 band_override: Optional[float] = None) -> BenchDiff:
    """Compare candidate against baseline over the ratio census.

    Per-metric: ``ratio = cand ÷ base``; worse-direction moves past the
    band regress, better-direction moves past it report "improved",
    inside the band is "ok". Metrics either side lacks are skipped with
    the reason. Backend mismatch skips EVERYTHING — cross-backend ratios
    (a TPU MFU vs a CPU MFU) are not noise, they are different
    quantities."""
    bb, cb = backend_of(base), backend_of(cand)
    bm, cm = ratio_metrics_of(base), ratio_metrics_of(cand)
    rows: List[dict] = []
    note = ""
    if bb != cb or "unknown" in (bb, cb):
        # an UNKNOWN backend (pre-backend-field artifacts) must not
        # bypass the guard: "can't prove same backend" compares nothing,
        # same as a proven mismatch — never a fake pass/fail
        if "unknown" in (bb, cb):
            who = " and ".join(s for s, b in (("base", bb),
                                              ("candidate", cb))
                               if b == "unknown")
            reason = "backend unknown"
            note = (f"backend unknown on {who}: cannot prove both "
                    f"records ran the same backend, nothing is "
                    f"comparable")
        else:
            reason = "backend mismatch"
            note = (f"backend mismatch ({bb} vs {cb}): ratio metrics "
                    f"are backend-relative, nothing is comparable")
        for name in sorted(set(bm) | set(cm)):
            rows.append({"metric": name, "status": "skipped",
                         "reason": reason})
        return BenchDiff(rows, bb, cb, note)
    for name in sorted(set(bm) | set(cm)):
        spec = RATIO_METRICS[name]
        if name not in bm or name not in cm:
            rows.append({"metric": name, "status": "skipped",
                         "reason": ("absent from baseline"
                                    if name not in bm
                                    else "absent from candidate")})
            continue
        b, c = bm[name], cm[name]
        if b == 0:
            # a second-artifact base (pinned baselines never carry
            # zeros) — no ratio can anchor on it
            rows.append({"metric": name, "status": "skipped",
                         "reason": "zero baseline value"})
            continue
        if band_override is not None:
            band = band_override
        elif spec.cpu_band is not None and bb == "cpu":
            band = spec.cpu_band
        else:
            band = spec.band
        ratio = c / b
        if spec.worse == "either":
            status = ("regressed" if abs(ratio - 1.0) > band else "ok")
        elif spec.worse == "lower":
            status = ("regressed" if ratio < 1.0 - band
                      else "improved" if ratio > 1.0 + band else "ok")
        else:  # worse == "higher"
            status = ("regressed" if ratio > 1.0 + band
                      else "improved" if ratio < 1.0 - band else "ok")
        rows.append({"metric": name, "base": b, "cand": c,
                     "ratio": round(ratio, 4), "band": band,
                     "worse": spec.worse, "status": status})
    return BenchDiff(rows, bb, cb, note)


def newest_round_artifact(repo_root: str) -> Optional[str]:
    """Highest-numbered ``BENCH_r*.json`` with a parsed payload (the
    default pin source). Ordered by the NUMERIC round — lexicographic
    sort would pin r99 over r100 (and r9 over r10) forever."""
    pat = re.compile(r"^BENCH_r(\d+)\.json$")
    cands = sorted((p for p in os.listdir(repo_root) if pat.match(p)),
                   key=lambda p: int(pat.match(p).group(1)))
    for p in reversed(cands):
        path = os.path.join(repo_root, p)
        try:
            if ratio_metrics_of(load_record(path)):
                return path
        except Exception:
            continue
    return None
