"""Declarative SLO rule vocabulary evaluated over metrics snapshots.

A rule names ONE series in the ``MetricsRegistry.collect()`` snapshot
(metric name + a label subset + a field — ``value`` for counters/gauges,
``p50``/``p99``/``count``/``sum`` for histograms) and a breach predicate
over its windowed value. The vocabulary is deliberately small — the same
four shapes the reference stack's fleet monitors reduce to:

* :class:`Threshold` — ceiling and/or floor on the value (or, with
  ``delta=True``, on the per-window change — the rate form a monotonic
  counter like ``pt_serving_pool_dry_drains_total`` needs);
* :class:`EwmaSpike` — value exceeds ``spike_ratio`` x its own EWMA
  (after a warmup), the step-time-jumped-3x detector;
* :class:`RatioBand` — value ÷ a pinned baseline falls outside
  ``[low, high]`` — the bench-variance policy's ratio-not-absolute
  discipline as a live rule (and the drift band the sharding planner
  reads to know its cost tables are stale);
* :class:`Staleness` — the series is absent from the snapshot (or, with
  ``require_change=True``, present but frozen) — the watcher's watcher:
  a plane that silently stopped publishing looks healthy to every other
  rule kind.

Rules carry their own *hysteresis* (``breach_for`` consecutive breached
windows before an incident) and *cooldown* (``cooldown_s`` between
incidents while the breach persists) — both enforced by the sentry core,
so every rule kind shares one tested implementation. A rule whose series
is missing is SKIPPED, not breached (except Staleness, whose whole job is
absence): a serving pack applied to a train-only process must stay quiet.

Evaluation is pure bookkeeping over plain floats — no device work, no
threads; per-rule mutable state lives in the dict the sentry owns, so a
rule object itself is immutable and shareable.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["SloRule", "Threshold", "EwmaSpike", "RatioBand", "Staleness",
           "trainer_rules", "serving_rules", "fabric_rules",
           "frontdoor_rules", "elastic_rules", "tracing_rules",
           "moe_rules", "default_rules"]


class SloRule:
    """Base: identity + series selector + hysteresis/cooldown knobs.

    ``labels`` is a SUBSET match against a series' label set (``{}``
    matches any); when several series match, the exact label set wins,
    else the first in snapshot order. ``field`` picks the snapshot entry
    key to read (histogram entries expose p50/p99/count/sum), plus the
    derived ``window_mean`` — mean of a histogram's new observations
    since the previous tick, the right input for a spike rule (reservoir
    percentiles lag a transient by half the reservoir).
    """

    kind = "rule"

    def __init__(self, name: str, metric: str,
                 labels: Optional[Dict[str, str]] = None,
                 field: str = "value", severity: str = "warning",
                 breach_for: int = 1, cooldown_s: float = 60.0,
                 description: str = ""):
        if breach_for < 1:
            raise ValueError(f"rule {name!r}: breach_for must be >= 1")
        if severity not in ("info", "warning", "critical"):
            raise ValueError(f"rule {name!r}: unknown severity "
                             f"{severity!r}")
        self.name = name
        self.metric = metric
        self.labels = dict(labels or {})
        self.field = field
        self.severity = severity
        self.breach_for = int(breach_for)
        self.cooldown_s = float(cooldown_s)
        self.description = description

    def check(self, value: Optional[float], state: dict,
              now: float) -> Tuple[bool, dict]:
        """One evaluation window: ``value`` is the resolved series value
        (None = series missing). Returns ``(breached, stats)``; ``stats``
        rides into the incident so the post-mortem carries the rule's
        windowed view, not just "it fired"."""
        raise NotImplementedError

    def __repr__(self):
        return (f"{type(self).__name__}({self.name!r}, "
                f"metric={self.metric!r})")


class Threshold(SloRule):
    """Ceiling and/or floor on the value; ``delta=True`` evaluates the
    per-window change instead (first window establishes the anchor and
    never breaches)."""

    kind = "threshold"

    def __init__(self, name: str, metric: str, ceiling: float = None,
                 floor: float = None, delta: bool = False, **kw):
        super().__init__(name, metric, **kw)
        if ceiling is None and floor is None:
            raise ValueError(f"rule {name!r}: need a ceiling or a floor")
        self.ceiling = None if ceiling is None else float(ceiling)
        self.floor = None if floor is None else float(floor)
        self.delta = bool(delta)

    def check(self, value, state, now):
        if value is None:
            return False, {"skipped": "series missing"}
        if self.delta:
            prev = state.get("prev")
            state["prev"] = value
            if prev is None:
                return False, {"skipped": "first window (delta anchor)"}
            value = value - prev
        stats = {"value": value, "ceiling": self.ceiling,
                 "floor": self.floor, "delta": self.delta}
        breached = ((self.ceiling is not None and value > self.ceiling)
                    or (self.floor is not None and value < self.floor))
        return breached, stats


class EwmaSpike(SloRule):
    """Value exceeds ``spike_ratio`` x its own exponentially-weighted
    moving average. The EWMA warms up for ``warmup`` windows before the
    rule can breach. While a breach streak is still short of
    ``breach_for`` the EWMA is FROZEN — each consecutive spiked window
    is judged against the pre-spike average, otherwise the first
    breached sample inflates the baseline and ``breach_for >= 2`` could
    only ever fire on a spike that out-spiked its own absorption
    (~spike_ratio² for the shipped defaults — a dead detector).
    Once the streak reaches ``breach_for`` (the window the sentry
    fires) absorption resumes, so a persistent level shift still
    becomes the new normal and stops re-breaching after one incident —
    the spike-vs-new-normal distinction this kind encodes (a permanent
    shift belongs to Threshold/RatioBand)."""

    kind = "ewma_spike"

    def __init__(self, name: str, metric: str, spike_ratio: float = 2.0,
                 alpha: float = 0.3, warmup: int = 3, **kw):
        super().__init__(name, metric, **kw)
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"rule {name!r}: alpha must be in (0, 1]")
        if spike_ratio <= 1.0:
            raise ValueError(f"rule {name!r}: spike_ratio must be > 1")
        self.spike_ratio = float(spike_ratio)
        self.alpha = float(alpha)
        self.warmup = int(warmup)

    def check(self, value, state, now):
        if value is None:
            return False, {"skipped": "series missing"}
        ewma = state.get("ewma")
        n = state.get("ewma_n", 0)
        breached = False
        stats = {"value": value, "ewma": ewma,
                 "spike_ratio": self.spike_ratio, "windows_seen": n}
        if ewma is not None and n >= self.warmup:
            breached = value > self.spike_ratio * ewma
        # state["streak"] is the sentry's count BEFORE this window
        if not breached or state.get("streak", 0) + 1 >= self.breach_for:
            state["ewma"] = (value if ewma is None
                             else ewma + self.alpha * (value - ewma))
        state["ewma_n"] = n + 1
        return breached, stats


class RatioBand(SloRule):
    """``value ÷ baseline`` outside ``[low, high]`` breaches. The
    baseline is PINNED at rule-construction time (a bench artifact, a
    design constant like 1.0 for a self-ratio such as
    ``pt_step_time_predicted_over_measured``) — the rule never learns,
    so it cannot normalize a slow drift away."""

    kind = "ratio_band"

    def __init__(self, name: str, metric: str, baseline: float,
                 low: float = 0.75, high: float = 1.25, **kw):
        super().__init__(name, metric, **kw)
        if baseline <= 0:
            raise ValueError(f"rule {name!r}: baseline must be positive")
        if not low < high:
            raise ValueError(f"rule {name!r}: need low < high")
        self.baseline = float(baseline)
        self.low = float(low)
        self.high = float(high)

    def check(self, value, state, now):
        if value is None:
            return False, {"skipped": "series missing"}
        ratio = value / self.baseline
        stats = {"value": value, "baseline": self.baseline,
                 "ratio": ratio, "low": self.low, "high": self.high}
        return (ratio < self.low or ratio > self.high), stats


class Staleness(SloRule):
    """Breaches when the series is ABSENT from the snapshot — or, with
    ``require_change=True``, present but bit-identical to the previous
    window (a counter that should be moving, a percentile gauge a dead
    publisher left behind). Combine with ``breach_for`` for the number
    of quiet windows tolerated."""

    kind = "staleness"

    def __init__(self, name: str, metric: str,
                 require_change: bool = False, **kw):
        super().__init__(name, metric, **kw)
        self.require_change = bool(require_change)

    def check(self, value, state, now):
        prev = state.get("prev")
        state["prev"] = value
        if value is None:
            return True, {"value": None, "reason": "series missing"}
        if self.require_change and prev is not None and value == prev:
            return True, {"value": value, "reason": "series frozen"}
        return False, {"value": value}


# ---------------------------------------------------------------------------
# default rule packs
# ---------------------------------------------------------------------------

def trainer_rules(goodput_floor: float = 0.5,
                  drift_band: Tuple[float, float] = (0.33, 3.0),
                  step_spike_ratio: float = 3.0,
                  exposed_comm_ceiling: float = 0.6,
                  breach_for: int = 3,
                  cooldown_s: float = 300.0) -> List[SloRule]:
    """The training-loop pack: watches the PR 4 goodput ledger and the
    PR 9 cost-model drift at the log boundaries ``Trainer.fit`` already
    crosses. Defaults are deliberately loose — a pack must be quiet on a
    healthy run and demand ``breach_for`` consecutive bad windows, not
    page on one noisy boundary."""
    return [
        Threshold(
            "goodput_floor", "pt_goodput_fraction", floor=goodput_floor,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="productive wall-time fraction collapsed: the "
                        "run is mostly compiling/checkpointing/replaying"),
        RatioBand(
            "step_time_predicted_drift",
            "pt_step_time_predicted_over_measured",
            labels={"component": "train"}, baseline=1.0,
            low=drift_band[0], high=drift_band[1],
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="cost-model drift: the roofline prediction and "
                        "the measured step time disagree past the band "
                        "— recalibrate OpCostDB before trusting a plan"),
        EwmaSpike(
            "step_time_spike", "pt_train_step_seconds",
            field="window_mean",
            spike_ratio=step_spike_ratio, alpha=0.3, warmup=3,
            severity="critical", breach_for=2, cooldown_s=cooldown_s,
            description="per-step wall time spiked vs its own EWMA: "
                        "input stall, thermal/contention event, or a "
                        "recompile storm"),
        RatioBand(
            "exposed_comm", "pt_exposed_comm_fraction",
            labels={"component": "train"}, baseline=1.0,
            low=0.0, high=exposed_comm_ceiling,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="exposed (un-overlapped) comm fraction over the "
                        "band: start->done windows collapsed — a flag "
                        "flip, libtpu downgrade, or a schedule "
                        "regression serialized the collective lane. The "
                        "gauge only exists on executables with async "
                        "windows, so sync-lowered (CPU) runs skip"),
    ]


def serving_rules(itl_p99_ceiling_s: float = 0.25,
                  ttft_p99_ceiling_s: float = 2.0,
                  prefix_hit_floor: float = 0.2,
                  spec_accept_floor: float = 0.2,
                  pool_dry_ceiling_per_window: float = 8.0,
                  breach_for: int = 3,
                  cooldown_s: float = 300.0) -> List[SloRule]:
    """The serving pack over the engine's published gauges. The hit-rate
    and accept-rate floors only engage on engines that publish those
    series (prefix_cache / spec_k enabled) — missing series skip."""
    return [
        Threshold(
            "itl_p99_ceiling", "pt_serving_itl_seconds",
            labels={"q": "p99"}, ceiling=itl_p99_ceiling_s,
            severity="critical", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="inter-token latency p99 over target: running "
                        "decodes are stalling behind prefills or "
                        "preemptions"),
        Threshold(
            "ttft_p99_ceiling", "pt_serving_ttft_seconds",
            labels={"q": "p99"}, ceiling=ttft_p99_ceiling_s,
            severity="critical", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="time-to-first-token p99 over target: admission "
                        "queue is backing up"),
        Threshold(
            "prefix_hit_rate_floor", "pt_serving_prefix_hit_rate",
            floor=prefix_hit_floor, severity="warning",
            breach_for=breach_for, cooldown_s=cooldown_s,
            description="radix-cache hit rate collapsed: workload "
                        "stopped sharing prefixes or the tree is being "
                        "evicted under pool pressure"),
        Threshold(
            "spec_accept_rate_floor", "pt_spec_accept_rate",
            floor=spec_accept_floor, severity="warning",
            breach_for=breach_for, cooldown_s=cooldown_s,
            description="speculative accept rate collapsed: the draft "
                        "provider no longer predicts this workload"),
        Threshold(
            "pool_dry_drain_rate", "pt_serving_pool_dry_drains_total",
            ceiling=pool_dry_ceiling_per_window, delta=True,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="KV pool running dry every window: capacity "
                        "pressure — shrink admission or grow num_pages"),
    ]


def fabric_rules(replicas: Optional[List[str]] = None,
                 ttft_p99_ceiling_s: float = 2.0,
                 itl_p99_ceiling_s: float = 0.25,
                 replica_itl_p99_ceiling_s: Optional[float] = None,
                 prefix_hit_floor: float = 0.2,
                 replicas_alive_floor: Optional[float] = None,
                 handoff_failures_per_window: float = 2.0,
                 breach_for: int = 3,
                 cooldown_s: float = 300.0) -> List[SloRule]:
    """The serving-fabric pack (ISSUE 12): AGGREGATE p99 TTFT/ITL
    ceilings at the router boundary, a replica-death floor on the
    heartbeat gauge, a per-window handoff-failure ceiling, and — when
    ``replicas`` names the pool — a per-replica prefix-hit-rate floor
    and ITL ceiling over the engine series' ``engine=<name>`` label
    sets. Per-replica rules skip while that replica publishes nothing
    (the serving pack's missing-series contract), so one pack serves
    any pool size.

    ``replicas_alive_floor`` defaults to ``len(replicas)`` when the
    pool is named (any death pages after ``breach_for`` windows) and
    stays off otherwise. ``replica_itl_p99_ceiling_s`` defaults to the
    aggregate ceiling."""
    rules: List[SloRule] = [
        Threshold(
            "fabric_ttft_p99_ceiling", "pt_fabric_ttft_seconds",
            labels={"q": "p99"}, ceiling=ttft_p99_ceiling_s,
            severity="critical", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="fabric-aggregate time-to-first-token p99 over "
                        "target: the global queue is backing up or "
                        "routing is concentrating load"),
        Threshold(
            "fabric_itl_p99_ceiling", "pt_fabric_itl_seconds",
            labels={"q": "p99"}, ceiling=itl_p99_ceiling_s,
            severity="critical", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="fabric-aggregate inter-token latency p99 over "
                        "target: decode replicas are stalling (cold "
                        "long prefills landing on them? disaggregate)"),
        Threshold(
            "fabric_handoff_failure_rate",
            "pt_fabric_handoff_failures_total",
            ceiling=handoff_failures_per_window, delta=True,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="prefill→decode handoffs failing every window: "
                        "transfers are corrupt, pools too small to "
                        "adopt, or a transport is flapping — requests "
                        "are falling back to cold serving"),
    ]
    if replicas:
        if replicas_alive_floor is None:
            replicas_alive_floor = float(len(replicas))
        per_itl = (replica_itl_p99_ceiling_s
                   if replica_itl_p99_ceiling_s is not None
                   else itl_p99_ceiling_s)
        for r in replicas:
            rules.append(Threshold(
                f"fabric_replica_{r}_prefix_hit_floor",
                "pt_serving_prefix_hit_rate",
                labels={"engine": r}, floor=prefix_hit_floor,
                severity="warning", breach_for=breach_for,
                cooldown_s=cooldown_s,
                description=f"replica {r}: radix hit rate collapsed — "
                            f"affinity routing stopped landing its "
                            f"prefix traffic here, or its tree is "
                            f"being evicted under pool pressure"))
            rules.append(Threshold(
                f"fabric_replica_{r}_itl_p99_ceiling",
                "pt_serving_itl_seconds",
                labels={"engine": r, "q": "p99"}, ceiling=per_itl,
                severity="critical", breach_for=breach_for,
                cooldown_s=cooldown_s,
                description=f"replica {r}: decode ITL p99 over its "
                            f"ceiling — the router's hysteresis should "
                            f"be spilling affinity traffic off it"))
    if replicas_alive_floor is not None:
        rules.append(Threshold(
            "fabric_replicas_alive_floor", "pt_fabric_replicas_alive",
            floor=replicas_alive_floor, severity="critical",
            breach_for=1, cooldown_s=cooldown_s,
            description="router lost contact with at least one "
                        "replica: failover re-admission is running, "
                        "capacity is reduced"))
    return rules


def frontdoor_rules(replicas: Optional[List[str]] = None,
                    ttft_p99_ceiling_s: float = 2.0,
                    shed_level_ceiling: float = 1.5,
                    deadline_misses_per_window: float = 5.0,
                    slow_disconnects_per_window: float = 3.0,
                    retries_per_window: float = 10.0,
                    breaker_trips_per_window: float = 0.0,
                    breach_for: int = 3,
                    cooldown_s: float = 300.0) -> List[SloRule]:
    """The front-door robustness pack (ISSUE 16), watching the edge the
    typed-refusal contract promises clients:

    * admitted-request p99 TTFT at the router boundary stays under
      ``ttft_p99_ceiling_s`` — the ceiling the load-test smoke leg
      asserts under 2x offered load WITH shedding (if this fires, the
      ladder is admitting more than the pool can serve on time);
    * the shed ladder living at BROWNOUT (level 2) for ``breach_for``
      windows — shedding is the mechanism, sustained brownout is the
      capacity signal;
    * deadline misses / slow-loris evictions / dedupe-resumed retries
      per window — each a typed, bounded event individually, a storm
      collectively (deadlines too tight, a stalled client fleet, or a
      flapping connection path);
    * per-replica breaker trips (when ``replicas`` names the pool):
      ANY trip pages — a replica that hung or died took a failover,
      capacity is reduced until its half-open probe readmits it.

    Missing series skip (same contract as every pack): a fabric without
    deadlines or a breaker stays quiet on those rules."""
    rules: List[SloRule] = [
        Threshold(
            "frontdoor_ttft_p99_ceiling", "pt_fabric_ttft_seconds",
            labels={"q": "p99"}, ceiling=ttft_p99_ceiling_s,
            severity="critical", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="admitted-request p99 TTFT over the front-door "
                        "ceiling: the shed ladder is admitting more "
                        "than the pool serves on time — raise shed "
                        "thresholds' aggression or grow the pool"),
        Threshold(
            "frontdoor_shed_brownout", "pt_frontdoor_shed_level",
            ceiling=shed_level_ceiling, severity="warning",
            breach_for=breach_for, cooldown_s=cooldown_s,
            description="the load-shedding ladder is living at "
                        "brownout: cold prefills deferred and spec_k "
                        "capped every window — this is a capacity "
                        "signal, not weather; add replicas"),
        Threshold(
            "frontdoor_slow_client_disconnects",
            "pt_frontdoor_disconnects_total",
            labels={"reason": "slow"},
            ceiling=slow_disconnects_per_window, delta=True,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="slow-loris evictions every window: a client "
                        "fleet stopped reading its streams (or the "
                        "outbox bound is too tight for their RTT)"),
        Threshold(
            "frontdoor_retry_rate", "pt_frontdoor_retries_total",
            ceiling=retries_per_window, delta=True,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="dedupe-resumed retries every window: clients "
                        "are reconnecting in bulk — a flapping network "
                        "path or a front door restarting under them"),
    ]
    for kind in ("ttft", "total"):
        rules.append(Threshold(
            f"frontdoor_deadline_miss_rate_{kind}",
            "pt_frontdoor_deadline_miss_total",
            labels={"kind": kind},
            ceiling=deadline_misses_per_window, delta=True,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description=f"{kind}-deadline cancellations every window: "
                        f"budgets too tight for current load, or "
                        f"capacity quietly shrank (check the breaker "
                        f"and replicas-alive rules)"))
    for r in (replicas or ()):
        rules.append(Threshold(
            f"frontdoor_breaker_{r}_trips",
            "pt_frontdoor_breaker_open_total",
            labels={"replica": r},
            ceiling=breaker_trips_per_window, delta=True,
            severity="critical", breach_for=1, cooldown_s=cooldown_s,
            description=f"replica {r}: circuit breaker opened (hung or "
                        f"crashed) — failover re-admission ran, "
                        f"capacity reduced until its half-open probe "
                        f"readmits it"))
    return rules


def elastic_rules(membership_changes_per_window: float = 2.0,
                  reshard_failures_per_window: float = 0.0,
                  world_size_floor: Optional[float] = None,
                  breach_for: int = 1,
                  cooldown_s: float = 300.0) -> List[SloRule]:
    """Alert pack for the elastic scale-in/out flow (ISSUE 15).

    A single membership change is the normal weather of preemptible
    pods — the flow exists to absorb it. What pages is the PATTERN:
    membership flapping faster than re-planning can converge (the run
    spends its life resharding, not training), or any resharded restore
    FAILING (the one mechanism that turns a lost host into a resumed
    run is broken — the next preemption is unrecoverable)."""
    rules: List[SloRule] = [
        Threshold(
            "elastic_membership_change_rate",
            "pt_elastic_membership_changes_total",
            ceiling=membership_changes_per_window, delta=True,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="world size flapping every window: the pod is "
                        "churning hosts faster than replan+reshard can "
                        "converge — training throughput is going to "
                        "replay and recompilation, not steps"),
        Threshold(
            "elastic_reshard_failures",
            "pt_elastic_reshard_failures_total",
            ceiling=reshard_failures_per_window, delta=True,
            severity="critical", breach_for=1,
            cooldown_s=cooldown_s,
            description="a resharded restore failed this window: the "
                        "checkpoint cannot be loaded on the surviving "
                        "mesh (infeasible axis or corrupt shard) — the "
                        "run is one preemption away from dead; pick a "
                        "feasible config or fall back to a committed "
                        "step that reshapes cleanly"),
    ]
    if world_size_floor is not None:
        rules.append(Threshold(
            "elastic_world_size_floor", "pt_elastic_world_size",
            floor=float(world_size_floor), severity="critical",
            breach_for=1, cooldown_s=cooldown_s,
            description="surviving world size fell below the minimum "
                        "the job can make progress on — scale the pod "
                        "back up or lower the floor deliberately"))
    return rules


def tracing_rules(queue_frac_ceiling: float = 0.5,
                  untracked_frac_ceiling: float = 0.1,
                  breach_for: int = 3,
                  cooldown_s: float = 300.0) -> List[SloRule]:
    """The distributed-tracing pack (ISSUE 19), breaching on
    ATTRIBUTION SHIFTS rather than totals: the tracer publishes
    ``pt_trace_ttft_frac{hop=...}`` gauges per completed trace, and a
    TTFT whose queue share climbs past the ceiling names the culprit
    (admission backlog) before the aggregate p99 ceiling even moves.
    The untracked ceiling is the instrumentation's own watchdog — a
    residual past it means a hop lost its spans (the ≥95% attribution
    contract the acceptance bound pins). Both series only exist while
    tracing is enabled, so the pack is silent otherwise (the
    missing-series skip contract)."""
    return [
        Threshold(
            "trace_ttft_frac_queue", "pt_trace_ttft_frac",
            labels={"hop": "queue"}, ceiling=queue_frac_ceiling,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="router-queue share of TTFT over the ceiling: "
                        "requests spend their first-token budget "
                        "waiting for dispatch — fair-admission backlog "
                        "or no replica capacity; the attached traces "
                        "name the hop"),
        Threshold(
            "trace_ttft_frac_untracked", "pt_trace_ttft_frac",
            labels={"hop": "untracked"},
            ceiling=untracked_frac_ceiling,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="untracked TTFT residual over the ceiling: a "
                        "latency-owning hop is missing its spans "
                        "(instrumentation regression) or a new hop "
                        "appeared between instrumented ones"),
    ]


def moe_rules(imbalance_ceiling: float = 2.0,
              aux_loss_floor: float = 0.5,
              router_z_spike_ratio: float = 3.0,
              exposed_comm_ceiling: float = 0.6,
              breach_for: int = 3,
              cooldown_s: float = 300.0) -> List[SloRule]:
    """The expert-parallel MoE pack (ISSUE 20), watching the routing
    health series ``publish_moe_metrics`` exports and the overlap gauge
    the a2a lane shares with every other collective:

    * ``pt_moe_load_imbalance`` is ``e × max expert share`` — exactly
      the bottleneck statistic the planner's entropy-priced a2a divides
      ep bandwidth by. Sustained past the ceiling means a hot expert is
      serializing dispatch AND the plan was priced for a balance the
      run no longer has — re-plan with the live histogram;
    * the aux-loss floor is the estimator's own watchdog: the GShard
      aux sits near 1.0 when balanced and RISES under skew, so a value
      collapsing toward 0 means the me/ce inputs got misaligned
      (a routing-pipeline regression), not a healthy router;
    * a router-z spike vs its own EWMA — router logits blowing up
      precedes routing collapse by many steps;
    * exposed-comm over the band while the MoE series are live: the
      dispatch/combine all-to-all stopped overlapping (a schedule or
      flag regression on the ep lane).

    Every series skips when missing (dense models, eval-only runs), so
    the pack composes with ``trainer_rules`` without double-paging."""
    return [
        Threshold(
            "moe_load_imbalance_ceiling", "pt_moe_load_imbalance",
            ceiling=imbalance_ceiling, severity="warning",
            breach_for=breach_for, cooldown_s=cooldown_s,
            description="e x max expert share over the ceiling: a hot "
                        "expert is the a2a bottleneck — the entropy "
                        "pricing divisor the plan assumed no longer "
                        "holds; re-plan with the live histogram or "
                        "raise the aux-loss weight"),
        Threshold(
            "moe_aux_loss_floor", "pt_moe_aux_loss",
            floor=aux_loss_floor, severity="warning",
            breach_for=breach_for, cooldown_s=cooldown_s,
            description="GShard aux loss collapsed toward 0: the "
                        "estimator's me/ce inputs are misaligned "
                        "(routing-pipeline regression) — balanced "
                        "routing reads ~1.0, never ~0"),
        EwmaSpike(
            "moe_router_z_spike", "pt_moe_router_z",
            spike_ratio=router_z_spike_ratio, alpha=0.3, warmup=3,
            severity="warning", breach_for=2, cooldown_s=cooldown_s,
            description="router z-loss spiked vs its own EWMA: gate "
                        "logits are blowing up — routing collapse "
                        "follows; check lr/init on the gate"),
        RatioBand(
            "moe_exposed_a2a", "pt_exposed_comm_fraction",
            labels={"component": "train"}, baseline=1.0,
            low=0.0, high=exposed_comm_ceiling,
            severity="warning", breach_for=breach_for,
            cooldown_s=cooldown_s,
            description="exposed comm over the band on an MoE run: the "
                        "dispatch/combine all-to-all stopped "
                        "overlapping with expert compute (flag flip or "
                        "schedule regression on the ep lane)"),
    ]


def default_rules() -> List[SloRule]:
    """trainer + serving packs at their defaults. Takes NO kwargs on
    purpose: callers wanting tuned thresholds compose
    ``trainer_rules(...) + serving_rules(...)`` directly — silently
    ignoring a misplaced threshold kwarg would watch the wrong SLO."""
    return trainer_rules() + serving_rules()
