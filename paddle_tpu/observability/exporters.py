"""Metric exporters: crash-safe JSONL time-series, Prometheus text
exposition (file snapshot + optional stdlib HTTP endpoint), console table.

All exporters consume the plain-dict snapshot from
``MetricsRegistry.collect()`` — they never reach into live metric state, so
an exporter crash can't corrupt the registry and the set of exporters is
trivially extensible.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Dict, List, Optional

__all__ = ["JSONLExporter", "PrometheusExporter", "ConsoleSummary",
           "render_prometheus", "parse_prometheus"]


# ---------------------------------------------------------------------------
# JSONL time-series
# ---------------------------------------------------------------------------

class JSONLExporter:
    """Append-only JSONL: each export appends one line per series with a
    shared timestamp. Crash-safe by construction — lines are written with
    a single ``write`` + flush, so a crash can at worst leave one torn
    final line, which a line-by-line reader skips (``load_jsonl``).

    Long runs rotate (ISSUE 10 satellite): with ``max_bytes`` set, an
    export that would push the live file past the cap first rotates it to
    ``<path>.1`` (shifting ``.1 -> .2`` … and dropping beyond
    ``keep_segments``), so a week-long serving job holds at most
    ``(keep_segments + 1) * max_bytes`` of telemetry on disk. One export
    is never split across segments — each segment stays independently
    parseable, and :meth:`load_rotated` reads them oldest-first."""

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 keep_segments: int = 3):
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError("max_bytes must be positive (None disables "
                             "rotation)")
        if keep_segments < 1:
            raise ValueError("keep_segments must be >= 1")
        self.path = path
        self.max_bytes = max_bytes
        self.keep_segments = int(keep_segments)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._closed = False
        self._lock = threading.Lock()

    def export(self, snapshot: List[dict]) -> int:
        ts = round(time.time(), 3)
        lines = []
        for entry in snapshot:
            rec = dict(entry)
            rec["ts"] = ts
            lines.append(json.dumps(rec, sort_keys=True))
        blob = "".join(ln + "\n" for ln in lines)
        with self._lock:
            if self._closed:
                # close() is final — enable()'s replace-and-close relies
                # on a replaced exporter never appending again
                raise ValueError("export() on a closed JSONLExporter")
            if self._f is None or self._f.closed:
                # a failed rotation reopen must not brick the exporter
                # forever — retry the open on the next export
                self._f = open(self.path, "a", encoding="utf-8")
            if (self.max_bytes is not None and self._f.tell() > 0
                    and self._f.tell() + len(blob.encode("utf-8"))
                    > self.max_bytes):
                self._rotate_locked()
            self._f.write(blob)
            self._f.flush()
        return len(lines)

    def _rotate_locked(self) -> None:
        """Shift the segment chain by one: live -> .1, .k -> .k+1,
        .keep_segments dropped. The live file reopens empty; a crash
        mid-rotation at worst loses the oldest (dropped-anyway) segment
        — the newest data always survives because the live file is only
        renamed, never rewritten. A filesystem that accepts appends but
        refuses renames disables rotation after ONE failed attempt
        (warned): re-shifting the chain on every export would delete
        every kept segment while the live file grew anyway."""
        try:
            self._f.close()
        except Exception:
            pass
        self._f = None
        try:
            # drop the end of the chain AND any segments beyond it — a
            # previous run with a larger keep_segments leaves .k files
            # this run's shift would otherwise never touch, silently
            # breaking the (keep_segments + 1) * max_bytes disk bound
            for k in self._segment_numbers(self.path):
                if k >= self.keep_segments:
                    os.remove(f"{self.path}.{k}")
            for k in range(self.keep_segments - 1, 0, -1):
                src = f"{self.path}.{k}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{k + 1}")
            os.replace(self.path, f"{self.path}.1")
        except OSError as e:
            warnings.warn(f"JSONLExporter: segment rotation of "
                          f"{self.path} failed ({e}); rotation disabled "
                          f"for this exporter", RuntimeWarning)
            self.max_bytes = None
        finally:
            self._f = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            try:
                if self._f is not None:
                    self._f.close()
            except Exception:
                pass

    @staticmethod
    def _segment_numbers(path: str) -> List[int]:
        """Numeric suffixes of ``<path>.N`` segments on disk, ascending
        — the ONE definition of what belongs to the rotation chain."""
        ks = []
        d = os.path.dirname(os.path.abspath(path)) or "."
        base = os.path.basename(path)
        for name in os.listdir(d):
            if name.startswith(base + "."):
                suffix = name[len(base) + 1:]
                if suffix.isdigit():
                    ks.append(int(suffix))
        return sorted(ks)

    @staticmethod
    def load_jsonl(path: str) -> List[dict]:
        """Parse line-by-line, skipping a torn final line (the crash-safety
        contract)."""
        out = []
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    # only the LAST line may be torn; anything else is
                    # corruption the caller must see
                    rest = f.read().strip()
                    if rest:
                        raise
        return out

    @staticmethod
    def load_rotated(path: str) -> List[dict]:
        """Load the full rotated series oldest-first: ``<path>.N`` …
        ``<path>.1`` then the live file, each through the torn-tail-
        tolerant per-file parser (a rotated segment was closed cleanly,
        but a crash can still tear its final line — same tolerance
        applies)."""
        out: List[dict] = []
        for k in reversed(JSONLExporter._segment_numbers(path)):
            out.extend(JSONLExporter.load_jsonl(f"{path}.{k}"))
        if os.path.exists(path):
            out.extend(JSONLExporter.load_jsonl(path))
        return out


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _prom_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def render_prometheus(snapshot: List[dict]) -> str:
    """Render a collect() snapshot in Prometheus text exposition format
    (one # TYPE header per metric, histogram as _bucket/_sum/_count).

    Empty histograms are NOT special-cased here: ``collect()`` emits a
    zeroed series (all ``_bucket`` counts 0, ``_count`` 0) for every
    registered histogram with no observations, so the exposition carries
    a stable series set from the first scrape — this renderer just prints
    whatever bucket rows the snapshot holds."""
    by_name: Dict[str, List[dict]] = {}
    for e in snapshot:
        by_name.setdefault(e["name"], []).append(e)
    lines: List[str] = []
    for name in sorted(by_name):
        entries = by_name[name]
        lines.append(f"# TYPE {name} {entries[0]['type']}")
        for e in entries:
            if e["type"] == "histogram":
                for le, cum in e["buckets"]:
                    lb = dict(e["labels"])
                    lb["le"] = str(le)
                    lines.append(f"{name}_bucket{_prom_labels(lb)} {cum}")
                lines.append(
                    f"{name}_sum{_prom_labels(e['labels'])} {e['sum']}")
                lines.append(
                    f"{name}_count{_prom_labels(e['labels'])} {e['count']}")
            else:
                lines.append(
                    f"{name}{_prom_labels(e['labels'])} {e['value']}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> Dict[str, Dict[tuple, float]]:
    """Minimal text-format parser (the round-trip validator the smoke
    test uses): {metric_name: {sorted-label-tuple: value}}. Handles the
    subset render_prometheus emits — enough to prove the exposition is
    well-formed, not a general scraper."""
    out: Dict[str, Dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        body, _, val = line.rpartition(" ")
        if not body:
            raise ValueError(f"unparseable sample line: {line!r}")
        labels: Dict[str, str] = {}
        if body.endswith("}"):
            name, _, rest = body.partition("{")
            for item in _split_label_items(rest[:-1]):
                k, _, v = item.partition("=")
                if not (v.startswith('"') and v.endswith('"')):
                    raise ValueError(f"bad label value in: {line!r}")
                labels[k] = v[1:-1].replace('\\"', '"').replace("\\\\", "\\")
        else:
            name = body
        out.setdefault(name, {})[tuple(sorted(labels.items()))] = float(val)
    return out


def _split_label_items(s: str) -> List[str]:
    """Split `a="x",b="y,z"` on commas outside quotes."""
    items, cur, in_q, esc = [], [], False, False
    for ch in s:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\":
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        items.append("".join(cur))
    return [i for i in items if i]


class _PromHandler:
    """Lazily-built BaseHTTPRequestHandler subclass bound to an exporter."""

    @staticmethod
    def build(exporter: "PrometheusExporter"):
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 (stdlib API name)
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = exporter.latest_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr spam
                pass

        return Handler


class PrometheusExporter:
    """Text-format exposition. ``path`` writes an atomic snapshot file per
    export (node-exporter textfile-collector style); ``http_port`` serves
    the latest snapshot at ``/metrics`` from a stdlib ThreadingHTTPServer
    daemon thread (port 0 = ephemeral; see ``.port`` after start)."""

    def __init__(self, path: Optional[str] = None,
                 http_port: Optional[int] = None):
        self.path = path
        self._text = "# no export yet\n"
        self._lock = threading.Lock()
        self._server = None
        self._thread = None
        self.port = None
        if http_port is not None:
            self._start_http(http_port)

    def latest_text(self) -> str:
        with self._lock:
            return self._text

    def export(self, snapshot: List[dict]) -> str:
        text = render_prometheus(snapshot)
        with self._lock:
            self._text = text
        if self.path:
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(text)
            os.replace(tmp, self.path)
        return text

    def _start_http(self, port: int) -> None:
        from http.server import ThreadingHTTPServer

        self._server = ThreadingHTTPServer(
            ("127.0.0.1", int(port)), _PromHandler.build(self))
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="pt-prom-http", daemon=True)
        self._thread.start()

    def close(self) -> None:
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except Exception:
                pass
            self._server = None


# ---------------------------------------------------------------------------
# console summary
# ---------------------------------------------------------------------------

class ConsoleSummary:
    """Human-readable table of the snapshot (the `p.summary()` of the
    metrics plane). ``export`` returns the string; ``echo=True`` also
    prints it."""

    def __init__(self, echo: bool = False):
        self.echo = echo

    def export(self, snapshot: List[dict]) -> str:
        lines = [f"{'Metric':<44} {'Labels':<28} {'Value':>14}"]
        for e in sorted(snapshot, key=lambda e: (e["name"],
                                                 sorted(e["labels"].items()))):
            lb = ",".join(f"{k}={v}" for k, v in sorted(e["labels"].items()))
            if e["type"] == "histogram":
                val = (f"n={e['count']} p50={e.get('p50', float('nan')):.4g}"
                       f" p99={e.get('p99', float('nan')):.4g}")
                lines.append(f"{e['name']:<44} {lb[:28]:<28} {val:>14}")
            else:
                v = e["value"]
                sval = f"{v:.6g}" if isinstance(v, float) else str(v)
                lines.append(f"{e['name']:<44} {lb[:28]:<28} {sval:>14}")
        out = "\n".join(lines)
        if self.echo:
            print(out, flush=True)
        return out
